"""User-task manager: async REST operation tracking.

Reference CC/servlet/UserTaskManager.java:56-834 — every async request gets
a UUID (returned in the `User-Task-ID` response header); repeated requests
with the same task id (or same client + URL) attach to the in-flight
operation instead of starting a new one; completed tasks are retained for a
configurable time and listed by the USER_TASKS endpoint.
"""
from __future__ import annotations

import dataclasses
import enum
import threading
import time as _time
import uuid as _uuid
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

from cruise_control_tpu.sched import runtime as sched_runtime

USER_TASK_ID_HEADER = "User-Task-ID"

#: endpoint -> task category (reference CruiseControlEndPoint.java:17-36
#: EndpointType: {KAFKA, CRUISE_CONTROL} x {ADMIN, MONITOR}); drives the
#: per-category completed-task retention/caps of UserTaskManagerConfig
ENDPOINT_CATEGORY: Dict[str, str] = {
    "BOOTSTRAP": "cruise.control.admin",
    "TRAIN": "cruise.control.admin",
    "LOAD": "kafka.monitor",
    "PARTITION_LOAD": "kafka.monitor",
    "PROPOSALS": "kafka.monitor",
    "STATE": "cruise.control.monitor",
    "ADD_BROKER": "kafka.admin",
    "REMOVE_BROKER": "kafka.admin",
    "FIX_OFFLINE_REPLICAS": "kafka.admin",
    "REBALANCE": "kafka.admin",
    "STOP_PROPOSAL_EXECUTION": "kafka.admin",
    "PAUSE_SAMPLING": "cruise.control.admin",
    "RESUME_SAMPLING": "cruise.control.admin",
    "KAFKA_CLUSTER_STATE": "kafka.monitor",
    "DEMOTE_BROKER": "kafka.admin",
    "USER_TASKS": "cruise.control.monitor",
    "REVIEW_BOARD": "cruise.control.monitor",
    "ADMIN": "cruise.control.admin",
    "REVIEW": "cruise.control.admin",
    "TOPIC_CONFIGURATION": "kafka.admin",
    "SCENARIOS": "kafka.monitor",
}


def body_fingerprint(body) -> str:
    """Stable short hash of a request body ("" for no body).  Dedup of
    async tasks keys on (client, endpoint+query, BODY): two scenario
    batches submitted with identical query strings but different JSON
    bodies are different operations and must not coalesce."""
    if body is None or body == "" or body == b"":
        return ""
    if isinstance(body, str):
        body = body.encode("utf-8", errors="replace")
    import hashlib
    return hashlib.sha256(body).hexdigest()[:16]


class TaskStatus(enum.Enum):
    ACTIVE = "Active"
    COMPLETED = "Completed"
    COMPLETED_WITH_ERROR = "CompletedWithError"


@dataclasses.dataclass
class UserTaskInfo:
    task_id: str
    endpoint: str
    query: str
    client_id: str
    start_ms: float
    future: Future
    status: TaskStatus = TaskStatus.ACTIVE
    end_ms: float = 0.0
    #: hash of the POST body this task was started with (dedup scope)
    body_hash: str = ""
    #: approximate JSON size of the completed result — large scenario
    #: reports are visible in USER_TASKS without fetching them
    result_bytes: Optional[int] = None
    #: scheduler ticket of the task's most recent solve submission
    #: (sched/queue.SolveTicket): surfaces WHY a task is waiting —
    #: class, queue position, estimated start
    sched_ticket: Optional[object] = None
    #: flight-recorder trace id of the operation (obs/trace.py): the
    #: same id the solve response body carries as `traceId`, so a
    #: USER_TASKS listing links straight into TRACES
    trace_id: str = ""
    #: which solver produced the completed result (portfolio/): the
    #: response body's solverProvenance block, lifted so a USER_TASKS
    #: listing shows portfolio wins without fetching each result
    solver_provenance: Optional[dict] = None

    def to_json(self) -> dict:
        out = {
            "UserTaskId": self.task_id,
            "RequestURL": f"{self.endpoint}?{self.query}" if self.query
                          else self.endpoint,
            "ClientIdentity": self.client_id,
            "StartMs": self.start_ms,
            "Status": self.status.value,
        }
        if self.trace_id:
            out["TraceId"] = self.trace_id
        if self.solver_provenance is not None:
            out["SolverProvenance"] = dict(self.solver_provenance)
        if self.body_hash:
            out["RequestBodySha"] = self.body_hash
        if self.result_bytes is not None:
            out["ResultSizeBytes"] = self.result_bytes
        ticket = self.sched_ticket
        if (ticket is not None and self.status == TaskStatus.ACTIVE
                and not ticket.done()):
            # device-time scheduler visibility: the class this task's
            # solve dispatches at (coalesced solves report the BEST
            # attached waiter's class), its 1-BASED place in the dispatch
            # order
            # (0 = on the device RIGHT NOW, never a queued state), and
            # the start estimate (actual start once dispatched,
            # queue-depth x latency-EWMA before).  Dropped once the
            # solve RESOLVES: a task still ACTIVE through a long
            # execution phase is no longer on (or waiting for) the
            # device, and reporting QueuePosition=0 for it would read
            # as a solve occupying the device
            out["SchedulerClass"] = ticket.klass.name
            position = ticket.queue_position()
            out["QueuePosition"] = 0 if position is None else position + 1
            out["EstimatedStartMs"] = round(ticket.estimated_start_ms(), 1)
        return out


class UserTaskManager:
    """Thread-safe registry of async operations."""

    def __init__(self, max_active_tasks: int = 25,
                 completed_retention_s: float = 24 * 3600.0,
                 max_cached_completed_tasks: Optional[int] = None,
                 attach_max_age_s: Optional[float] = None,
                 max_workers: int = 8,
                 category_retention_s: Optional[Dict[str, float]] = None,
                 category_max_cached: Optional[Dict[str, int]] = None,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self._max_active = max_active_tasks
        self._retention_s = completed_retention_s
        #: completed-task cache cap (reference
        #: max.cached.completed.user.tasks): oldest evicted beyond this
        self._max_cached_completed = max_cached_completed_tasks
        #: per-category overrides (reference UserTaskManagerConfig
        #: completed.{kafka,cruise.control}.{admin,monitor}.* keys; the
        #: category of a task comes from ENDPOINT_CATEGORY)
        self._category_retention_s = category_retention_s or {}
        self._category_max_cached = category_max_cached or {}
        #: implicit same-client+URL resumption window (reference
        #: webserver.session.maxExpiryTimeMs session binding expiry)
        self._attach_max_age_s = attach_max_age_s
        self._time = time_fn or _time.time
        self._lock = threading.Lock()
        self._tasks: Dict[str, UserTaskInfo] = {}
        #: (client_id, endpoint+query, body hash) -> task id, for
        #: implicit resumption (body_fingerprint("")="" for body-less
        #: requests)
        self._by_request: Dict[Tuple[str, str, str], str] = {}
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix="user-task")

    # ------------------------------------------------------------------
    def get_or_create(self, endpoint: str, query: str, client_id: str,
                      operation: Callable[[], Any],
                      task_id: Optional[str] = None,
                      body: Optional[str] = None,
                      trace_id: str = "") -> UserTaskInfo:
        """Attach to an existing task (by explicit id or same
        client+URL+body) or start `operation` on the pool.

        `body` is the raw POST body (endpoints like SCENARIOS carry
        their payload there): its hash joins the implicit dedup key so
        two different bodies behind identical query strings never
        coalesce into one task.  `trace_id` is the flight-recorder
        trace of the operation (used only when a NEW task starts;
        attaching re-polls report the original task's trace)."""
        now_ms = self._time() * 1000.0
        body_hash = body_fingerprint(body)
        key = (client_id, f"{endpoint}?{query}", body_hash)
        with self._lock:
            self._expire(now_ms)
            if task_id is not None:
                info = self._tasks.get(task_id)
                if info is None:
                    raise KeyError(f"unknown user task {task_id}")
                # compare PARSED params: clients may re-order or re-encode
                # the same query between polls
                import urllib.parse
                same = (info.endpoint == endpoint
                        and sorted(urllib.parse.parse_qsl(
                            info.query, keep_blank_values=True))
                        == sorted(urllib.parse.parse_qsl(
                            query, keep_blank_values=True)))
                if not same:
                    # a stale/reused header must not attach to a different
                    # operation (reference UserTaskManager scopes task ids
                    # to their request)
                    raise ValueError(
                        f"user task {task_id} belongs to "
                        f"{info.endpoint}?{info.query}, not "
                        f"{endpoint}?{query}")
                if body_hash and body_hash != info.body_hash:
                    # re-polls may omit the body (header-only long-poll);
                    # a DIFFERENT body under a reused header may not
                    # attach to the old operation
                    raise ValueError(
                        f"user task {task_id} was started with a "
                        f"different request body")
                return info
            existing = self._by_request.get(key)
            if existing is not None:
                info = self._tasks.get(existing)
                if info is not None and info.status == TaskStatus.ACTIVE:
                    return info
            active = sum(1 for t in self._tasks.values()
                         if t.status == TaskStatus.ACTIVE)
            if active >= self._max_active:
                raise RuntimeError(
                    f"too many active user tasks ({active}); retry later")
            new_id = str(_uuid.uuid4())

            def run() -> Any:
                # every scheduler submission the operation makes on this
                # worker thread lands back on the task, so USER_TASKS can
                # report QueuePosition/SchedulerClass/EstimatedStartMs
                sched_runtime.set_submission_listener(
                    lambda ticket: self._attach_ticket(new_id, ticket))
                try:
                    result = operation()
                    self._finish(new_id, TaskStatus.COMPLETED, result)
                    return result
                except BaseException:
                    self._finish(new_id, TaskStatus.COMPLETED_WITH_ERROR)
                    raise
                finally:
                    sched_runtime.clear_submission_listener()

            # submit while still holding the lock: the task must never be
            # visible with future=None (a concurrent identical request
            # attaches to it immediately)
            info = UserTaskInfo(new_id, endpoint, query, client_id, now_ms,
                                future=self._pool.submit(run),
                                body_hash=body_hash, trace_id=trace_id)
            self._tasks[new_id] = info
            self._by_request[key] = new_id
        return info

    @staticmethod
    def _result_size_bytes(result) -> Optional[int]:
        import json
        try:
            return len(json.dumps(result, default=str))
        except (TypeError, ValueError, RecursionError) as exc:
            # size is a courtesy note; an unserializable result is the
            # response layer's problem, not the task registry's
            import logging
            logging.getLogger(__name__).debug(
                "result size estimation failed: %s", exc)
            return None

    def _attach_ticket(self, task_id: str, ticket: object) -> None:
        with self._lock:
            info = self._tasks.get(task_id)
            if info is not None:
                info.sched_ticket = ticket

    def _finish(self, task_id: str, status: TaskStatus,
                result: Any = None) -> None:
        size = (self._result_size_bytes(result)
                if status is TaskStatus.COMPLETED else None)
        provenance = (result.get("solverProvenance")
                      if isinstance(result, dict) else None)
        with self._lock:
            info = self._tasks.get(task_id)
            if info is not None:
                info.status = status
                info.end_ms = self._time() * 1000.0
                info.result_bytes = size
                if provenance is not None:
                    info.solver_provenance = provenance

    def _retention_for(self, endpoint: str) -> float:
        cat = ENDPOINT_CATEGORY.get(endpoint)
        return self._category_retention_s.get(cat, self._retention_s)

    def _expire(self, now_ms: float) -> None:
        dead = [tid for tid, t in self._tasks.items()
                if t.status != TaskStatus.ACTIVE
                and t.end_ms < now_ms
                - self._retention_for(t.endpoint) * 1000.0]
        for tid in dead:
            info = self._tasks.pop(tid)
            self._by_request.pop(
                (info.client_id, f"{info.endpoint}?{info.query}",
                 info.body_hash), None)

        def evict_oldest_beyond(tasks, cap):
            done = sorted(tasks, key=lambda t: t.end_ms)
            for info in done[:max(0, len(done) - cap)]:
                self._tasks.pop(info.task_id, None)
                key = (info.client_id, f"{info.endpoint}?{info.query}",
                       info.body_hash)
                # only sever the binding if it still points at THIS task —
                # a newer ACTIVE task may have re-bound the same key
                if self._by_request.get(key) == info.task_id:
                    self._by_request.pop(key, None)

        for cat, cap in self._category_max_cached.items():
            evict_oldest_beyond(
                [t for t in self._tasks.values()
                 if t.status != TaskStatus.ACTIVE
                 and ENDPOINT_CATEGORY.get(t.endpoint) == cat], cap)
        if self._max_cached_completed is not None:
            evict_oldest_beyond([t for t in self._tasks.values()
                                 if t.status != TaskStatus.ACTIVE],
                                self._max_cached_completed)
        if self._attach_max_age_s is not None:
            attach_cutoff = now_ms - self._attach_max_age_s * 1000.0
            for key, tid in list(self._by_request.items()):
                info = self._tasks.get(tid)
                # ACTIVE tasks keep their binding — the implicit
                # same-client+URL resume flow must survive solves longer
                # than the session expiry
                if info is None or (info.status != TaskStatus.ACTIVE
                                    and info.start_ms < attach_cutoff):
                    self._by_request.pop(key, None)

    # ------------------------------------------------------------------
    def get(self, task_id: str) -> Optional[UserTaskInfo]:
        with self._lock:
            return self._tasks.get(task_id)

    def all_tasks(self) -> List[UserTaskInfo]:
        with self._lock:
            self._expire(self._time() * 1000.0)
            return sorted(self._tasks.values(), key=lambda t: -t.start_ms)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)
