"""REST API server.

Reference CC/servlet/KafkaCruiseControlServlet.java:39-232 +
KafkaCruiseControlApp.java (Jetty wiring): 19 endpoints under
`/kafkacruisecontrol/...`, async POSTs tracked by the UserTaskManager with
`User-Task-ID` headers, optional two-step verification through the
purgatory, pluggable security.

The dispatch core (`handle_request`) is transport-free — the stdlib
ThreadingHTTPServer wrapper feeds it, and tests drive it directly.
"""
from __future__ import annotations

import json
import logging
import ssl
import threading
import time as _time
import urllib.parse
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Mapping, Optional, Tuple

from cruise_control_tpu.api import responses as R
from cruise_control_tpu.api.parameters import (GET_ENDPOINTS, POST_ENDPOINTS,
                                               VALID_PARAMS, ParameterError,
                                               QueryParams)
from cruise_control_tpu.api.purgatory import Purgatory
from cruise_control_tpu.api.security import (AuthenticationError,
                                             AuthorizationError,
                                             NoSecurityProvider,
                                             SecurityProvider)
from cruise_control_tpu.api.user_tasks import (USER_TASK_ID_HEADER,
                                               UserTaskManager)
from cruise_control_tpu.analyzer.context import OptimizationOptions
from cruise_control_tpu.core.anomaly import AnomalyType
from cruise_control_tpu.executor.strategy import strategy_from_names
from cruise_control_tpu.facade import CruiseControl, OngoingExecutionError
from cruise_control_tpu.obs import export as obs_export
from cruise_control_tpu.obs import recorder as obs_recorder
from cruise_control_tpu.obs import trace as obs_trace
from cruise_control_tpu.sched.queue import QueueFullError

LOG = logging.getLogger(__name__)
#: NCSA-style access log, one line per HTTP request (reference
#: KafkaCruiseControlApp NCSA access log)
ACCESS_LOG = logging.getLogger("accessLogger")
_NCSA_MONTHS = ("Jan", "Feb", "Mar", "Apr", "May", "Jun",
                "Jul", "Aug", "Sep", "Oct", "Nov", "Dec")

BASE_PATH = "/kafkacruisecontrol"

#: endpoints answered synchronously (no user task)
SYNC_ENDPOINTS = {"STATE", "KAFKA_CLUSTER_STATE", "USER_TASKS",
                  "REVIEW_BOARD", "REVIEW", "STOP_PROPOSAL_EXECUTION",
                  "PAUSE_SAMPLING", "RESUME_SAMPLING", "ADMIN", "FLEET",
                  "TRACES"}

#: the Prometheus scrape path, served OUTSIDE the API prefix (scrapers
#: conventionally hit bare /metrics); still behind authentication
METRICS_PATH = "/metrics"


class HttpError(Exception):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(message)
        self.status = status
        self.message = message


def make_server_ssl_context(certfile: str, keyfile: Optional[str] = None,
                            key_password: Optional[str] = None,
                            protocol: str = "TLS") -> ssl.SSLContext:
    """TLS context from PEM files (config keys `webserver.ssl.*`;
    reference KafkaCruiseControlApp SSL connector).  `certfile` may hold
    both certificate and key; pass `keyfile` when they are separate.
    `protocol` (webserver.ssl.protocol) floors the negotiated version:
    "TLS" (library default), "TLSv1.2" or "TLSv1.3"."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    floor = {"TLS": None, "TLSV1.2": ssl.TLSVersion.TLSv1_2,
             "TLSV1.3": ssl.TLSVersion.TLSv1_3}.get((protocol or
                                                     "TLS").upper())
    if floor is None and (protocol or "TLS").upper() != "TLS":
        raise ValueError(f"unsupported webserver.ssl.protocol "
                         f"{protocol!r}; use TLS, TLSv1.2 or TLSv1.3")
    if floor is not None:
        ctx.minimum_version = floor
    ctx.load_cert_chain(certfile, keyfile=keyfile or None,
                        password=key_password or None)
    return ctx


class CruiseControlApp:
    """Endpoint dispatch over a CruiseControl facade."""

    def __init__(self, cruise_control: CruiseControl,
                 security: Optional[SecurityProvider] = None,
                 two_step_verification: bool = False,
                 async_response_timeout_s: float = 1.0,
                 access_log: bool = True,
                 purgatory_kwargs: Optional[dict] = None,
                 user_task_kwargs: Optional[dict] = None,
                 cors_enabled: bool = False,
                 cors_origin: str = "*",
                 cors_allow_methods: str = "OPTIONS, GET, POST",
                 cors_expose_headers: str = USER_TASK_ID_HEADER,
                 url_prefix: Optional[str] = None,
                 endpoint_classes: Optional[dict] = None,
                 request_reason_required: bool = False,
                 session_path: str = "/",
                 ui_diskpath: str = "",
                 ui_urlprefix: str = "/ui",
                 time_fn: Optional[Callable[[], float]] = None,
                 fleet=None,
                 metrics_endpoint_enabled: bool = True) -> None:
        self.cc = cruise_control
        #: fleet registry (fleet/registry.FleetRegistry) when this
        #: process serves multiple clusters: `?cluster=<id>` selects the
        #: tenant on every endpoint (404 unknown, 503 draining, default
        #: tenant when omitted) and the FLEET endpoint lists them.  None
        #: = the single-tenant path, byte-identical to pre-fleet
        self.fleet = fleet
        self.security = security or NoSecurityProvider()
        #: per-endpoint (request class, parameters class) overrides
        #: (reference CruiseControlRequestConfig /
        #: CruiseControlParametersConfig; see api.request_registry)
        self._endpoint_classes = endpoint_classes or {}
        #: POSTs must carry a non-empty `reason` parameter (reference
        #: WebServerConfig `request.reason.required`)
        self._reason_required = request_reason_required
        #: cookie path for async-session tracking (reference
        #: `webserver.session.path`)
        self.session_path = session_path or "/"
        #: static UI serving (reference `webserver.ui.diskpath` /
        #: `webserver.ui.urlprefix`)
        self._ui_diskpath = ui_diskpath
        self._ui_urlprefix = (ui_urlprefix or "/ui").rstrip("/") or "/ui"
        self.purgatory = Purgatory(time_fn=time_fn,
                                   **(purgatory_kwargs or {})) \
            if two_step_verification else None
        self.user_tasks = UserTaskManager(time_fn=time_fn,
                                          **(user_task_kwargs or {}))
        self._async_timeout = async_response_timeout_s
        self._access_log = access_log
        #: CORS (reference webserver.http.cors.*): when enabled, every
        #: response carries the allow-origin header
        self._cors_headers = ({"Access-Control-Allow-Origin": cors_origin,
                               "Access-Control-Allow-Methods":
                               cors_allow_methods,
                               "Access-Control-Expose-Headers":
                               cors_expose_headers,
                               "Access-Control-Allow-Headers":
                               "Content-Type, Authorization, User-Task-ID"}
                              if cors_enabled else {})
        #: mount point (reference webserver.api.urlprefix)
        self.base_path = (url_prefix.rstrip("/") if url_prefix
                          else BASE_PATH)
        #: serve the OpenMetrics scrape page at /metrics
        #: (obs.metrics.endpoint.enabled)
        self._metrics_endpoint_enabled = metrics_endpoint_enabled
        #: graceful-drain state (main.py SIGTERM handler): Retry-After
        #: seconds while draining, None while serving normally.  Writes
        #: answer 503 + Retry-After (clients back off exactly like on a
        #: 429); reads keep working so operators can watch the drain.
        self._draining: Optional[float] = None
        self._http: Optional[ThreadingHTTPServer] = None

    # ------------------------------------------------------------------
    # graceful drain (SIGTERM path)
    # ------------------------------------------------------------------
    def drain(self, retry_after_s: float = 30.0) -> None:
        """Stop admitting WRITES: every POST answers 503 + Retry-After
        (the same backpressure contract as the scheduler's 429 — the
        client honors the hint and retries against the replacement
        process).  Reads stay up so STATE/TRACES remain queryable
        while the in-flight solve finishes."""
        self._draining = max(1.0, float(retry_after_s))

    @property
    def draining(self) -> bool:
        return self._draining is not None

    # ------------------------------------------------------------------
    # transport-free dispatch
    # ------------------------------------------------------------------
    def handle_request(self, method: str, path: str, query_string: str = "",
                       headers: Optional[Mapping[str, str]] = None,
                       client: str = "local",
                       body: Optional[str] = None
                       ) -> Tuple[int, Dict[str, str], dict]:
        """(status, response headers, json body).  `body` is the raw
        request body (SCENARIOS carries its spec list there); it joins
        the user-task dedup key for async POSTs."""
        headers = dict(headers or {})
        # peer address as a pseudo-header for providers that filter on it
        # (trusted.proxy.services.ip.regex) — OVERWRITE unconditionally: a
        # client-supplied value must never reach the address filter
        headers["X-Remote-Addr"] = client
        if (method == "GET" and path == METRICS_PATH
                and self._metrics_endpoint_enabled):
            # OpenMetrics scrape: every sensor registry as one page,
            # fleet tenants labeled cluster="<id>" (obs/export.py).
            # Authenticated like everything else — sensor names leak
            # topology
            try:
                self.security.authenticate(headers)
            except AuthenticationError as exc:
                status, hdrs, err = self._error(401, exc)
                return status, {**hdrs,
                                **self.security.auth_challenge_headers()}, \
                    err
            text = obs_export.render_for(self.cc, fleet=self.fleet)
            return 200, {}, {"__raw__": text.encode("utf-8"),
                             "__content_type__": obs_export.CONTENT_TYPE}
        if (method == "GET" and self._ui_diskpath
                and (path == self._ui_urlprefix
                     or path.startswith(self._ui_urlprefix + "/"))):
            # static UI sits behind authentication like every endpoint
            # (reference: Jetty's security handler fronts the whole server)
            try:
                self.security.authenticate(headers)
            except AuthenticationError as exc:
                status, hdrs, body = self._error(401, exc)
                return status, {**hdrs,
                                **self.security.auth_challenge_headers()}, \
                    body
            return self._serve_ui(path)
        try:
            endpoint = self._endpoint_of(method, path)
            principal = self.security.authenticate(headers)
            self.security.authorize(principal, endpoint)
            if self._draining is not None and (
                    endpoint in POST_ENDPOINTS or endpoint == "REVIEW"):
                # graceful drain: no new mutations once shutdown began
                # — clients treat the 503 + Retry-After like a 429 and
                # resubmit to the replacement process.  REVIEW is a
                # write too (the authz layer's definition): approving a
                # purgatory request mutates state the exit would lose
                import math
                retry_after = max(1, int(math.ceil(self._draining)))
                return 503, {"Retry-After": str(retry_after)}, {
                    "errorMessage": "ServerDraining: shutting down; "
                                    "retry against the replacement "
                                    "process",
                    "retryAfterSeconds": retry_after, "version": 1}
            req_cls, par_cls = self._endpoint_classes.get(
                endpoint, (None, QueryParams))
            params = par_cls(
                endpoint, urllib.parse.parse_qs(query_string,
                                                keep_blank_values=True))
            # tenant resolution (fleet/): 404 unknown, 503 draining —
            # resolved BEFORE metering so the per-endpoint request
            # sensors land in the addressed tenant's registry
            cc = self._cc_for(params,
                              for_write=endpoint in POST_ENDPOINTS)
            # per-endpoint request sensors (reference servlet meters/timers,
            # KafkaCruiseControlServlet.java:60-65)
            registry = getattr(cc, "metrics", None)
            if registry is not None:
                registry.meter(f"{endpoint}-request-rate").mark()
            if (self._reason_required and endpoint in POST_ENDPOINTS
                    and "reason" in VALID_PARAMS[endpoint]
                    and not params.get("reason")):
                raise ParameterError(
                    f"{endpoint} requires a reason parameter "
                    f"(request.reason.required=true)")
            request = req_cls(endpoint) if req_cls is not None else None
            if endpoint in SYNC_ENDPOINTS:
                if endpoint in POST_ENDPOINTS:
                    # sync mutating endpoints go through the purgatory too
                    parked = self._purgatory_gate(endpoint, params,
                                                  query_string, client)
                    if parked is not None:
                        return parked
                out = (request.handle_sync(self, params) if request
                       else self._handle_sync(endpoint, params, cc=cc))
                return 200, {}, out
            return self._handle_async(endpoint, params, query_string,
                                      client, headers, request=request,
                                      body=body, cc=cc)
        except (ParameterError, ValueError) as exc:
            return self._error(400, exc)
        except AuthenticationError as exc:
            status, hdrs, body = self._error(401, exc)
            # advertise the login provider (jwt.authentication.provider.url)
            return status, {**hdrs,
                            **self.security.auth_challenge_headers()}, body
        except AuthorizationError as exc:
            return self._error(403, exc)
        except KeyError as exc:
            return self._error(404, exc)
        except OngoingExecutionError as exc:
            return self._error(409, exc)
        except QueueFullError as exc:
            # scheduler backpressure: the class queue is at its cap —
            # 429 with a Retry-After derived from the solve-latency EWMA
            return self._rate_limited(exc)
        except HttpError as exc:
            return self._error(exc.status, exc)
        except Exception as exc:  # noqa: BLE001 - 500 with message
            LOG.exception("request failed")
            return self._error(500, exc)

    @staticmethod
    def _error(status: int, exc: Exception) -> Tuple[int, Dict[str, str],
                                                     dict]:
        return status, {}, {"errorMessage": f"{type(exc).__name__}: {exc}",
                            "version": 1}

    @staticmethod
    def _rate_limited(exc: "QueueFullError",
                      extra_headers: Optional[Dict[str, str]] = None
                      ) -> Tuple[int, Dict[str, str], dict]:
        """429 + Retry-After for scheduler queue-cap rejections.  The
        body repeats the hint as `retryAfterSeconds` for clients that
        cannot read headers."""
        import math
        retry_after = max(1, int(math.ceil(exc.retry_after_s)))
        return 429, {**(extra_headers or {}),
                     "Retry-After": str(retry_after)}, \
            {"errorMessage": f"{type(exc).__name__}: {exc}",
             "retryAfterSeconds": retry_after, "version": 1}

    def _serve_ui(self, path: str) -> Tuple[int, Dict[str, str], dict]:
        """Serve the bundled UI from disk (reference
        `webserver.ui.diskpath` / `webserver.ui.urlprefix`; Jetty static
        resource handler).  Bodies carry raw bytes via the `__raw__`
        sentinel the HTTP layer streams verbatim."""
        import mimetypes
        import os
        rel = path[len(self._ui_urlprefix):].lstrip("/") or "index.html"
        root = os.path.abspath(self._ui_diskpath)
        full = os.path.abspath(os.path.join(root, rel))
        if not full.startswith(root + os.sep) and full != root:
            return 403, {}, {"errorMessage": "forbidden", "version": 1}
        if not os.path.isfile(full):
            return 404, {}, {"errorMessage": f"no such UI file {rel}",
                             "version": 1}
        ctype = mimetypes.guess_type(full)[0] or "application/octet-stream"
        with open(full, "rb") as fh:
            return 200, {}, {"__raw__": fh.read(),
                             "__content_type__": ctype}

    # ------------------------------------------------------------------
    # fleet tenant resolution
    # ------------------------------------------------------------------
    def _cc_for(self, params: QueryParams, for_write: bool = False):
        """The facade addressed by `?cluster=` (default tenant when
        omitted).  Unknown tenants answer 404; draining tenants answer
        503 for mutating endpoints (`for_write`)."""
        cluster = params.get("cluster") if "cluster" in \
            VALID_PARAMS.get(params.endpoint, set()) else None
        if self.fleet is None:
            if cluster is not None:
                raise HttpError(
                    404, f"unknown cluster {cluster!r}: this server is "
                         f"not running a fleet (--fleet-config)")
            return self.cc
        from cruise_control_tpu.fleet.registry import (TenantDrainingError,
                                                       UnknownTenantError)
        try:
            return self.fleet.facade_for(cluster, for_write=for_write)
        except UnknownTenantError as exc:
            raise HttpError(404, str(exc))
        except TenantDrainingError as exc:
            raise HttpError(503, str(exc))

    # public delegates for configured Request classes
    # (api.request_registry.Request defaults call back into these)
    def default_sync_handler(self, endpoint: str, params) -> dict:
        return self._handle_sync(endpoint, params,
                                 cc=self._cc_for(params))

    def default_operation(self, endpoint: str, params, body=None):
        return self._operation_for(endpoint, params, body=body,
                                   cc=self._cc_for(params, for_write=True))

    def _endpoint_of(self, method: str, path: str) -> str:
        base = self.base_path
        if not path.startswith(base + "/"):
            raise HttpError(404, f"unknown path {path}; expected "
                                 f"{base}/<endpoint>")
        endpoint = path[len(base) + 1:].strip("/").upper()
        if endpoint not in GET_ENDPOINTS and endpoint not in POST_ENDPOINTS \
                and endpoint != "REVIEW":
            raise HttpError(404, f"unknown endpoint {endpoint}")
        if method == "GET" and endpoint not in GET_ENDPOINTS:
            raise HttpError(405, f"{endpoint} is not a GET endpoint")
        if method == "POST" and endpoint not in POST_ENDPOINTS \
                and endpoint != "REVIEW":
            raise HttpError(405, f"{endpoint} is not a POST endpoint")
        return endpoint

    def _purgatory_gate(self, endpoint: str, params: QueryParams,
                        query_string: str, client: str,
                        body: Optional[str] = None
                        ) -> Optional[Tuple[int, Dict[str, str], dict]]:
        """Two-step verification: park unreviewed POSTs, consume approvals.
        Returns a parked-response triple, or None to proceed.

        For body-carrying endpoints (SCENARIOS) the BODY HASH joins the
        reviewed request identity: an approval must not be replayable
        with a different payload behind the same query string."""
        if self.purgatory is None or endpoint not in POST_ENDPOINTS:
            return None
        if body:
            from cruise_control_tpu.api.user_tasks import body_fingerprint
            sep = "&" if query_string else ""
            query_string = (f"{query_string}{sep}"
                            f"body_sha={body_fingerprint(body)}")
        review_id = params.get_int("review_id")
        if review_id is None:
            req = self.purgatory.submit(endpoint, query_string, client)
            return 202, {}, {"reviewResult": req.to_json(), "version": 1}
        self.purgatory.take_approved(review_id, endpoint, query_string)
        return None

    def _re_arming(self, op: Callable[[], dict], endpoint: str,
                   params: QueryParams) -> Callable[[], dict]:
        """Wrap a gated operation so a scheduler queue-cap rejection
        rolls the consumed one-shot approval back to APPROVED.  The
        rollback runs INSIDE the task (worker thread, exactly once,
        before the failed future resolves) rather than in the poll
        handler: the rejection may surface on the initial request, on a
        later re-poll carrying the task id, or on no poll at all — and a
        stale poll of a dead task must never re-arm an approval a
        successful retry has since re-consumed."""
        if self.purgatory is None or endpoint not in POST_ENDPOINTS:
            return op
        review_id = params.get_int("review_id")
        if review_id is None:
            return op

        def gated_op() -> dict:
            try:
                return op()
            except QueueFullError:
                self.purgatory.re_arm(review_id)
                raise
        return gated_op

    # ------------------------------------------------------------------
    # async machinery (reference handler/async + UserTaskManager)
    # ------------------------------------------------------------------
    def _handle_async(self, endpoint: str, params: QueryParams,
                      query_string: str, client: str,
                      headers: Mapping[str, str],
                      request=None, body: Optional[str] = None,
                      cc=None) -> Tuple[int, Dict[str, str], dict]:
        task_id = None
        for k, v in headers.items():
            if k.lower() == USER_TASK_ID_HEADER.lower():
                task_id = v
        # purgatory gate — skipped when re-polling an in-flight task (the
        # review id was already consumed when the task started)
        if task_id is None:
            parked = self._purgatory_gate(endpoint, params, query_string,
                                          client, body=body)
            if parked is not None:
                return parked
        trace = None
        if task_id is not None:
            # attach-only: get_or_create never runs the operation when a
            # task id is given (and a body-less re-poll must not trip
            # body validation in the operation builder)
            op: Callable[[], dict] = lambda: {}  # noqa: E731
        else:
            op = (request.operation(self, params) if request is not None
                  else self._operation_for(endpoint, params, body=body,
                                           cc=cc))
            op = self._re_arming(op, endpoint, params)
            # mint the request's TraceContext HERE — the transport edge
            # (obs/trace.py).  The operation runs on a USER_TASKS pool
            # worker, so the context crosses the thread hop inside
            # `finishing`; the trace finishes (and lands in the flight
            # recorder) when the OPERATION does, not when this poll
            # returns
            trace = obs_trace.start_detached(
                f"rest.{endpoint}", endpoint=endpoint, client=client,
                **({"cluster": params.get("cluster")}
                   if params.get("cluster") else {}))
            op = obs_trace.finishing(trace, op)
        info = self.user_tasks.get_or_create(
            endpoint, query_string, client, op, task_id=task_id,
            body=body,
            trace_id=trace.trace_id if trace is not None else "")
        # attach re-polls report the ORIGINAL operation's trace id
        trace_id = info.trace_id
        hdrs = {USER_TASK_ID_HEADER: info.task_id,
                # async session cookie scoped to the configured path
                # (reference webserver.session.path; the reference tracks
                # async requests per servlet session)
                "Set-Cookie": (f"CCSESSION={info.task_id}; "
                               f"Path={self.session_path}")}
        if trace_id:
            hdrs["Trace-Id"] = trace_id

        def with_trace(payload: dict) -> dict:
            # COPY instead of mutating: the payload may be the task's
            # cached result dict, shared with a concurrent poll of the
            # same (coalesced) task that is mid-serialization on
            # another handler thread
            if trace_id and isinstance(payload, dict) \
                    and "__raw__" not in payload \
                    and "traceId" not in payload:
                return {**payload, "traceId": trace_id}
            return payload

        try:
            result = info.future.result(timeout=self._async_timeout)
            return 200, hdrs, with_trace(result)
        except FutureTimeout:
            return 202, hdrs, with_trace(
                {"progress": [{"operation": endpoint,
                               "status": "InProgress"}],
                 "version": 1})
        except Exception as exc:  # noqa: BLE001 - operation failed
            LOG.warning("async %s operation failed: %s: %s", endpoint,
                        type(exc).__name__, exc)
            if isinstance(exc, QueueFullError):
                # the solve was rejected at the scheduler's queue cap:
                # backpressure, not failure — 429 + Retry-After (the
                # task id headers still ride along for diagnostics).
                # The consumed two-step approval was already re-armed
                # inside the task itself (_re_arming): the rejection may
                # surface on ANY poll of the task — or on none, if the
                # client gives up — so the rollback cannot live here
                status, rl_hdrs, rl_body = self._rate_limited(
                    exc, extra_headers=hdrs)
                return status, rl_hdrs, with_trace(rl_body)
            status = 409 if isinstance(exc, OngoingExecutionError) else 500
            return status, hdrs, with_trace(
                {"errorMessage": f"{type(exc).__name__}: {exc}",
                 "version": 1})

    # ------------------------------------------------------------------
    # per-endpoint operations
    # ------------------------------------------------------------------
    def _operation_for(self, endpoint: str, params: QueryParams,
                       body: Optional[str] = None,
                       cc=None) -> Callable[[], dict]:
        cc = cc if cc is not None else self.cc
        if endpoint == "SCENARIOS":
            # batched what-if analysis (scenario/engine.py): spec list in
            # the JSON body, DRY-RUN ONLY — the engine ranks
            # hypotheticals, it can never execute them.  Body validation
            # happens HERE (request time, 400 on garbage), not inside the
            # async task.
            from cruise_control_tpu.scenario.report import batch_report
            from cruise_control_tpu.scenario.spec import \
                parse_scenarios_payload
            if not getattr(cc, "_scenario_enabled", True):
                # deterministic configuration rejection: answer 400 at
                # request time, not a failed task at poll time
                raise ValueError("the scenario engine is disabled "
                                 "(scenario.engine.enabled=false)")
            specs, goal_override, include_base = \
                parse_scenarios_payload(body)
            verbose = params.get_bool("verbose")
            reason = params.get("reason", "SCENARIOS via REST")

            def scenarios_op() -> dict:
                result = cc.evaluate_scenarios(
                    specs, goals=goal_override,
                    include_base=include_base, reason=reason)
                return batch_report(result, verbose=verbose)
            return scenarios_op

        if endpoint == "PROPOSALS":
            goals = params.get_csv("goals")
            verbose = params.get_bool("verbose")
            ignore_cache = params.get_bool("ignore_proposal_cache")
            excluded = params.get_csv("excluded_topics")
            portfolio_width = params.get_int("portfolio_width")
            options = (OptimizationOptions(
                excluded_topics=frozenset(excluded)) if excluded else None)

            def proposals_op() -> dict:
                result = cc.optimizations(goals, options,
                                          ignore_proposal_cache=ignore_cache,
                                          portfolio_width=portfolio_width)
                return R.optimization_result(result, verbose=verbose)
            return proposals_op

        if endpoint == "LOAD":
            def load_op() -> dict:
                state, topo = cc.cluster_model()
                return R.broker_stats(state, topo)
            return load_op

        if endpoint == "PARTITION_LOAD":
            resource = params.get_resource("resource")
            entries = params.get_int("entries")
            topic = params.get("topic")

            def partition_load_op() -> dict:
                state, topo = cc.cluster_model()
                return {"records": R.partition_load(
                    state, topo, resource=resource, entries=entries,
                    topic_pattern=topic),
                    "version": 1}
            return partition_load_op

        if endpoint == "BOOTSTRAP":
            def bootstrap_op() -> dict:
                # enough synchronous rounds to fill every window
                agg = cc.load_monitor.partition_aggregator
                rounds = agg.num_windows + 1
                cc.load_monitor.task_runner.bootstrap(rounds)
                return {"message": f"bootstrapped {rounds} sampling rounds",
                        "version": 1}
            return bootstrap_op

        if endpoint == "TRAIN":
            def train_op() -> dict:
                cc.load_monitor.train()
                return {"message": "training triggered", "version": 1}
            return train_op

        if endpoint in ("REBALANCE", "ADD_BROKER", "REMOVE_BROKER",
                        "DEMOTE_BROKER", "FIX_OFFLINE_REPLICAS",
                        "TOPIC_CONFIGURATION"):
            return self._mutation_operation(endpoint, params, cc=cc)

        raise HttpError(404, f"unhandled endpoint {endpoint}")

    def _mutation_operation(self, endpoint: str, params: QueryParams,
                            cc=None) -> Callable[[], dict]:
        cc = cc if cc is not None else self.cc
        dryrun = params.get_bool("dryrun", default=True)
        verbose = params.get_bool("verbose")
        goals = params.get_csv("goals")
        reason = params.get("reason", f"{endpoint} via REST")
        throttle = params.get_float("replication_throttle")
        exec_kwargs: dict = {}
        if throttle is not None:
            exec_kwargs["replication_throttle"] = throttle
        conc = params.get_int("concurrent_partition_movements_per_broker")
        if conc is not None:
            exec_kwargs["concurrent_inter_broker_moves"] = conc
        lead = params.get_int("concurrent_leader_movements")
        if lead is not None:
            exec_kwargs["concurrent_leader_movements"] = lead
        strategies = params.get_csv("replica_movement_strategies")
        strategy = strategy_from_names(strategies) if strategies else None

        if endpoint in ("ADD_BROKER", "REMOVE_BROKER", "DEMOTE_BROKER"):
            raw = params.get("brokerid") or ""
            if ";" in raw:
                # K candidate broker sets ("1,2;3,4"): the facade routes
                # these through the scenario engine (dry-run only) and
                # returns the ranked what-if report
                try:
                    broker_ids = [[int(x) for x in grp.split(",")
                                   if x.strip()]
                                  for grp in raw.split(";") if grp.strip()]
                except ValueError:
                    raise ParameterError(
                        "brokerid candidate sets must be CSV integers "
                        "separated by ';'")
            else:
                broker_ids = params.get_csv_ints("brokerid")
            if not broker_ids:
                raise ParameterError(f"{endpoint} requires brokerid")
        else:
            broker_ids = None

        def run() -> dict:
            if endpoint == "REBALANCE":
                excluded = params.get_csv("excluded_topics")
                dests = params.get_csv_ints("destination_broker_ids")
                options = None
                if excluded or dests:
                    options = OptimizationOptions(
                        excluded_topics=frozenset(excluded or ()),
                        requested_destination_broker_ids=frozenset(
                            dests or ()))
                op = cc.rebalance(goals=goals, dryrun=dryrun,
                                  options=options, reason=reason,
                                  strategy=strategy,
                                  ignore_proposal_cache=params.get_bool(
                                      "ignore_proposal_cache"),
                                  kafka_assigner=params.get_bool(
                                      "kafka_assigner"),
                                  portfolio_width=params.get_int(
                                      "portfolio_width"),
                                  **exec_kwargs)
            elif endpoint == "ADD_BROKER":
                op = cc.add_brokers(broker_ids, goals=goals, dryrun=dryrun,
                                    reason=reason, **exec_kwargs)
            elif endpoint == "REMOVE_BROKER":
                op = cc.remove_brokers(broker_ids, goals=goals,
                                       dryrun=dryrun, reason=reason,
                                       **exec_kwargs)
            elif endpoint == "DEMOTE_BROKER":
                op = cc.demote_brokers(broker_ids, dryrun=dryrun,
                                       reason=reason, **exec_kwargs)
            elif endpoint == "FIX_OFFLINE_REPLICAS":
                op = cc.fix_offline_replicas(goals=goals, dryrun=dryrun,
                                             reason=reason, **exec_kwargs)
            else:  # TOPIC_CONFIGURATION
                topic = params.get("topic")
                rf = params.get_int("replication_factor")
                if not topic or rf is None:
                    raise ParameterError(
                        "TOPIC_CONFIGURATION requires topic and "
                        "replication_factor")
                op = cc.update_topic_replication_factor(
                    topic, rf, goals=goals, dryrun=dryrun, reason=reason,
                    **exec_kwargs)
            if op.optimizer_result is not None:
                body = R.optimization_result(op.optimizer_result,
                                             verbose=verbose)
            else:   # direct-proposal operations (RF change, what-ifs)
                body = {"summary": {
                    "numReplicaMovements": sum(
                        1 for p in op.proposals if p.has_replica_action),
                    "numProposals": len(op.proposals)},
                    "goalSummary": []}
                if verbose:
                    body["proposals"] = [p.to_json() for p in op.proposals]
            if op.scenario_report is not None:
                # multiple candidate broker sets were ranked by the
                # scenario engine: the full report rides along, the
                # summary/proposals above describe the best candidate
                body["scenarioReport"] = op.scenario_report
            body["dryRun"] = op.dryrun
            if op.execution_uuid:
                body["executionId"] = op.execution_uuid
            return body
        return run

    # ------------------------------------------------------------------
    # sync endpoints
    # ------------------------------------------------------------------
    def _handle_sync(self, endpoint: str, params: QueryParams,
                     cc=None) -> dict:
        cc = cc if cc is not None else self.cc
        if endpoint == "FLEET":
            if self.fleet is None:
                raise HttpError(
                    404, "fleet serving is not configured "
                         "(start with --fleet-config)")
            return {**self.fleet.fleet_json(
                verbose=params.get_bool("verbose")), "version": 1}
        if endpoint == "TRACES":
            # flight-recorder query (obs/recorder.py): pinned incident
            # traces a query RETURNS count as exported and drop their
            # pin.  Under a fleet, `?cluster=` was already validated by
            # tenant resolution above; it filters by the trace's
            # cluster tag here.
            cluster = params.get("cluster")
            limit = params.get_int("limit")
            # a query only counts as an EXPORT (dropping pins) when it
            # delivers the span trees — a compact listing that stripped
            # them would unpin incident traces without ever handing
            # their evidence over
            deliver_trees = (params.get("trace_id") is not None
                             or params.get_bool("verbose"))
            traces = obs_recorder.get_recorder().query(
                trace_id=params.get("trace_id"), cluster=cluster,
                outcome=params.get("outcome"),
                limit=limit if limit is not None else 32,
                export=deliver_trees,
                since_ms=params.get_float("since"),
                min_duration_ms=params.get_float("min_duration_ms"))
            out = {"traces": traces,
                   "recorder": obs_recorder.get_recorder().to_json(),
                   "version": 1}
            if not deliver_trees:
                # compact listing: ids / outcomes / durations only (the
                # tree of ONE trace is what ?trace_id= fetches)
                out["traces"] = [
                    {k: v for k, v in t.items() if k != "root"}
                    for t in traces]
            return out
        if endpoint == "STATE":
            substates = params.get_csv("substates")
            out = cc.state(substates)
            if self.fleet is not None:
                want = {s.lower() for s in (substates or ("fleet",))}
                if "fleet" in want:
                    out["FleetState"] = self.fleet.state_json()
                if "sensors" in want and "Sensors" in out:
                    # fleet-level sensors (fleet-bucket-compiles,
                    # fleet-folded-solves, shared-scheduler meters) ride
                    # along with the tenant's own
                    out["Sensors"].update(self.fleet.metrics.to_json())
            out["version"] = 1
            return out
        if endpoint == "KAFKA_CLUSTER_STATE":
            out = R.kafka_cluster_state(
                cc.load_monitor.metadata.refresh_metadata())
            out["version"] = 1
            return out
        if endpoint == "USER_TASKS":
            ids = params.get_csv("user_task_ids")
            tasks = self.user_tasks.all_tasks()
            if ids:
                tasks = [t for t in tasks if t.task_id in set(ids)]
            return {"userTasks": [t.to_json() for t in tasks], "version": 1}
        if endpoint == "REVIEW_BOARD":
            if self.purgatory is None:
                raise HttpError(400, "two-step verification is disabled")
            ids = params.get_csv_ints("review_ids")
            return {"requestInfo": [r.to_json() for r
                                    in self.purgatory.all_requests(ids)],
                    "version": 1}
        if endpoint == "REVIEW":
            if self.purgatory is None:
                raise HttpError(400, "two-step verification is disabled")
            approve = params.get_csv_ints("approve") or []
            discard = params.get_csv_ints("discard") or []
            reason = params.get("reason", "")
            changed = self.purgatory.review(approve, discard, reason)
            return {"requestInfo": [r.to_json() for r in changed],
                    "version": 1}
        if endpoint == "STOP_PROPOSAL_EXECUTION":
            cc.stop_execution(force=params.get_bool("force_stop"))
            return {"message": "execution stop requested", "version": 1}
        if endpoint == "PAUSE_SAMPLING":
            cc.pause_sampling(params.get("reason", "paused via REST"))
            return {"message": "sampling paused", "version": 1}
        if endpoint == "RESUME_SAMPLING":
            cc.resume_sampling(params.get("reason", "resumed via REST"))
            return {"message": "sampling resumed", "version": 1}
        if endpoint == "ADMIN":
            out: dict = {"version": 1}
            for param, enable in (("enable_self_healing_for", True),
                                  ("disable_self_healing_for", False)):
                names = params.get_csv(param)
                if names:
                    changed = {}
                    for name in names:
                        try:
                            at = AnomalyType[name.upper()]
                        except KeyError:
                            raise ParameterError(
                                f"unknown anomaly type {name!r}")
                        old = cc.anomaly_detector.set_self_healing_for(
                            at, enable)
                        changed[at.name] = {"before": old, "after": enable}
                    out.setdefault("selfHealing", {}).update(changed)
            return out
        raise HttpError(404, f"unhandled sync endpoint {endpoint}")

    # ------------------------------------------------------------------
    # HTTP transport
    # ------------------------------------------------------------------
    def start(self, host: str = "127.0.0.1", port: int = 9090,
              ssl_context: Optional["ssl.SSLContext"] = None) -> int:
        """Start the HTTP(S) server; returns the bound port.

        `ssl_context` wraps the listening socket for TLS (reference
        KafkaCruiseControlApp.java:100-173 optional SSL connector); build
        one from config with `make_server_ssl_context`."""
        app = self

        class Handler(BaseHTTPRequestHandler):
            MAX_BODY_BYTES = 16 * 1024 * 1024

            def _dispatch(self, method: str) -> None:
                parsed = urllib.parse.urlsplit(self.path)
                request_body: Optional[str] = None
                if method == "POST":
                    try:
                        length = int(self.headers.get("Content-Length",
                                                      0) or 0)
                    except ValueError:
                        length = 0
                    if length > self.MAX_BODY_BYTES:
                        self.send_error(413, "request body too large")
                        return
                    if length > 0:
                        request_body = self.rfile.read(length).decode(
                            "utf-8", errors="replace")
                status, hdrs, body = app.handle_request(
                    method, parsed.path, parsed.query,
                    dict(self.headers.items()),
                    client=self.client_address[0],
                    body=request_body)
                hdrs = {**hdrs, **app._cors_headers}
                if isinstance(body, dict) and "__raw__" in body:
                    data = body["__raw__"]
                    ctype = body.get("__content_type__",
                                     "application/octet-stream")
                else:
                    data = json.dumps(body, indent=2).encode()
                    ctype = "application/json"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                for k, v in hdrs.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self) -> None:  # noqa: N802 - stdlib API
                self._dispatch("GET")

            def do_POST(self) -> None:  # noqa: N802
                self._dispatch("POST")

            def do_OPTIONS(self) -> None:  # noqa: N802
                # CORS preflight: browsers send OPTIONS before any
                # cross-origin request carrying Authorization/User-Task-ID
                self.send_response(204)
                for k, v in app._cors_headers.items():
                    self.send_header(k, v)
                if app._cors_headers:
                    self.send_header("Access-Control-Allow-Methods",
                                     "GET, POST, OPTIONS")
                self.send_header("Content-Length", "0")
                self.end_headers()

            def log_request(self, code="-", size="-") -> None:
                # NCSA common-log line per request (reference
                # KafkaCruiseControlApp.java:133-148 NCSA access log),
                # logger name `accessLogger` so deployments route it to
                # its own file
                if app._access_log:
                    code = getattr(code, "value", code)
                    now = _time.localtime()
                    # fixed English month names: %b is locale-dependent
                    # and would break NCSA parsers under non-C locales
                    stamp = ("%02d/%s/%04d:%02d:%02d:%02d %s" % (
                        now.tm_mday, _NCSA_MONTHS[now.tm_mon - 1],
                        now.tm_year, now.tm_hour, now.tm_min, now.tm_sec,
                        _time.strftime("%z", now)))
                    ACCESS_LOG.info(
                        '%s - - [%s] "%s" %s %s', self.client_address[0],
                        stamp, self.requestline, code, size)

            def log_message(self, fmt: str, *args) -> None:
                LOG.debug("http: " + fmt, *args)

        self._http = ThreadingHTTPServer((host, port), Handler)
        if ssl_context is not None:
            self._http.socket = ssl_context.wrap_socket(
                self._http.socket, server_side=True)
        threading.Thread(target=self._http.serve_forever,
                         name="rest-server", daemon=True).start()
        return self._http.server_address[1]

    def stop(self) -> None:
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
            self._http = None
        self.user_tasks.shutdown()
