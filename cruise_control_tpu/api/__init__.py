"""REST API plane (SURVEY.md §2.7): endpoint dispatch, async user tasks,
two-step verification purgatory, pluggable security."""
from cruise_control_tpu.api.parameters import (ParameterError, QueryParams,
                                               VALID_PARAMS)
from cruise_control_tpu.api.purgatory import (Purgatory, ReviewRequest,
                                              ReviewStatus)
from cruise_control_tpu.api.security import (AuthenticationError,
                                             AuthorizationError,
                                             BasicSecurityProvider,
                                             NoSecurityProvider, Principal,
                                             Role, SecurityProvider,
                                             TokenSecurityProvider,
                                             TrustedProxySecurityProvider)
from cruise_control_tpu.api.server import BASE_PATH, CruiseControlApp
from cruise_control_tpu.api.user_tasks import (USER_TASK_ID_HEADER,
                                               TaskStatus, UserTaskInfo,
                                               UserTaskManager)

__all__ = [
    "CruiseControlApp", "BASE_PATH", "QueryParams", "ParameterError",
    "VALID_PARAMS", "Purgatory", "ReviewRequest", "ReviewStatus",
    "SecurityProvider", "NoSecurityProvider", "BasicSecurityProvider",
    "TokenSecurityProvider", "TrustedProxySecurityProvider", "Principal",
    "Role", "AuthenticationError", "AuthorizationError",
    "UserTaskManager", "UserTaskInfo", "TaskStatus", "USER_TASK_ID_HEADER",
]
