"""Two-step verification purgatory for POST requests.

Reference CC/servlet/purgatory/Purgatory.java:1-280 + the wiki's
2-step-verification doc: when enabled, mutating POSTs are parked as
review requests; an admin approves or discards them through REVIEW, and an
approved request executes when re-submitted with its review id.
"""
from __future__ import annotations

import dataclasses
import enum
import itertools
import threading
import time as _time
from typing import Callable, Dict, List, Optional


class ReviewStatus(enum.Enum):
    PENDING_REVIEW = "PENDING_REVIEW"
    APPROVED = "APPROVED"
    SUBMITTED = "SUBMITTED"
    DISCARDED = "DISCARDED"


@dataclasses.dataclass
class ReviewRequest:
    review_id: int
    endpoint: str
    query: str
    submitter: str
    status: ReviewStatus
    submitted_ms: float
    reason: str = ""
    status_update_ms: float = 0.0

    def to_json(self) -> dict:
        return {
            "Id": self.review_id,
            "EndPoint": self.endpoint,
            "RequestURL": f"{self.endpoint}?{self.query}" if self.query
                          else self.endpoint,
            "SubmitterAddress": self.submitter,
            "Status": self.status.value,
            "SubmissionTimeMs": self.submitted_ms,
            "Reason": self.reason,
        }


class Purgatory:
    def __init__(self, retention_s: float = 7 * 24 * 3600.0,
                 max_requests: Optional[int] = None,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self._retention_s = retention_s
        #: cap on parked requests (reference two.step.purgatory.max.requests)
        self._max_requests = max_requests
        self._time = time_fn or _time.time
        self._lock = threading.Lock()
        self._ids = itertools.count()
        self._requests: Dict[int, ReviewRequest] = {}

    def submit(self, endpoint: str, query: str, submitter: str
               ) -> ReviewRequest:
        now_ms = self._time() * 1000.0
        with self._lock:
            self._expire(now_ms)
            if self._max_requests is not None:
                pending = sum(1 for r in self._requests.values()
                              if r.status == ReviewStatus.PENDING_REVIEW)
                if pending >= self._max_requests:
                    raise ValueError(
                        f"purgatory full: {pending} pending requests "
                        f"(two.step.purgatory.max.requests="
                        f"{self._max_requests})")
            rid = next(self._ids)
            req = ReviewRequest(rid, endpoint, query, submitter,
                                ReviewStatus.PENDING_REVIEW, now_ms)
            self._requests[rid] = req
            return req

    def review(self, approve_ids: List[int], discard_ids: List[int],
               reason: str = "") -> List[ReviewRequest]:
        now_ms = self._time() * 1000.0
        with self._lock:
            overlap = set(approve_ids) & set(discard_ids)
            if overlap:
                raise ValueError(f"ids both approved and discarded: "
                                 f"{sorted(overlap)}")
            out = []
            for rid, status in (
                    [(i, ReviewStatus.APPROVED) for i in approve_ids]
                    + [(i, ReviewStatus.DISCARDED) for i in discard_ids]):
                req = self._requests.get(rid)
                if req is None:
                    raise KeyError(f"unknown review id {rid}")
                if req.status not in (ReviewStatus.PENDING_REVIEW,
                                      ReviewStatus.APPROVED):
                    raise ValueError(
                        f"review {rid} is {req.status.value}; cannot change")
                req.status = status
                req.reason = reason
                req.status_update_ms = now_ms
                out.append(req)
            return out

    @staticmethod
    def _canonical_query(query: str) -> List:
        """Query params sorted, with review_id stripped — the approval is
        bound to exactly what was reviewed."""
        import urllib.parse
        pairs = urllib.parse.parse_qsl(query, keep_blank_values=True)
        return sorted((k, v) for k, v in pairs if k.lower() != "review_id")

    def take_approved(self, review_id: int, endpoint: str,
                      query: str = "") -> ReviewRequest:
        """Consume an approved request for execution (one shot).  The
        resubmission must match the reviewed endpoint AND parameters —
        otherwise an approved harmless request could authorize an arbitrary
        mutation."""
        with self._lock:
            req = self._requests.get(review_id)
            if req is None:
                raise KeyError(f"unknown review id {review_id}")
            if req.endpoint != endpoint:
                raise ValueError(
                    f"review {review_id} is for {req.endpoint}, "
                    f"not {endpoint}")
            if self._canonical_query(req.query) \
                    != self._canonical_query(query):
                raise ValueError(
                    f"review {review_id} was approved for different "
                    f"parameters ({req.query!r})")
            if req.status != ReviewStatus.APPROVED:
                raise ValueError(
                    f"review {review_id} is {req.status.value}, "
                    f"not APPROVED")
            req.status = ReviewStatus.SUBMITTED
            req.status_update_ms = self._time() * 1000.0
            return req

    def re_arm(self, review_id: int) -> None:
        """Roll a consumed (SUBMITTED) approval back to APPROVED because
        the submission was rejected at the scheduler's queue cap — the
        reviewed operation never executed, so burning the one-shot
        approval would turn documented backpressure ("retry later") into
        a permanent failure.  One execution per approval still holds:
        only the request that consumed the approval re-arms it, and only
        when the solve was never admitted."""
        with self._lock:
            req = self._requests.get(review_id)
            if req is not None and req.status == ReviewStatus.SUBMITTED:
                req.status = ReviewStatus.APPROVED
                req.status_update_ms = self._time() * 1000.0

    def all_requests(self, review_ids: Optional[List[int]] = None
                     ) -> List[ReviewRequest]:
        with self._lock:
            self._expire(self._time() * 1000.0)
            reqs = self._requests.values()
            if review_ids is not None:
                reqs = [r for r in reqs if r.review_id in set(review_ids)]
            return sorted(reqs, key=lambda r: r.review_id)

    def _expire(self, now_ms: float) -> None:
        cutoff = now_ms - self._retention_s * 1000.0
        for rid in [rid for rid, r in self._requests.items()
                    if r.submitted_ms < cutoff]:
            del self._requests[rid]
