"""Machine-readable JSON Schemas for every REST response body.

The reference publishes OpenAPI YAML per endpoint and walks its
@JsonResponseClass annotations against it in a conformance test
(reference: cruise-control/src/test/java/.../ResponseTest.java:1-227,
cruise-control/src/yaml/endpoints/*.yaml).  Here the schemas are the
source of truth in code: `ENDPOINT_SCHEMAS` maps endpoint → JSON Schema
(draft 2020-12) for the 200 body, plus shared schemas for the 202
async-progress body, the purgatory 202 review body, and the error body.
`python -m cruise_control_tpu.api.schema` emits the whole set as one JSON
document (docs/RESPONSE_SCHEMAS.json); tests/test_response_schema.py
validates live server output against these.
"""
from __future__ import annotations

import json
from typing import Dict

_NUM = {"type": "number"}
_INT = {"type": "integer"}
_STR = {"type": "string"}
_BOOL = {"type": "boolean"}


def _obj(properties: dict, required=None, extra=True) -> dict:
    out = {"type": "object", "properties": properties,
           "additionalProperties": extra}
    if required:
        out["required"] = sorted(required)
    return out


def _arr(items: dict) -> dict:
    return {"type": "array", "items": items}


_BROKER_ROW = _obj({
    "Broker": _INT, "Host": _STR, "Rack": _STR,
    "BrokerState": {"enum": ["ALIVE", "DEAD"]},
    "Replicas": _INT, "Leaders": _INT,
    "CpuPct": _NUM, "NwInRate": _NUM, "NwOutRate": _NUM,
    "DiskMB": _NUM, "DiskPct": _NUM,
}, required=["Broker", "BrokerState", "Replicas", "Leaders"])

_HOST_ROW = _obj({
    "Host": _STR, "Replicas": _INT, "Leaders": _INT,
    "CpuPct": _NUM, "NwInRate": _NUM, "NwOutRate": _NUM, "DiskMB": _NUM,
}, required=["Host", "Replicas", "Leaders"])

BROKER_STATS = _obj({
    "brokers": _arr(_BROKER_ROW),
    "hosts": _arr(_HOST_ROW),
}, required=["brokers", "hosts"])

_PARTITION_ROW = _obj({
    "topic": _STR, "partition": _INT, "leader": _INT,
    "followers": _arr(_INT),
    "cpu": _NUM, "networkInbound": _NUM, "networkOutbound": _NUM,
    "disk": _NUM,
}, required=["topic", "partition", "leader", "followers"])

PARTITION_LOAD = _obj({
    "records": _arr(_PARTITION_ROW), "version": _INT,
}, required=["records", "version"])

_PROPOSAL = _obj({
    "topicPartition": _obj({"topic": _STR, "partition": _INT}),
    "oldLeader": _INT,
    "oldReplicas": _arr(_INT),
    "newReplicas": _arr(_INT),
}, required=["topicPartition", "newReplicas"])

#: which solver produced an optimization result (portfolio/): absent
#: entirely for a plain greedy solve with no portfolio in play, so
#: pre-portfolio response bodies stay byte-identical
_SOLVER_PROVENANCE = _obj({
    "solver": {"enum": ["greedy", "portfolio"]},
    "portfolioWidth": _INT,
    "portfolioSeed": _INT,
    "generation": {},
    "rung": {"enum": ["FUSED", "EAGER", "CPU"]},
    "candidateIndex": _INT,
    "perturbation": _STR,
    "greedyFitness": _NUM,
    "bestCandidateFitness": {"type": ["number", "null"]},
    "error": _STR,
}, required=["solver", "portfolioWidth", "portfolioSeed"])

OPTIMIZATION_RESULT = _obj({
    "summary": _obj({
        "numReplicaMovements": _INT,
        "numLeaderMovements": _INT,
        "dataToMoveMB": _NUM,
        "numProposals": _INT,
        "excludedTopics": _arr(_STR),
        "onDemandBalancednessScoreBefore": {"type": ["number", "null"]},
        "onDemandBalancednessScoreAfter": _NUM,
        "provisionStatus": _STR,
    }, required=["numReplicaMovements", "numProposals"]),
    "goalSummary": _arr(_obj({
        "goal": _STR,
        "status": {"enum": ["FIXED", "VIOLATED", "NO-ACTION"]},
    }, required=["goal", "status"])),
    "violatedGoalsBefore": _arr(_STR),
    "violatedGoalsAfter": _arr(_STR),
    "solverProvenance": _SOLVER_PROVENANCE,
    "proposals": _arr(_PROPOSAL),
}, required=["summary", "goalSummary"])

KAFKA_CLUSTER_STATE = _obj({
    "KafkaBrokerState": _obj({
        "LeaderCountByBrokerId": _obj({}, extra=True),
        "ReplicaCountByBrokerId": _obj({}, extra=True),
        "OutOfSyncCountByBrokerId": _obj({}, extra=True),
        "OfflineReplicaCountByBrokerId": _obj({}, extra=True),
        "IsController": _obj({}, extra=True),
    }, required=["LeaderCountByBrokerId", "ReplicaCountByBrokerId"]),
    "KafkaPartitionState": _obj({}, extra=True),
    "version": _INT,
}, required=["KafkaBrokerState", "KafkaPartitionState", "version"])

#: crash-recovery telemetry inside ExecutorState (executor/journal.py +
#: recovery.py): present only when journaling is configured or a
#: recovery ran — journal-less deployments see the pre-journal body
_EXECUTOR_RECOVERY = _obj({
    "journalEnabled": _BOOL,
    "recoveryInProgress": _BOOL,
    "journal": _obj({
        "directory": _STR, "broken": _BOOL, "writes": _INT,
        "bytesWritten": _INT, "errors": _INT,
    }),
    "lastRecovery": _obj({
        "mode": {"enum": ["resume", "abort"]},
        "uuid": _STR,
        "resumed": _BOOL,
        "tasksTotal": _INT, "tasksTerminal": _INT,
        "tasksAdopted": _INT, "tasksPending": _INT,
        "clearedThrottleBrokers": _arr(_INT),
        "cancelledReassignments": _INT,
        "journalTruncated": _BOOL,
        "phaseAtCrash": {"type": ["string", "null"]},
        "recoveredAtMs": _NUM,
    }),
}, required=["journalEnabled", "recoveryInProgress"])

#: per-class SLO burn state (obs/slo.py; substate `slo`)
_SLO_CLASS = _obj({
    "objective": _obj({
        "latencyMs": _NUM, "queueWaitMs": _NUM, "errorBudget": _NUM,
    }),
    "windowSolves": _INT,
    "queueWaitBurn": _NUM,
    "deviceTimeBurn": _NUM,
    "burn": _NUM,
    "budgetRemaining": _NUM,
    "status": {"enum": ["ok", "burning", "breach"]},
}, required=["burn", "status"])

SLO_STATUS = _obj({
    "enabled": _BOOL,
    "windowS": _NUM,
    "alertThreshold": _NUM,
    "status": {"enum": ["ok", "burning", "breach"]},
    "worstBurn": _NUM,
    "worstClass": {"type": ["string", "null"]},
    "classes": {"type": "object", "additionalProperties": _SLO_CLASS},
    "detector": _obj({
        "breachedClasses": _arr(_STR), "reported": _INT,
    }),
}, required=["enabled", "status", "worstBurn"])

STATE = _obj({
    "MonitorState": _obj({}, extra=True),
    "ExecutorState": _obj({"recovery": _EXECUTOR_RECOVERY}, extra=True),
    "AnalyzerState": _obj({}, extra=True),
    "AnomalyDetectorState": _obj({}, extra=True),
    "PortfolioState": _obj({}, extra=True),
    "SchedulerState": _obj({}, extra=True),
    "FleetState": _obj({}, extra=True),
    "IncrementalStoreState": _obj({}, extra=True),
    "sloStatus": SLO_STATUS,
    "version": _INT,
}, required=["version"])

_FLEET_TENANT = _obj({
    "clusterId": _STR,
    "status": {"enum": ["ACTIVE", "DRAINING"]},
    "isDefault": _BOOL,
    "registeredAtMs": _INT,
    "monitor": _obj({}, extra=True),
    "solverRung": {"enum": ["FUSED", "EAGER", "CPU"]},
    "hasOngoingExecution": _BOOL,
    "state": _obj({}, extra=True),
    "stateError": _STR,
}, required=["clusterId", "status", "isDefault"])

#: fleet tenant listing (multi-cluster serving, fleet/registry.py)
FLEET = _obj({
    "clusters": _arr(_FLEET_TENANT),
    "defaultTenant": {"oneOf": [_STR, {"type": "null"}]},
    "buckets": _obj({
        "bucketFloor": _INT,
        "trackedCombos": _INT,
        "totalCombos": _INT,
        "maxTracked": _INT,
    }, required=["bucketFloor", "totalCombos"]),
    "foldEnabled": _BOOL,
    "router": _obj({
        "totalFoldedSolves": _INT,
        "totalFoldBatches": _INT,
        "totalFallbacks": _INT,
        "maxGroup": _INT,
    }),
    "version": _INT,
}, required=["clusters", "defaultTenant", "buckets", "foldEnabled",
             "version"])

_USER_TASK = _obj({
    "UserTaskId": _STR,
    "Status": {"enum": ["Active", "Completed", "CompletedWithError"]},
    "RequestURL": _STR,
    "ClientIdentity": _STR,
    "StartMs": _NUM,
    # device-time scheduler visibility (present while the task's solve
    # is queued or running): priority class, 1-based dispatch-order
    # position while queued with 0 reserved for on-the-device-now, and
    # the estimated/actual start
    "SchedulerClass": {"enum": ["ANOMALY_HEAL", "USER_INTERACTIVE",
                                "PRECOMPUTE", "SCENARIO_SWEEP"]},
    "QueuePosition": {"type": "integer", "minimum": 0},
    "EstimatedStartMs": _NUM,
    "SolverProvenance": _SOLVER_PROVENANCE,
}, required=["UserTaskId", "Status"])

USER_TASKS = _obj({
    "userTasks": _arr(_USER_TASK), "version": _INT,
}, required=["userTasks", "version"])

_REVIEW_REQUEST = _obj({
    "Id": _INT, "Status": _STR, "EndPoint": _STR, "Reason": _STR,
    "SubmitterAddress": _STR,
}, required=["Id", "Status", "EndPoint"])

REVIEW_BOARD = _obj({
    "requestInfo": _arr(_REVIEW_REQUEST), "version": _INT,
}, required=["requestInfo", "version"])

_SCENARIO_OUTCOME = _obj({
    "name": _STR,
    "feasible": _BOOL,
    "rung": {"enum": ["FUSED", "EAGER", "CPU"]},
    "reason": _STR,
    "balancedness": _NUM,
    "numReplicaMoves": _INT,
    "numLeadershipMoves": _INT,
    "dataToMoveMB": _NUM,
    "violatedGoalsBefore": _arr(_STR),
    "violatedGoalsAfter": _arr(_STR),
    "statsAfter": _obj({}, extra=True),
    "vsBase": _obj({
        "balancednessDelta": _NUM,
        "violatedGoalsAfterDelta": _INT,
        "dataToMoveDeltaMB": _NUM,
        "numReplicaMovesDelta": _INT,
    }),
    "numProposals": _INT,
    "proposals": _arr(_PROPOSAL),
}, required=["name", "feasible", "rung", "balancedness"])

SCENARIOS = _obj({
    "scenarios": _arr(_SCENARIO_OUTCOME),
    "base": {"oneOf": [_SCENARIO_OUTCOME, {"type": "null"}]},
    "batch": _obj({
        "numScenarios": _INT,
        "rung": {"enum": ["FUSED", "EAGER", "CPU"]},
        "oomHalvings": _INT,
        "deviceBatchSizes": _arr(_INT),
        "compileS": _NUM,
        "solveS": _NUM,
        "durationS": _NUM,
    }, required=["numScenarios", "rung", "oomHalvings"]),
    "dryRun": {"const": True},
    "version": _INT,
}, required=["scenarios", "batch", "dryRun", "version"])

#: one span node in a trace tree (recursive via $ref-free nesting: the
#: validator in tests walks `children` with the same shape)
_TRACE_SPAN = _obj({
    "spanId": _INT,
    "name": _STR,
    "startMs": _NUM,
    "durationMs": _NUM,
    "tags": _obj({}, extra=True),
    "events": _arr(_obj({}, extra=True)),
    "children": _arr(_obj({}, extra=True)),
}, required=["spanId", "name", "durationMs"])

_TRACE = _obj({
    "traceId": _STR,
    "name": _STR,
    "outcome": {"enum": ["ok", "failed", "degraded", "fallback",
                         "preempted", "rejected"]},
    "tags": _obj({}, extra=True),
    "startMs": _NUM,
    "durationMs": _NUM,
    "numSpans": _INT,
    "droppedSpans": _INT,
    "root": _TRACE_SPAN,
}, required=["traceId", "outcome", "durationMs"])

TRACES = _obj({
    "traces": _arr(_TRACE),
    "recorder": _obj({
        "capacity": _INT, "retained": _INT, "pinned": _INT,
        "recorded": _INT, "pinnedTotal": _INT, "exportedPins": _INT,
        "sampledOut": _INT,
    }),
    "version": _INT,
}, required=["traces", "version"])

MESSAGE = _obj({"message": _STR, "version": _INT},
               required=["message", "version"])

ADMIN = _obj({
    "selfHealing": _obj({}, extra=True), "version": _INT,
}, required=["version"])

#: 202 body while an async operation is still running
ASYNC_PROGRESS = _obj({
    "progress": _arr(_obj({
        "operation": _STR, "status": _STR,
    }, required=["operation"])),
    "version": _INT,
}, required=["progress", "version"])

#: 202 body when two-step verification parks a POST
REVIEW_PARKED = _obj({
    "reviewResult": _REVIEW_REQUEST, "version": _INT,
}, required=["reviewResult", "version"])

ERROR = _obj({"errorMessage": _STR, "version": _INT},
             required=["errorMessage", "version"])

#: 429 body when the device-time scheduler rejects at a class queue cap
#: (the same hint also rides the `Retry-After` response header)
RATE_LIMITED = _obj({
    "errorMessage": _STR,
    "retryAfterSeconds": _NUM,
    "version": _INT,
}, required=["errorMessage", "retryAfterSeconds", "version"])

#: endpoint → JSON Schema of the 200 response body
ENDPOINT_SCHEMAS: Dict[str, dict] = {
    "STATE": STATE,
    "KAFKA_CLUSTER_STATE": KAFKA_CLUSTER_STATE,
    "LOAD": BROKER_STATS,
    "PARTITION_LOAD": PARTITION_LOAD,
    "PROPOSALS": OPTIMIZATION_RESULT,
    "USER_TASKS": USER_TASKS,
    "REVIEW_BOARD": REVIEW_BOARD,
    "REVIEW": REVIEW_BOARD,
    "BOOTSTRAP": MESSAGE,
    "TRAIN": MESSAGE,
    "STOP_PROPOSAL_EXECUTION": MESSAGE,
    "PAUSE_SAMPLING": MESSAGE,
    "RESUME_SAMPLING": MESSAGE,
    "ADMIN": ADMIN,
    "REBALANCE": OPTIMIZATION_RESULT,
    "ADD_BROKER": OPTIMIZATION_RESULT,
    "REMOVE_BROKER": OPTIMIZATION_RESULT,
    "DEMOTE_BROKER": OPTIMIZATION_RESULT,
    "FIX_OFFLINE_REPLICAS": OPTIMIZATION_RESULT,
    "TOPIC_CONFIGURATION": OPTIMIZATION_RESULT,
    "SCENARIOS": SCENARIOS,
    "FLEET": FLEET,
    "TRACES": TRACES,
}

#: non-200 body schemas by meaning
AUX_SCHEMAS: Dict[str, dict] = {
    "async_progress_202": ASYNC_PROGRESS,
    "review_parked_202": REVIEW_PARKED,
    "rate_limited_429": RATE_LIMITED,
    "error": ERROR,
}


def document() -> dict:
    """The full schema artifact as one JSON document."""
    from cruise_control_tpu.scenario.spec import SCENARIOS_REQUEST_SCHEMA
    return {
        "$schema": "https://json-schema.org/draft/2020-12/schema",
        "title": "cruise_control_tpu REST response schemas",
        "endpoints": ENDPOINT_SCHEMAS,
        "aux": AUX_SCHEMAS,
        # endpoints that take a JSON request BODY publish its schema too
        "requests": {"SCENARIOS": SCENARIOS_REQUEST_SCHEMA},
    }


if __name__ == "__main__":
    print(json.dumps(document(), indent=2, sort_keys=True))
