"""OpenMetrics rendering of the sensor registries (`/metrics`).

The registries stay exactly what they were (utils/metrics.py JSON
through the STATE endpoint); this module renders the same sensors as an
OpenMetrics/Prometheus text page.  Naming goes through the ONE canonical
mapping in utils/metrics.py (`openmetrics_sensor`): internal
`sensor-name` forms become `cc_tpu_sensor_name`, and the fleet's
`cluster.<id>.<sensor>` export tagging becomes a proper
`{cluster="<id>"}` label so one scrape sees every tenant as labeled
series of the same family instead of N differently-named metrics.

Type mapping:

* counter  -> `<name>_total` counter
* meter    -> `<name>_total` counter + `<name>_rate` gauge (recent)
* timer    -> `<name>_count` / `_mean_seconds` / `_max_seconds` /
              `_p99_seconds` gauges
* histogram-> a real histogram family: cumulative `_bucket{le=...}`,
              `_sum`, `_count`
* gauge    -> gauge (a broken gauge exports no sample, never garbage)
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from cruise_control_tpu.utils.metrics import openmetrics_sensor

#: the content type Prometheus scrapes negotiate for OpenMetrics
CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"


def _escape(value) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"')


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v) -> str:
    if v is None:
        return "NaN"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Family:
    __slots__ = ("name", "kind", "samples")

    def __init__(self, name: str, kind: str) -> None:
        self.name = name
        self.kind = kind
        #: (sample suffix, labels, value)
        self.samples: List[Tuple[str, Dict[str, str], object]] = []


def _families_of(sensors: Dict[str, dict]) -> List[_Family]:
    fams: Dict[str, _Family] = {}

    def fam(name: str, kind: str) -> _Family:
        f = fams.get(name)
        if f is None:
            f = fams[name] = _Family(name, kind)
        return f

    for raw_name in sorted(sensors):
        data = sensors[raw_name]
        if not isinstance(data, dict):
            continue
        name, labels = openmetrics_sensor(raw_name)
        kind = data.get("type")
        if kind == "counter":
            fam(name, "counter").samples.append(
                ("_total", labels, data.get("count", 0)))
        elif kind == "meter":
            fam(name, "counter").samples.append(
                ("_total", labels, data.get("count", 0)))
            fam(name + "_rate", "gauge").samples.append(
                ("", labels, data.get("recentRate", 0.0)))
        elif kind == "timer":
            fam(name + "_count", "gauge").samples.append(
                ("", labels, data.get("count", 0)))
            for key, suffix in (("meanMs", "_mean_seconds"),
                                ("maxMs", "_max_seconds"),
                                ("p99Ms", "_p99_seconds")):
                if key in data:
                    fam(name + suffix, "gauge").samples.append(
                        ("", labels, data[key] / 1e3))
        elif kind == "histogram":
            f = fam(name + "_seconds", "histogram")
            buckets = data.get("buckets", {})
            for le, count in buckets.items():
                f.samples.append(("_bucket",
                                  {**labels, "le": str(le)}, count))
            f.samples.append(("_sum", labels, data.get("sum", 0.0)))
            f.samples.append(("_count", labels, data.get("count", 0)))
        elif kind == "gauge":
            value = data.get("value")
            if value is not None:
                fam(name, "gauge").samples.append(("", labels, value))
            else:
                # the family still announces itself so a scrape knows
                # the sensor exists even while its callable is broken
                fam(name, "gauge")
        else:
            # unknown sensor shape: export what we can as a gauge
            value = data.get("value", data.get("count"))
            if value is not None:
                fam(name, "gauge").samples.append(("", labels, value))
    return [fams[k] for k in sorted(fams)]


def render_openmetrics(sensors: Dict[str, dict]) -> str:
    """One OpenMetrics page from a registry JSON (a
    `MetricRegistry.to_json()` dict, or the fleet's `sensors_json()`
    with its `cluster.<id>.` tagged keys)."""
    lines: List[str] = []
    for family in _families_of(sensors):
        lines.append(f"# TYPE {family.name} {family.kind}")
        for suffix, labels, value in family.samples:
            lines.append(f"{family.name}{suffix}{_fmt_labels(labels)} "
                         f"{_fmt_value(value)}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def render_for(cc, fleet=None) -> str:
    """The `/metrics` page for a server: the fleet's tagged union when
    serving a fleet (per-tenant series labeled `cluster=`), the single
    facade's registry otherwise."""
    if fleet is not None:
        return render_openmetrics(fleet.sensors_json())
    return render_openmetrics(cc.metrics.to_json())
