"""Request-scoped solve tracing.

A `TraceContext` (trace + parent span id) is minted at the REST
transport (api/server.py) for every solve-bearing request — or by the
facade itself for solves with no request behind them (the precompute
loop, detector self-healing) — and propagated by contextvar on the
minting thread, by explicit capture across thread hops (the USER_TASKS
pool wraps the operation with `activate`, the device-time scheduler
carries it on `SolveJob.trace`).  Everything below the facade records
spans against whatever context is active: the scheduler's queue
wait/dispatch/fold/preemption, each degradation-ladder rung attempt,
model materialization (store hit / fast-forward / rebuild), progcache
consults, and the solver's single end-of-solve instrument fetch — so
one tree answers "where did this request's 2.3 s go" across all six
runtime layers.

Design constraints (pinned in tests/test_obs.py):

* **always-on, bounded** — a span is two `time.time()` reads and one
  list append under the trace's lock; spans are capped per trace
  (`Trace.MAX_SPANS`, overflow counted, never an error);
* **zero device cost** — tracing never calls into jax: the K=1
  scheduled solve stays byte-identical to inline with the SAME
  `jax.device_get` count whether tracing is on or off;
* **no package dependencies** — like sched/runtime.py, this module
  imports nothing from the package (obs.recorder only), so the
  optimizer, the scheduler, the store and the cache can all hook in
  without cycles.

Span construction goes through the helpers here ONLY — `span()`,
`record_span()`, `event()` — never by instantiating `Span`/`SpanRecord`
elsewhere (tools/lint.py trace rule): the helpers are what keep
parenting, capping and cross-thread activation coherent.
"""
from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import json
import logging
import threading
import time as _time
import uuid as _uuid
from typing import Callable, Dict, List, NamedTuple, Optional

LOG = logging.getLogger(__name__)

#: structured JSON trace log (obs.trace.log.enabled): one line per
#: finished trace, logger name `traceLogger` so deployments route it to
#: its own file exactly like the NCSA access log
TRACE_LOG = logging.getLogger("traceLogger")

#: outcome precedence, worst first — a trace that both degraded and was
#: preempted reports "degraded".  "rejected" is queue-cap backpressure
#: (HTTP 429): visible in the ring, but NOT pinned — a rejection storm
#: must not flush the genuinely failed/degraded traces the recorder
#: exists to preserve (obs/recorder.PINNED_OUTCOMES).
OUTCOME_ORDER = ("failed", "degraded", "fallback", "preempted",
                 "rejected", "ok")

_ENABLED = True
_TRACE_LOG_ENABLED = False
#: flight-recorder sampling under load (obs.trace.sample.rate): the
#: fraction of OK traces handed to the recorder.  Non-ok outcomes
#: (failed/degraded/fallback/preempted/rejected) are ALWAYS kept — at
#: load-harness rates the ring churns in seconds, and sampling must
#: thin the healthy wash, never the incident evidence.  The keep/drop
#: decision hashes the trace id, so a given trace's fate is
#: deterministic and reproducible.
_SAMPLE_RATE = 1.0
_CONFIG_LOCK = threading.Lock()


def configure(enabled: Optional[bool] = None,
              trace_log_enabled: Optional[bool] = None,
              sample_rate: Optional[float] = None) -> None:
    """Process-wide switches (obs.tracing.enabled /
    obs.trace.log.enabled / obs.trace.sample.rate); None leaves a
    switch as found."""
    global _ENABLED, _TRACE_LOG_ENABLED, _SAMPLE_RATE
    with _CONFIG_LOCK:
        if enabled is not None:
            _ENABLED = bool(enabled)
        if trace_log_enabled is not None:
            _TRACE_LOG_ENABLED = bool(trace_log_enabled)
        if sample_rate is not None:
            _SAMPLE_RATE = min(1.0, max(0.0, float(sample_rate)))


def enabled() -> bool:
    return _ENABLED


def sample_rate() -> float:
    return _SAMPLE_RATE


def _sampled_in(trace_id: str) -> bool:
    """Deterministic keep decision for an OK trace: the trace id (16
    random hex chars) hashes to a point in [0, 1) compared against the
    sample rate — no RNG state, so replaying a run reproduces exactly
    which traces the recorder kept."""
    rate = _SAMPLE_RATE
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return (int(trace_id[:8], 16) / float(0x100000000)) < rate


@dataclasses.dataclass
class SpanRecord:
    """One FINISHED span.  Never constructed outside this module (lint
    trace rule) — use `span()` / `record_span()`."""

    span_id: int
    parent_id: Optional[int]
    name: str
    start_s: float
    end_s: float
    tags: Dict[str, object] = dataclasses.field(default_factory=dict)


class Trace:
    """One request's span tree plus its outcome flags.  Thread-safe:
    spans arrive from the REST thread, the USER_TASKS pool worker and
    the scheduler dispatch thread of the same solve."""

    #: span cap per trace: a runaway instrumentation loop must degrade
    #: to dropped spans (counted), never to unbounded memory
    MAX_SPANS = 512

    def __init__(self, name: str, tags: Optional[dict] = None) -> None:
        self.trace_id = _uuid.uuid4().hex[:16]
        self.name = name
        self.tags: Dict[str, object] = dict(tags or {})
        self.started_s = _time.time()
        self.ended_s: Optional[float] = None
        self.dropped_spans = 0
        self._lock = threading.Lock()
        self._spans: List[SpanRecord] = []
        self._events: List[dict] = []
        self._flags: set = set()
        self._next_id = 1
        self.root_id = 0        # the root span always exists, id 0

    # -- span bookkeeping ----------------------------------------------
    def new_span_id(self) -> Optional[int]:
        with self._lock:
            if len(self._spans) >= self.MAX_SPANS:
                self.dropped_spans += 1
                return None
            sid = self._next_id
            self._next_id += 1
            return sid

    def add_span(self, record: SpanRecord) -> None:
        with self._lock:
            if len(self._spans) < self.MAX_SPANS:
                self._spans.append(record)
            else:
                self.dropped_spans += 1

    def add_event(self, span_id: Optional[int], name: str,
                  tags: dict) -> None:
        with self._lock:
            if len(self._events) < self.MAX_SPANS:
                self._events.append({"spanId": span_id, "name": name,
                                     "atS": _time.time(), **tags})

    def mark(self, flag: str) -> None:
        """Set an outcome flag ("failed", "degraded", "fallback",
        "preempted"); the worst one wins (OUTCOME_ORDER)."""
        with self._lock:
            self._flags.add(flag)

    @property
    def outcome(self) -> str:
        with self._lock:
            for o in OUTCOME_ORDER:
                if o in self._flags:
                    return o
            return "ok"

    # -- rendering -----------------------------------------------------
    def to_json(self) -> dict:
        with self._lock:
            spans = list(self._spans)
            events = list(self._events)
            dropped = self.dropped_spans
        ended = self.ended_s if self.ended_s is not None else _time.time()
        by_parent: Dict[Optional[int], List[SpanRecord]] = {}
        for s in spans:
            by_parent.setdefault(s.parent_id, []).append(s)
        ev_by_span: Dict[Optional[int], List[dict]] = {}
        for e in events:
            ev_by_span.setdefault(e["spanId"], []).append(
                {k: v for k, v in e.items() if k != "spanId"})

        def node(span_id: int, name: str, start: float, end: float,
                 tags: dict) -> dict:
            out = {
                "spanId": span_id,
                "name": name,
                "startMs": round(start * 1000.0, 3),
                "durationMs": round((end - start) * 1000.0, 3),
            }
            if tags:
                out["tags"] = dict(tags)
            evs = ev_by_span.get(span_id)
            if evs:
                out["events"] = evs
            children = [node(c.span_id, c.name, c.start_s, c.end_s,
                             c.tags)
                        for c in sorted(by_parent.get(span_id, []),
                                        key=lambda s: (s.start_s,
                                                       s.span_id))]
            # orphans (parent span hit the cap and was dropped) re-root
            # under the root so they stay visible
            if span_id == self.root_id:
                known = {s.span_id for s in spans} | {self.root_id}
                children += [node(c.span_id, c.name, c.start_s, c.end_s,
                                  c.tags)
                             for c in spans
                             if c.parent_id not in known]
            if children:
                out["children"] = children
            return out

        return {
            "traceId": self.trace_id,
            "name": self.name,
            "outcome": self.outcome,
            "tags": dict(self.tags),
            "startMs": round(self.started_s * 1000.0, 3),
            "durationMs": round((ended - self.started_s) * 1000.0, 3),
            "numSpans": len(spans) + 1,
            "droppedSpans": dropped,
            "root": node(self.root_id, self.name, self.started_s, ended,
                         self.tags),
        }


class TraceContext(NamedTuple):
    """What crosses a thread hop: the trace plus the span to parent
    under.  Minted at the REST transport; `SolveJob.trace` carries it to
    the scheduler's dispatch thread."""

    trace: Trace
    span_id: int

    @property
    def trace_id(self) -> str:
        return self.trace.trace_id


_CURRENT: contextvars.ContextVar[Optional[TraceContext]] = \
    contextvars.ContextVar("cc_tpu_trace", default=None)


class _ActiveSpan:
    """Handle yielded by `span()` while the span is open."""

    __slots__ = ("trace", "span_id", "parent_id", "name", "start_s",
                 "tags")

    def __init__(self, trace: Trace, span_id: int,
                 parent_id: Optional[int], name: str,
                 tags: dict) -> None:
        self.trace = trace
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start_s = _time.time()
        self.tags = tags

    def set_tag(self, key: str, value) -> None:
        self.tags[key] = value

    def event(self, name: str, **tags) -> None:
        self.trace.add_event(self.span_id, name, tags)


# ---------------------------------------------------------------------------
# context accessors
# ---------------------------------------------------------------------------
def current() -> Optional[Trace]:
    ctx = _CURRENT.get()
    return ctx.trace if ctx is not None else None


def current_context() -> Optional[TraceContext]:
    return _CURRENT.get()


def current_trace_id() -> Optional[str]:
    ctx = _CURRENT.get()
    return ctx.trace.trace_id if ctx is not None else None


@contextlib.contextmanager
def activate(ctx: Optional[TraceContext]):
    """Make `ctx` the current trace context for the duration — the
    cross-thread half of propagation (pool workers, the scheduler
    dispatch thread).  None is a valid scope (no-op)."""
    if ctx is None:
        yield
        return
    token = _CURRENT.set(ctx)
    try:
        yield
    finally:
        _CURRENT.reset(token)


# ---------------------------------------------------------------------------
# trace lifecycle
# ---------------------------------------------------------------------------
def start(name: str, **tags) -> Optional[Trace]:
    """Mint a trace and make it current (root span id 0).  Returns None
    when tracing is disabled."""
    if not _ENABLED:
        return None
    trace = Trace(name, tags)
    _CURRENT.set(TraceContext(trace, trace.root_id))
    return trace


def start_detached(name: str, **tags) -> Optional[Trace]:
    """Mint a trace WITHOUT touching the current thread's context — for
    transports that hand the trace to a worker thread (`activate` +
    `finishing`)."""
    if not _ENABLED:
        return None
    return Trace(name, tags)


def finish(trace: Optional[Trace],
           error: Optional[BaseException] = None) -> None:
    """End a trace: stamp the end time, fold in a terminal error, hand
    the finished tree to the flight recorder, and (when
    obs.trace.log.enabled) emit one structured JSON log line."""
    if trace is None:
        return
    # a finished trace must not linger as the thread's current context
    # (the next solve on this thread would append spans to a dead,
    # already-recorded trace instead of minting its own)
    ctx = _CURRENT.get()
    if ctx is not None and ctx.trace is trace:
        _CURRENT.set(None)
    trace.ended_s = _time.time()
    if error is not None:
        # an exception class may declare its own outcome (duck-typed so
        # this module keeps zero package dependencies): QueueFullError
        # sets trace_outcome="rejected" — backpressure, not failure
        trace.mark(getattr(error, "trace_outcome", None) or "failed")
        trace.tags.setdefault("error",
                              f"{type(error).__name__}: {error}")
    from cruise_control_tpu.obs import recorder as _recorder
    if _TRACE_LOG_ENABLED:
        # the durable trace log sees EVERY finished trace — sampling
        # scopes the flight recorder only (obs.trace.sample.rate docs);
        # an audit stream that silently thinned with the ring would be
        # a lie
        try:
            TRACE_LOG.info("%s", json.dumps(trace.to_json(),
                                            sort_keys=True))
        except (TypeError, ValueError) as exc:
            LOG.warning("trace %s not JSON-serializable: %s",
                        trace.trace_id, exc)
    if trace.outcome == "ok" and not _sampled_in(trace.trace_id):
        # sampled out: the recorder counts the drop so operators can
        # tell "quiet ring" from "thinned ring"; non-ok traces never
        # reach this branch (outcome check above)
        _recorder.get_recorder().record_sampled_out()
        return
    _recorder.get_recorder().record(trace)


def finishing(trace: Optional[Trace],
              op: Callable[[], object]) -> Callable[[], object]:
    """Wrap `op` so it runs under `trace` (activated on whatever thread
    executes it) and finishes the trace when it returns or raises — the
    USER_TASKS-pool propagation shim."""
    if trace is None:
        return op
    ctx = TraceContext(trace, trace.root_id)

    def run():
        with activate(ctx):
            try:
                result = op()
            except BaseException as exc:
                finish(trace, error=exc)
                raise
            finish(trace)
            return result
    return run


@contextlib.contextmanager
def solve_trace(name: str, **tags):
    """The facade's entry helper: reuse the active trace (a REST-minted
    request context) or mint-and-finish one around the solve (the
    precompute loop, detector heals — solves with no request behind
    them).  Yields the trace (or None when tracing is off)."""
    existing = current()
    if existing is not None and existing.ended_s is None:
        for k, v in tags.items():
            existing.tags.setdefault(k, v)
        yield existing
        return
    trace = start_detached(name, **tags)
    if trace is None:
        yield None
        return
    token = _CURRENT.set(TraceContext(trace, trace.root_id))
    try:
        yield trace
    except BaseException as exc:
        finish(trace, error=exc)
        raise
    else:
        finish(trace)
    finally:
        # restore the PREVIOUS context (not just clear): a stale
        # finished trace from this thread's past must not shadow the
        # next solve
        _CURRENT.reset(token)


# ---------------------------------------------------------------------------
# span recording
# ---------------------------------------------------------------------------
@contextlib.contextmanager
def span(name: str, **tags):
    """Open a child span of the current context for the duration.
    Yields the active-span handle (set_tag/event), or None outside a
    trace — callers never need to guard."""
    ctx = _CURRENT.get()
    if ctx is None or not _ENABLED:
        yield None
        return
    trace = ctx.trace
    sid = trace.new_span_id()
    if sid is None:
        yield None
        return
    handle = _ActiveSpan(trace, sid, ctx.span_id, name, dict(tags))
    token = _CURRENT.set(TraceContext(trace, sid))
    try:
        yield handle
    except BaseException as exc:
        handle.tags.setdefault("error", f"{type(exc).__name__}: {exc}")
        raise
    finally:
        _CURRENT.reset(token)
        trace.add_span(SpanRecord(sid, handle.parent_id, name,
                                  handle.start_s, _time.time(),
                                  handle.tags))


def record_span(name: str, start_s: float, end_s: float,
                ctx: Optional[TraceContext] = None, **tags) -> None:
    """Append an already-timed span (queue waits, profiler segments)
    under `ctx` (default: the current context).  No-op without one."""
    if not _ENABLED:
        return
    ctx = ctx if ctx is not None else _CURRENT.get()
    if ctx is None:
        return
    sid = ctx.trace.new_span_id()
    if sid is None:
        return
    ctx.trace.add_span(SpanRecord(sid, ctx.span_id, name, start_s,
                                  end_s, dict(tags)))


def event(name: str, ctx: Optional[TraceContext] = None, **tags) -> None:
    """Attach an instantaneous event to the current span (or `ctx`)."""
    if not _ENABLED:
        return
    ctx = ctx if ctx is not None else _CURRENT.get()
    if ctx is None:
        return
    ctx.trace.add_event(ctx.span_id, name, tags)


def mark(flag: str, ctx: Optional[TraceContext] = None) -> None:
    """Set an outcome flag on the current (or given) trace."""
    ctx = ctx if ctx is not None else _CURRENT.get()
    if ctx is not None:
        ctx.trace.mark(flag)


def set_tag(key: str, value, ctx: Optional[TraceContext] = None) -> None:
    ctx = ctx if ctx is not None else _CURRENT.get()
    if ctx is not None:
        ctx.trace.tags[key] = value
