"""Flight recorder: the last N completed solve traces, incident-proof.

A fixed-size ring holds every completed trace (obs/trace.py hands them
over on `finish`).  Traces whose outcome is anything but "ok" — failed,
degraded, fallback, preempted — are additionally PINNED: they survive
ring eviction until a TRACES query actually returns them (exported), so
an incident's evidence cannot be washed out by the healthy traffic that
follows it.  `dump()` writes the whole recorder state as one structured
JSON log line; the facade calls it when a SolverDegraded anomaly fires,
so incidents self-capture without an operator on the box.

Queryable through the TRACES REST endpoint (`?trace_id=`, `?cluster=`,
`?outcome=degraded`, `?limit=`) and `tools/trace_dump.py`.

Like the segment profiler, the recorder is a process-wide singleton
(`get_recorder()`); under fleet serving every tenant records into the
same ring with its traces tagged `cluster=<tenant id>`, which is the
truth: there IS one device and one request stream.
"""
from __future__ import annotations

import json
import logging
import statistics
import threading
from typing import Dict, List, Optional

LOG = logging.getLogger(__name__)

#: the incident dump goes to its own logger so deployments can route it
#: to durable storage separately from the chatty service log
DUMP_LOG = logging.getLogger("flightRecorder")

DEFAULT_CAPACITY = 256
DEFAULT_MAX_PINNED = 256

#: outcomes pinned past ring eviction until exported.  "rejected"
#: (queue-cap backpressure, HTTP 429) is deliberately absent: a
#: rejection storm is hundreds of traces a minute, and pinning them
#: would FIFO-flush the real incident evidence
PINNED_OUTCOMES = frozenset(("failed", "degraded", "fallback",
                             "preempted"))


class FlightRecorder:
    """See module docstring.  Stores finished traces as JSON dicts (the
    tree is assembled once at record time; queries never touch live
    Trace objects)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 max_pinned: int = DEFAULT_MAX_PINNED) -> None:
        self.capacity = max(1, int(capacity))
        self.max_pinned = max(0, int(max_pinned))
        self._lock = threading.Lock()
        #: ring of completed traces, oldest first
        self._ring: List[dict] = []
        #: trace_id -> pinned trace (bad outcomes awaiting export)
        self._pinned: Dict[str, dict] = {}
        #: insertion order of pins (oldest evicted at max_pinned)
        self._pin_order: List[str] = []
        self.recorded = 0
        self.pinned_total = 0
        self.exported_pins = 0
        #: OK traces dropped by obs.trace.sample.rate before reaching
        #: the ring (obs/trace.py `_sampled_in`) — distinguishes a
        #: quiet ring from a sampling-thinned one
        self.sampled_out = 0

    def record_sampled_out(self) -> None:
        with self._lock:
            self.sampled_out += 1

    # ------------------------------------------------------------------
    def record(self, trace) -> None:
        """Accept a finished obs.trace.Trace (or a pre-rendered dict)."""
        doc = trace if isinstance(trace, dict) else trace.to_json()
        with self._lock:
            self.recorded += 1
            self._ring.append(doc)
            if len(self._ring) > self.capacity:
                del self._ring[:len(self._ring) - self.capacity]
            if doc.get("outcome", "ok") in PINNED_OUTCOMES \
                    and self.max_pinned:
                tid = doc.get("traceId", "")
                if tid and tid not in self._pinned:
                    self._pinned[tid] = doc
                    self._pin_order.append(tid)
                    self.pinned_total += 1
                    while len(self._pin_order) > self.max_pinned:
                        old = self._pin_order.pop(0)
                        self._pinned.pop(old, None)

    # ------------------------------------------------------------------
    def query(self, trace_id: Optional[str] = None,
              cluster: Optional[str] = None,
              outcome: Optional[str] = None,
              limit: Optional[int] = None,
              export: bool = True,
              since_ms: Optional[float] = None,
              min_duration_ms: Optional[float] = None) -> List[dict]:
        """Matching traces, newest first.  Pinned traces a query RETURNS
        count as exported and drop their pin (they remain in the ring
        subject to normal eviction); pass export=False to peek.

        `since_ms` keeps only traces that STARTED at/after the given
        epoch-milliseconds; `min_duration_ms` only traces at least that
        slow — the drill filters (`?since=`, `?min_duration_ms=` on the
        TRACES endpoint, `tools/trace_dump.py --follow`) so watching a
        loaded server never pages the whole ring."""
        with self._lock:
            seen = set()
            docs: List[dict] = []
            # pinned first (they may have been evicted from the ring),
            # then the ring newest-first
            for tid in reversed(self._pin_order):
                docs.append(self._pinned[tid])
                seen.add(tid)
            for doc in reversed(self._ring):
                tid = doc.get("traceId", "")
                if tid not in seen:
                    seen.add(tid)
                    docs.append(doc)
        out = []
        for doc in docs:
            if trace_id is not None \
                    and doc.get("traceId") != trace_id:
                continue
            if cluster is not None \
                    and doc.get("tags", {}).get("cluster") != cluster:
                continue
            if outcome is not None and doc.get("outcome") != outcome:
                continue
            if since_ms is not None \
                    and doc.get("startMs", 0.0) < since_ms:
                continue
            if min_duration_ms is not None \
                    and doc.get("durationMs", 0.0) < min_duration_ms:
                continue
            out.append(doc)
            if limit is not None and len(out) >= max(1, limit):
                break
        if export and out:
            with self._lock:
                for doc in out:
                    tid = doc.get("traceId", "")
                    if tid in self._pinned:
                        self._pinned.pop(tid, None)
                        self._pin_order.remove(tid)
                        self.exported_pins += 1
        return out

    def get(self, trace_id: str) -> Optional[dict]:
        hits = self.query(trace_id=trace_id, limit=1)
        return hits[0] if hits else None

    # ------------------------------------------------------------------
    def dump(self, reason: str = "", active: Optional[dict] = None
             ) -> int:
        """Write the recorder state (pinned + ring) as one structured
        JSON log line — called on SolverDegraded anomalies so the
        incident's traces are captured even if nobody queries TRACES.
        `active` is the IN-FLIGHT trace of the solve that triggered the
        dump (its partial tree): the degradation fires mid-solve,
        before that trace reaches the ring, so without it the dump
        would exclude the very trace it announces.  Returns the number
        of traces dumped; never raises."""
        try:
            with self._lock:
                pinned = [self._pinned[t] for t in self._pin_order]
                recent = list(self._ring[-16:])
            DUMP_LOG.warning("%s", json.dumps({
                "flightRecorderDump": {
                    "reason": reason,
                    "active": active,
                    "pinned": pinned,
                    "recent": recent,
                }}, sort_keys=True, default=str))
            return len(pinned) + len(recent) + (1 if active else 0)
        except Exception as exc:  # noqa: BLE001 - the dump is a
            # best-effort courtesy: it must never mask the anomaly that
            # triggered it
            LOG.warning("flight-recorder dump failed: %s", exc)
            return 0

    # ------------------------------------------------------------------
    def snapshot(self) -> List[dict]:
        """Every retained trace (ring order, oldest first) without
        export side effects — bench.py's trace-summary input."""
        with self._lock:
            seen = {d.get("traceId") for d in self._ring}
            extra = [self._pinned[t] for t in self._pin_order
                     if t not in seen]
            return extra + list(self._ring)

    def to_json(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "retained": len(self._ring),
                "pinned": len(self._pinned),
                "recorded": self.recorded,
                "pinnedTotal": self.pinned_total,
                "exportedPins": self.exported_pins,
                "sampledOut": self.sampled_out,
            }


def phase_summary(traces: List[dict]) -> dict:
    """Per-phase latency attribution over a set of finished traces: the
    slowest and the median trace (by duration), each broken into its
    top-level span durations — what bench.py embeds per BENCH_CONFIG
    mode so every BENCH_r* round carries attribution, not just totals."""
    done = [t for t in traces if t.get("durationMs") is not None]
    if not done:
        return {"numTraces": 0}

    def phases(doc: dict) -> dict:
        out: Dict[str, float] = {}

        def walk(node: dict) -> None:
            for child in node.get("children", []):
                name = child.get("name", "?")
                out[name] = out.get(name, 0.0) + child.get(
                    "durationMs", 0.0)
                walk(child)
        walk(doc.get("root", {}))
        return {k: round(v, 3) for k, v in sorted(out.items())}

    def entry(doc: dict) -> dict:
        return {"traceId": doc.get("traceId"),
                "outcome": doc.get("outcome"),
                "durationMs": doc.get("durationMs"),
                "phasesMs": phases(doc)}

    ordered = sorted(done, key=lambda t: t.get("durationMs", 0.0))
    durations = [t.get("durationMs", 0.0) for t in ordered]
    return {
        "numTraces": len(ordered),
        "p50Ms": round(statistics.median(durations), 3),
        "slowest": entry(ordered[-1]),
        "median": entry(ordered[len(ordered) // 2]),
    }


# ---------------------------------------------------------------------------
# process-wide singleton (same install pattern as utils/profiling.py)
# ---------------------------------------------------------------------------
_ACTIVE: Optional[FlightRecorder] = None
_ACTIVE_LOCK = threading.Lock()


def get_recorder() -> FlightRecorder:
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is None:
            _ACTIVE = FlightRecorder()
        return _ACTIVE


def install(recorder: Optional[FlightRecorder] = None) -> FlightRecorder:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = recorder or FlightRecorder()
        return _ACTIVE


def configure(capacity: Optional[int] = None,
              max_pinned: Optional[int] = None) -> FlightRecorder:
    """Resize the live recorder (obs.flight.recorder.* keys); retained
    traces survive a shrink up to the new capacity."""
    rec = get_recorder()
    with rec._lock:
        if capacity is not None:
            rec.capacity = max(1, int(capacity))
            if len(rec._ring) > rec.capacity:
                del rec._ring[:len(rec._ring) - rec.capacity]
        if max_pinned is not None:
            rec.max_pinned = max(0, int(max_pinned))
            while len(rec._pin_order) > rec.max_pinned:
                old = rec._pin_order.pop(0)
                rec._pinned.pop(old, None)
    return rec
