"""Observability: request-scoped tracing, the flight recorder, and the
OpenMetrics exporter (docs/OBSERVABILITY.md).

* `obs.trace` — TraceContext minted at the REST transport (or by the
  facade for request-less solves) and propagated through the USER_TASKS
  pool and the device-time scheduler; spans for queue wait, ladder rung
  attempts, model materialization and the device instrument fetch.
* `obs.recorder` — fixed-size ring of completed traces with pinned
  retention for failed/degraded/preempted/fallback ones; the TRACES
  endpoint and `tools/trace_dump.py` read it; SolverDegraded anomalies
  dump it.
* `obs.export` — `/metrics` OpenMetrics page over every sensor
  registry, `cluster.<id>.` tagging converted to labels.
* `obs.slo` — per-class latency/error-budget objectives with burn
  rates computed live from the scheduler histograms: STATE `sloStatus`,
  `cc_tpu_slo_*` series, and the SLO_BURN anomaly's math
  (docs/LOADGEN.md).
"""
from cruise_control_tpu.obs import export, recorder, slo, trace

__all__ = ["export", "recorder", "slo", "trace"]
