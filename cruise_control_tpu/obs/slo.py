"""Service-level objectives over the scheduler's per-class histograms.

PR-11 gave every solve a span tree and the scheduler per-class latency
histograms (`sched-wait-hist-<class>`, `sched-device-busy-hist-<class>`,
utils/metrics.Histogram); this module turns those raw distributions into
FIRST-CLASS objectives: per-class latency thresholds plus an error
budget (`slo.<class>.*` config keys), with the **burn rate** — the
fraction of the error budget the last window actually consumed —
computed live from the histograms' cumulative bucket counts.

Two burns per class, deliberately separate (docs/OPERATIONS.md §5 "SLO
burn"):

* **queue-wait burn** (`sched-wait-hist-<class>` vs
  `slo.<class>.queue.wait.ms`) — admission pressure: the device cannot
  keep up with the offered per-class load (shed SCENARIO_SWEEP, raise
  queue caps, add chips);
* **device-time burn** (`sched-device-busy-hist-<class>` vs
  `slo.<class>.latency.ms`) — the solves themselves got slower (ladder
  descent, cache miss storm, model growth).

`burn = (observations over threshold / observations) / error_budget`
over a sliding window of histogram snapshots: 1.0 means the window
consumed its budget exactly; `slo.burn.alert.threshold` (default 2×)
is where the SLO_BURN anomaly fires (detector/slo_burn.py,
notification-only).  Thresholds between bucket boundaries round DOWN
to the nearest boundary, over-counting borderline observations —
conservative by construction; align buckets with thresholds via
`obs.metrics.buckets.<name>` when exactness matters.

Surfaces (acceptance-pinned in tests/test_loadgen.py):

* STATE `sloStatus` block (facade.state, substate `slo`);
* `slo-*` gauges on the facade registry → `cc_tpu_slo_*` series on
  `/metrics`;
* SLO_BURN anomaly through the detector/notifier plane;
* the run-artifact `slo` block the load harness embeds and
  `tools/slo_gate.py` gates on.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time as _time
from typing import Callable, Dict, List, Optional, Tuple

_LOG = logging.getLogger(__name__)

#: SchedulerClass name -> the dashed suffix of its sched-* histograms.
#: Hardcoded strings instead of importing sched.policy: obs/ imports
#: nothing from the package (the same zero-dependency rule as trace.py)
CLASS_SENSOR_SUFFIX = {
    "ANOMALY_HEAL": "anomaly-heal",
    "USER_INTERACTIVE": "user-interactive",
    "PRECOMPUTE": "precompute",
    "SCENARIO_SWEEP": "scenario-sweep",
}

#: status ladder, worst last
STATUS_OK = "ok"
STATUS_BURNING = "burning"      # budget consumed faster than earned
STATUS_BREACH = "breach"        # burn at/over the alert threshold


@dataclasses.dataclass(frozen=True)
class ClassObjective:
    """One scheduler class's objective (config `slo.<class>.*`)."""

    latency_s: float        # device-time threshold (slo.<class>.latency.ms)
    queue_wait_s: float     # admission threshold (slo.<class>.queue.wait.ms)
    error_budget: float     # allowed fraction over threshold

    def to_json(self) -> dict:
        return {"latencyMs": round(self.latency_s * 1e3, 3),
                "queueWaitMs": round(self.queue_wait_s * 1e3, 3),
                "errorBudget": self.error_budget}


#: defaults mirror the config-key defaults in main_config.slo_config_def
#: (direct facade construction — tests, embedders — gets the same
#: objectives the config would hand build_cruise_control)
DEFAULT_OBJECTIVES: Dict[str, ClassObjective] = {
    "ANOMALY_HEAL": ClassObjective(5.0, 1.0, 0.01),
    "USER_INTERACTIVE": ClassObjective(2.0, 0.5, 0.02),
    "PRECOMPUTE": ClassObjective(30.0, 10.0, 0.05),
    "SCENARIO_SWEEP": ClassObjective(60.0, 30.0, 0.05),
}


def over_threshold(hist_json: dict, threshold_s: float) -> Tuple[int, int]:
    """(total observations, observations OVER the threshold) from a
    Histogram.to_json() dict.  The threshold rounds DOWN to the nearest
    bucket boundary, so in-between observations count as over —
    conservative (alarms early, never late)."""
    count = int(hist_json.get("count", 0))
    if not count:
        return 0, 0
    best_le = 0
    for bound_repr, cum in hist_json.get("buckets", {}).items():
        if bound_repr == "+Inf":
            continue
        try:
            bound = float(bound_repr)
        except ValueError:
            continue
        if bound <= threshold_s:
            best_le = max(best_le, int(cum))
    return count, max(0, count - best_le)


class SloEvaluator:
    """Windowed burn rates over a facade's sched-* histograms.

    Snapshots of (count, over-threshold) per class/dimension are taken
    at most every `min_refresh_s` (gauges scrape freely without
    re-walking histograms) and retained for `window_s`; burn is the
    delta between the newest and oldest retained snapshot, so a breach
    ages out of the status once the window rolls past it."""

    def __init__(self, registry,
                 objectives: Optional[Dict[str, ClassObjective]] = None,
                 enabled: bool = True,
                 window_s: float = 300.0,
                 alert_threshold: float = 2.0,
                 min_refresh_s: float = 1.0,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self._registry = registry
        self.objectives = dict(objectives or DEFAULT_OBJECTIVES)
        unknown = set(self.objectives) - set(CLASS_SENSOR_SUFFIX)
        if unknown:
            raise ValueError(f"unknown scheduler classes in SLO "
                             f"objectives: {sorted(unknown)}")
        self.enabled = enabled
        self.window_s = max(1.0, float(window_s))
        self.alert_threshold = max(1.0, float(alert_threshold))
        self._min_refresh_s = max(0.0, float(min_refresh_s))
        self._time = time_fn or _time.time
        self._lock = threading.Lock()
        #: [(t, {"wait.<CLASS>"|"dev.<CLASS>": (count, over)})]
        self._snapshots: List[Tuple[float, Dict[str, Tuple[int, int]]]] = []
        self._last_status: dict = self._empty_status()
        self.evaluations = 0
        self.evaluation_errors = 0

    # ------------------------------------------------------------------
    def _take_snapshot(self) -> Dict[str, Tuple[int, int]]:
        snap: Dict[str, Tuple[int, int]] = {}
        for klass, objective in self.objectives.items():
            suffix = CLASS_SENSOR_SUFFIX[klass]
            for dim, sensor, threshold in (
                    ("wait", f"sched-wait-hist-{suffix}",
                     objective.queue_wait_s),
                    ("dev", f"sched-device-busy-hist-{suffix}",
                     objective.latency_s)):
                hist = self._registry.peek(sensor)
                snap[f"{dim}.{klass}"] = (
                    over_threshold(hist.to_json(), threshold)
                    if hist is not None else (0, 0))
        return snap

    @staticmethod
    def _burn(newest: Tuple[int, int], oldest: Tuple[int, int],
              budget: float) -> Tuple[int, float]:
        """(window observations, burn) between two snapshots."""
        d_count = max(0, newest[0] - oldest[0])
        d_over = max(0, newest[1] - oldest[1])
        if not d_count:
            return 0, 0.0
        bad_fraction = d_over / d_count
        return d_count, bad_fraction / max(budget, 1e-9)

    def _empty_status(self) -> dict:
        return {
            "enabled": self.enabled,
            "windowS": self.window_s,
            "alertThreshold": self.alert_threshold,
            "status": STATUS_OK,
            "worstBurn": 0.0,
            "worstClass": None,
            "classes": {
                klass: {
                    "objective": obj.to_json(),
                    "windowSolves": 0,
                    "queueWaitBurn": 0.0,
                    "deviceTimeBurn": 0.0,
                    "burn": 0.0,
                    "budgetRemaining": 1.0,
                    "status": STATUS_OK,
                } for klass, obj in sorted(self.objectives.items())},
        }

    # ------------------------------------------------------------------
    def evaluate(self, force: bool = False) -> dict:
        """Refresh (rate-limited unless `force`) and return the
        sloStatus block.  Never raises — SLO math must not break STATE
        or a scrape."""
        if not self.enabled:
            return self._empty_status()
        try:
            return self._evaluate(force)
        except Exception as exc:  # noqa: BLE001 - status is telemetry
            self.evaluation_errors += 1
            _LOG.warning("SLO evaluation failed (serving the last "
                         "status): %s: %s", type(exc).__name__, exc)
            return self._last_status

    def _evaluate(self, force: bool) -> dict:
        now = self._time()
        with self._lock:
            fresh = (self._snapshots
                     and now - self._snapshots[-1][0] < self._min_refresh_s)
            if fresh and not force:
                return self._last_status
            self._snapshots.append((now, self._take_snapshot()))
            # retain the window plus ONE older snapshot as the base, so
            # a window that just rolled still has a full-width delta
            cutoff = now - self.window_s
            while (len(self._snapshots) > 2
                   and self._snapshots[1][0] <= cutoff):
                self._snapshots.pop(0)
            newest = self._snapshots[-1][1]
            oldest = self._snapshots[0][1]
            status = self._empty_status()
            worst = (0.0, None)
            for klass, objective in self.objectives.items():
                n_wait, wait_burn = self._burn(
                    newest[f"wait.{klass}"], oldest[f"wait.{klass}"],
                    objective.error_budget)
                n_dev, dev_burn = self._burn(
                    newest[f"dev.{klass}"], oldest[f"dev.{klass}"],
                    objective.error_budget)
                burn = max(wait_burn, dev_burn)
                cls = status["classes"][klass]
                cls.update({
                    "windowSolves": max(n_wait, n_dev),
                    "queueWaitBurn": round(wait_burn, 4),
                    "deviceTimeBurn": round(dev_burn, 4),
                    "burn": round(burn, 4),
                    "budgetRemaining": round(max(0.0, 1.0 - burn), 4),
                    "status": (STATUS_BREACH
                               if burn >= self.alert_threshold
                               else STATUS_BURNING if burn >= 1.0
                               else STATUS_OK),
                })
                if burn > worst[0]:
                    worst = (burn, klass)
            status["worstBurn"] = round(worst[0], 4)
            status["worstClass"] = worst[1]
            status["status"] = (
                STATUS_BREACH if worst[0] >= self.alert_threshold
                else STATUS_BURNING if worst[0] >= 1.0 else STATUS_OK)
            self._last_status = status
            self.evaluations += 1
            return status

    # ------------------------------------------------------------------
    def burn(self, klass: str) -> float:
        """Latest computed burn for one class (refreshes rate-limited)."""
        return float(self.evaluate()["classes"]
                     .get(klass, {}).get("burn", 0.0))

    def status_level(self) -> float:
        """Overall status as a number for the slo-status gauge:
        0 ok, 1 burning, 2 breach."""
        return float({STATUS_OK: 0, STATUS_BURNING: 1,
                      STATUS_BREACH: 2}[self.evaluate()["status"]])

    def attach_metrics(self, registry) -> None:
        """Register the slo-* gauges (→ `cc_tpu_slo_*` on /metrics)."""
        registry.gauge("slo-status", self.status_level)
        registry.gauge("slo-worst-burn",
                       lambda: float(self.evaluate()["worstBurn"]))
        for klass in self.objectives:
            suffix = CLASS_SENSOR_SUFFIX[klass]
            registry.gauge(f"slo-burn-rate-{suffix}",
                           lambda k=klass: self.burn(k))
            registry.gauge(
                f"slo-budget-remaining-{suffix}",
                lambda k=klass: float(
                    self.evaluate()["classes"][k]["budgetRemaining"]))
