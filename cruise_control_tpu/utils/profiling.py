"""Segment-level solve profiler (CC_TPU_PROFILE=1).

VERDICT round 5's #1 missing item: "a segment-by-segment analysis of
which of the 28 s [north solve is] shards (table rounds) vs replicates
(stats, diff)".  This module is the attribution instrument: under
``CC_TPU_PROFILE=1`` the optimizer re-segments the pipeline per goal,
inserts explicit sync points (``jax.block_until_ready``) after every
program, and records one row per segment here; ``table()`` renders the
per-segment table plus the category rollup that answers
shards-vs-replicates directly:

  * ``rounds``      — per-goal table/search rounds (the sharded work:
                      ``[B, S]`` broker-table planes, move/swap kernels)
  * ``leadership``  — leadership-goal rounds/sweeps (``[P, RF]`` planes;
                      replicated today, shardable on the partition axis)
  * ``stats``       — per-goal stats epilogues + violation sweeps
                      (replicated ``[B]``/``[R]`` reductions)
  * ``prebalance``  — the joint pre-pass (+ heal + before-sweep)
  * ``diff``        — final initial→final proposal diff (host side)
  * ``transfer``    — the single end-of-solve instrument fetch

Sync points cost transport latency, and profile mode runs one program
per goal instead of the fused multi-goal segments, so a profiled
wall-clock is NOT comparable to an unprofiled run — the table is for
attribution, not for the headline number.

Trace-structure counters (`trace_count`) are the in-kernel hooks:
`kernels.py` / `leadership.py` / `prebalance.py` / `model/stats.py` call
them while a program is TRACED, so the table can also report how many
round bodies / stats reductions each compiled program contains (tracing
happens once per program; the counts describe program structure, not
per-run execution).
"""
from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time as _time
from typing import Dict, List, Optional

LOG = logging.getLogger(__name__)

#: the opt-in env var (any value but "" / "0" enables profiling)
PROFILE_ENV = "CC_TPU_PROFILE"

#: goal names whose optimization is leadership-dominated ([P, RF]
#: transfer planes / global sweeps rather than [B, S] table rounds)
_LEADERSHIP_GOAL_MARKER = "Leader"


def enabled() -> bool:
    """True when CC_TPU_PROFILE requests segment profiling."""
    return os.environ.get(PROFILE_ENV, "") not in ("", "0")


def category_for_goal(goal_name: str) -> str:
    """Coarse shards-vs-replicates attribution bucket for a goal's
    optimization rounds (its stats epilogue is always ``stats``)."""
    if (_LEADERSHIP_GOAL_MARKER in goal_name
            or goal_name == "PreferredLeaderElectionGoal"):
        return "leadership"
    return "rounds"


@dataclasses.dataclass
class SegmentRecord:
    name: str
    category: str
    seconds: float
    meta: Dict[str, object] = dataclasses.field(default_factory=dict)


class SegmentProfiler:
    """Collects SegmentRecords across one or more solves; thread-safe
    (the facade's precompute thread may race request-path solves)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.records: List[SegmentRecord] = []
        #: program-structure counters filled at trace time (trace_count)
        self.trace_counts: Dict[str, int] = {}
        #: LIFETIME per-category seconds — unlike `records` (bounded,
        #: trimmed) and reset(), this only grows, so publish() deltas
        #: stay monotonic across record-buffer wraps and resets
        self._cum_totals: Dict[str, float] = {}
        #: per-category seconds already published to a MetricRegistry
        self._published: Dict[str, float] = {}

    #: bound on retained records: a long-lived facade with
    #: CC_TPU_PROFILE=1 left on records ~2·G+5 segments per precompute
    #: solve forever — without a cap the list (and any table() output)
    #: grows monotonically.  When full, the OLDEST half is dropped, so
    #: the table always covers the most recent solves.
    MAX_RECORDS = 4096

    def record(self, name: str, category: str, seconds: float,
               **meta) -> None:
        with self._lock:
            self.records.append(SegmentRecord(name, category, seconds,
                                              dict(meta)))
            self._cum_totals[category] = (
                self._cum_totals.get(category, 0.0) + seconds)
            if len(self.records) > self.MAX_RECORDS:
                del self.records[:len(self.records) // 2]
        # attach the segment to the active solve span (obs/trace.py):
        # host spans and device segment attribution land in ONE tree.
        # Lazy import — utils/ stays importable before obs is; a no-op
        # outside a trace (and profiling itself stays opt-in)
        from cruise_control_tpu.obs import trace as _obs_trace
        now = _time.time()
        _obs_trace.record_span(f"segment:{name}", now - seconds, now,
                               category=category, **meta)
        LOG.info("segment %-42s %-10s %8.0fms%s", name, category,
                 seconds * 1e3,
                 "".join(f" {k}={v}" for k, v in meta.items()))

    def reset(self) -> None:
        """Drop recorded segments (keeps trace counts — program structure
        does not change between a warmup run and the measured run)."""
        with self._lock:
            self.records.clear()

    def note_trace(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.trace_counts[key] = self.trace_counts.get(key, 0) + n

    def _category_totals_locked(self) -> Dict[str, float]:
        totals: Dict[str, float] = {}
        for r in self.records:
            totals[r.category] = totals.get(r.category, 0.0) + r.seconds
        return totals

    def category_totals(self) -> Dict[str, float]:
        with self._lock:
            return self._category_totals_locked()

    def table(self) -> str:
        """The per-segment table + category rollup, ready to print."""
        with self._lock:
            records = list(self.records)
            traces = dict(self.trace_counts)
        lines = ["segment                                      category   "
                 "    wall",
                 "-" * 68]
        for r in records:
            meta = "".join(f"  {k}={v}" for k, v in sorted(r.meta.items()))
            lines.append(f"{r.name:<44} {r.category:<10} {r.seconds:7.3f}s"
                         f"{meta}")
        total = sum(r.seconds for r in records)
        lines.append("-" * 68)
        for cat, secs in sorted(self.category_totals().items(),
                                key=lambda kv: -kv[1]):
            pct = 100.0 * secs / total if total else 0.0
            lines.append(f"{'total ' + cat:<44} {'':10} {secs:7.3f}s"
                         f"  ({pct:.0f}%)")
        lines.append(f"{'total':<44} {'':10} {total:7.3f}s")
        if traces:
            lines.append("")
            lines.append("program structure (bodies traced per compile):")
            for key, n in sorted(traces.items()):
                lines.append(f"  {key}: {n}")
        return "\n".join(lines)

    def publish(self, registry) -> None:
        """Push per-category time ACCRUED SINCE THE LAST PUBLISH into a
        utils.metrics.MetricRegistry as `segment-profile-<cat>-timer`
        sensors (the facade calls this after each profiled solve, so the
        STATE endpoint's `sensors` substate exposes the attribution).

        Deltas derive from the lifetime `_cum_totals` (monotonic even
        when the bounded `records` buffer trims or reset() runs), and
        the read-compare-store happens under one lock hold so concurrent
        publishes (precompute thread racing a request path) neither
        double-count nor lose an interval; only the registry update runs
        outside the lock."""
        with self._lock:
            totals = dict(self._cum_totals)
            deltas = {cat: secs - self._published.get(cat, 0.0)
                      for cat, secs in totals.items()}
            self._published = totals
        for cat, delta in deltas.items():
            if delta > 0:
                registry.update_timer(f"segment-profile-{cat}-timer",
                                      delta)

    def to_json(self) -> dict:
        with self._lock:
            # NB: must not call category_totals() here — self._lock is
            # not reentrant (that deadlocked --json runs once)
            return {
                "segments": [dataclasses.asdict(r) for r in self.records],
                "category_totals_s": self._category_totals_locked(),
                "trace_counts": dict(self.trace_counts),
            }


#: process-wide active profiler (None when not installed); the optimizer
#: records into it when CC_TPU_PROFILE is set, installing one on demand
#: so a bare `CC_TPU_PROFILE=1 python bench.py` needs no extra wiring
_ACTIVE: Optional[SegmentProfiler] = None
_ACTIVE_LOCK = threading.Lock()


def active() -> Optional[SegmentProfiler]:
    return _ACTIVE


def install(profiler: Optional[SegmentProfiler] = None) -> SegmentProfiler:
    """Install (and return) the process-wide profiler."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = profiler or SegmentProfiler()
        return _ACTIVE


def uninstall() -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = None


def ensure_active() -> SegmentProfiler:
    """The active profiler, installing one if none is — check and
    install under ONE lock hold, so concurrent solves (facade precompute
    racing a request path) agree on a single profiler instead of the
    second install orphaning the first's records."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is None:
            _ACTIVE = SegmentProfiler()
        return _ACTIVE


def trace_count(key: str, n: int = 1) -> None:
    """Trace-time structure hook for kernels/stats: a no-op unless
    profiling is enabled AND a profiler is installed (zero overhead on
    the production path — one dict lookup per TRACE, never per run)."""
    if _ACTIVE is not None and enabled():
        _ACTIVE.note_trace(key, n)
