"""Durable-write helpers: the ONE place host state reaches disk.

Every persistent store in the framework — the compiled-program cache
(parallel/progcache.py), the broker-failure table
(detector/broker_failure.py), the metric-sample store
(monitor/sampling/sample_store.py) and the executor journal
(executor/journal.py) — shares the same two disciplines:

* **atomic publication**: `atomic_write` writes a temp file NEXT TO the
  target and `os.replace`s it into place, so a reader (or a process
  that crashes mid-write) can never observe a torn file; concurrent
  writers each publish a complete file and the last rename wins;
* **CRC-framed append logs**: `crc_frame`/`read_crc_json` give
  append-only JSONL logs a per-record crc32 so replay can detect a
  torn tail (the record a dying process half-wrote) and truncate at
  the FIRST bad record instead of trusting garbage.

tools/lint.py enforces the funnel (durable-write rule): `open(.., "w")`
/ `os.rename` / `os.replace` outside this module fails `make lint` —
a store that bypasses these helpers silently loses the crash-safety
contract the executor journal depends on.
"""
from __future__ import annotations

import json
import os
import tempfile
import zlib
from typing import IO, Iterable, List, Optional, Tuple


def fsync_file(fh) -> None:
    """Flush + fsync one open file object."""
    fh.flush()
    os.fsync(fh.fileno())


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so renames/creates inside it reach the disk
    journal (a rename is durable only once its directory entry is)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write(path: str, data: bytes, fsync: bool = False) -> None:
    """Write-temp-then-rename publication of one complete file.

    The temp file lives NEXT TO the target (same filesystem, so the
    rename is atomic); on any failure the temp file is removed and the
    previous content of `path` is untouched.  With `fsync` the data
    and the directory entry are forced to disk before returning —
    journal-grade durability; without it the write is still atomic but
    rides the page cache (the program-cache trade-off)."""
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               prefix=".tmp-", suffix="~")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
            if fsync:
                fsync_file(fh)
        os.replace(tmp, path)
        if fsync:
            fsync_dir(os.path.dirname(path) or ".")
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path: str, obj, fsync: bool = False) -> None:
    atomic_write(path, json.dumps(obj, sort_keys=True,
                                  separators=(",", ":")).encode(),
                 fsync=fsync)


def atomic_rewrite(path: str, chunks: Iterable[bytes],
                   fsync: bool = False) -> int:
    """Compaction primitive: stream `chunks` into a temp file and
    atomically replace `path` with it (rewrite-temp-then-rename).
    Returns the number of bytes written.  Used by retention compaction
    (sample store) where the new content is a filtered stream of the
    old — never loaded into memory at once."""
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               prefix=".tmp-", suffix="~")
    written = 0
    try:
        with os.fdopen(fd, "wb") as fh:
            for chunk in chunks:
                fh.write(chunk)
                written += len(chunk)
            if fsync:
                fsync_file(fh)
        os.replace(tmp, path)
        if fsync:
            fsync_dir(os.path.dirname(path) or ".")
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return written


def replace(src: str, dst: str) -> None:
    """Atomic move/overwrite (quarantine paths etc.) — funneled here so
    the durable-write lint rule has one audited rename site."""
    os.replace(src, dst)


# ---------------------------------------------------------------------------
# CRC-framed JSONL records (append-only WAL framing)
# ---------------------------------------------------------------------------
def crc_frame(payload: bytes) -> bytes:
    """One framed record: `<8-hex-crc32> <payload>\\n`.  The payload
    must not contain newlines (compact JSON never does)."""
    return b"%08x %s\n" % (zlib.crc32(payload) & 0xFFFFFFFF, payload)


def json_frame(record: dict) -> bytes:
    return crc_frame(json.dumps(record, sort_keys=True,
                                separators=(",", ":")).encode())


def parse_crc_frame(line: bytes) -> Optional[bytes]:
    """The payload of one framed line, or None when the frame is bad
    (short line, bad hex, crc mismatch — all the torn-tail shapes)."""
    line = line.rstrip(b"\n")
    if len(line) < 10 or line[8:9] != b" ":
        return None
    try:
        want = int(line[:8], 16)
    except ValueError:
        return None
    payload = line[9:]
    if zlib.crc32(payload) & 0xFFFFFFFF != want:
        return None
    return payload


def read_crc_json(path: str) -> Tuple[List[dict], bool]:
    """Replay one CRC-framed JSONL file: `(records, truncated)`.

    Reading stops at the FIRST bad record (crc mismatch, unparseable
    json, missing trailing newline on the last line): everything after
    a torn record is untrustworthy even if it frames correctly, so the
    tail is logically truncated — `truncated` tells the caller the
    file did not end cleanly."""
    records: List[dict] = []
    if not os.path.exists(path):
        return records, False
    with open(path, "rb") as fh:
        for raw in fh:
            if not raw.endswith(b"\n"):
                return records, True          # torn final record
            payload = parse_crc_frame(raw)
            if payload is None:
                return records, True
            try:
                records.append(json.loads(payload))
            except ValueError:
                return records, True
    return records, False


def open_append(path: str) -> IO[bytes]:
    """Open an append-only record log (the WAL segment handle)."""
    return open(path, "ab")
