"""Deterministic fault-injection harness.

The self-healing discipline the framework applies to Kafka clusters
(detect → degrade → recover → report) is applied to the solver itself by
PR 2; proving that discipline needs REPRODUCIBLE faults.  This module
provides named injection sites compiled into the hot paths — optimizer
compile/execute, facade precompute, monitor sampler fetch/store, executor
admin calls — that are inert (one None check) unless a test or a chaos
sweep installs a `FaultPlan`.

Scripting surface:

    plan = FaultPlan(seed=7)
    plan.fail_nth("optimizer.execute", 1)            # fail the 1st call
    plan.fail_nth("executor.admin.describe_cluster", (2, 3))
    plan.fail_probability("monitor.sampler.fetch", 0.25)  # seeded RNG
    plan.fail_always("optimizer.compile", until=4)   # calls 1-4 fail
    plan.hang_nth("mesh.dispatch", 1, release)       # 1st call BLOCKS
    with faults.injected(plan):
        ...

Hangs vs failures: a *failure* raises; a *hang* BLOCKS the calling
thread — either for a fixed number of seconds or until a
`threading.Event` the test holds is set.  Hangs simulate the failure
mode exceptions cannot: a wedged XLA dispatch / stuck collective that
never returns (the PR-12 mesh-recovery surface).  Production code
wraps hang-capable sites in the watched-dispatch gateway
(parallel/health.py), which is exactly what the hang exists to
exercise: the watchdog must release the dispatch thread while the
wedged worker thread stays blocked.

Every injected exception is a `FaultError` carrying its `.site`, so the
degradation ladder's failure classifier can bucket scripted faults by the
layer they hit (compile vs runtime vs I/O) exactly as it buckets real
ones.  Sites self-register on first `inject()` so `known_sites()` reports
the wired surface; per-site call and failure counts make scenario
assertions exact.
"""
from __future__ import annotations

import contextlib
import dataclasses
import random
import threading
import time as _time
from typing import Dict, Iterable, Optional, Tuple, Union

#: every site that executed at least one inject() in this process —
#: the live map of where faults CAN be injected
_KNOWN_SITES: set = set()
_KNOWN_LOCK = threading.Lock()


class FaultError(RuntimeError):
    """An injected fault.  `site` names the injection point so failure
    classification can treat a scripted compile fault exactly like a real
    compiler error."""

    def __init__(self, site: str, message: str = "") -> None:
        super().__init__(message or f"injected fault at {site}")
        self.site = site


@dataclasses.dataclass
class _SiteRule:
    fail_calls: frozenset = frozenset()      # 1-based call numbers
    fail_until: int = 0                      # calls 1..fail_until fail
    probability: float = 0.0
    exc_factory: Optional[object] = None     # callable(site) -> Exception
    hang_calls: frozenset = frozenset()      # 1-based call numbers
    hang_until: int = 0                      # calls 1..hang_until hang
    #: how a triggered hang blocks: float seconds, or a threading.Event
    #: the test sets to release the wedged thread
    hang_on: Optional[object] = None


class FaultPlan:
    """A deterministic script of faults, keyed by site name."""

    def __init__(self, seed: int = 0) -> None:
        self._rules: Dict[str, _SiteRule] = {}
        self._rng = random.Random(seed)

    def _rule(self, site: str) -> _SiteRule:
        return self._rules.setdefault(site, _SiteRule())

    def fail_nth(self, site: str, nth: Union[int, Iterable[int]],
                 exc_factory=None) -> "FaultPlan":
        """Fail the nth call (1-based), or each call in an iterable."""
        calls = frozenset((nth,) if isinstance(nth, int) else nth)
        rule = self._rule(site)
        rule.fail_calls = rule.fail_calls | calls
        if exc_factory is not None:
            rule.exc_factory = exc_factory
        return self

    def fail_always(self, site: str, until: Optional[int] = None,
                    exc_factory=None) -> "FaultPlan":
        """Fail every call, or calls 1..until when `until` is given."""
        rule = self._rule(site)
        rule.fail_until = (2 ** 31 if until is None else int(until))
        if exc_factory is not None:
            rule.exc_factory = exc_factory
        return self

    def fail_probability(self, site: str, p: float,
                         exc_factory=None) -> "FaultPlan":
        """Fail each call with probability p (seeded — reruns of the same
        plan over the same call sequence reproduce the same faults)."""
        rule = self._rule(site)
        rule.probability = float(p)
        if exc_factory is not None:
            rule.exc_factory = exc_factory
        return self

    def hang_nth(self, site: str, nth: Union[int, Iterable[int]],
                 hang_on) -> "FaultPlan":
        """HANG the nth call (1-based), or each call in an iterable:
        the calling thread blocks for `hang_on` seconds (float) or
        until `hang_on` (a threading.Event) is set.  This is the
        chip-loss / wedged-collective injection: the call never raises
        — it simply does not return in time."""
        calls = frozenset((nth,) if isinstance(nth, int) else nth)
        rule = self._rule(site)
        rule.hang_calls = rule.hang_calls | calls
        rule.hang_on = hang_on
        return self

    def hang_always(self, site: str, hang_on,
                    until: Optional[int] = None) -> "FaultPlan":
        """Hang every call, or calls 1..until when `until` is given."""
        rule = self._rule(site)
        rule.hang_until = (2 ** 31 if until is None else int(until))
        rule.hang_on = hang_on
        return self

    def should_hang(self, site: str, call_number: int):
        """The hang spec (seconds or Event) when this call hangs, else
        None."""
        rule = self._rules.get(site)
        if rule is None or rule.hang_on is None:
            return None
        if (call_number in rule.hang_calls
                or call_number <= rule.hang_until):
            return rule.hang_on
        return None

    def should_fail(self, site: str, call_number: int) -> bool:
        rule = self._rules.get(site)
        if rule is None:
            return False
        if call_number in rule.fail_calls or call_number <= rule.fail_until:
            return True
        return rule.probability > 0.0 \
            and self._rng.random() < rule.probability

    def exception_for(self, site: str) -> BaseException:
        rule = self._rules.get(site)
        if rule is not None and rule.exc_factory is not None:
            return rule.exc_factory(site)
        return FaultError(site)


class FaultInjector:
    """An installed plan plus per-site call/failure counters."""

    def __init__(self, plan: FaultPlan) -> None:
        self._plan = plan
        self._lock = threading.Lock()
        self._calls: Dict[str, int] = {}
        self._failures: Dict[str, int] = {}
        self._hangs: Dict[str, int] = {}

    def fire(self, site: str) -> None:
        with self._lock:
            n = self._calls.get(site, 0) + 1
            self._calls[site] = n
            fail = self._plan.should_fail(site, n)
            hang = None if fail else self._plan.should_hang(site, n)
            if fail:
                self._failures[site] = self._failures.get(site, 0) + 1
            elif hang is not None:
                self._hangs[site] = self._hangs.get(site, 0) + 1
        if fail:
            raise self._plan.exception_for(site)
        if hang is not None:
            # block OUTSIDE the lock: the wedged thread must not stop
            # other sites (or this site's counters) from firing
            if isinstance(hang, (int, float)):
                _time.sleep(float(hang))
            else:
                hang.wait()

    def call_count(self, site: str) -> int:
        with self._lock:
            return self._calls.get(site, 0)

    def failure_count(self, site: str) -> int:
        with self._lock:
            return self._failures.get(site, 0)

    def hang_count(self, site: str) -> int:
        with self._lock:
            return self._hangs.get(site, 0)

    def counts(self) -> Dict[str, Tuple[int, int]]:
        """{site: (calls, failures)} for every site that fired."""
        with self._lock:
            return {s: (c, self._failures.get(s, 0))
                    for s, c in sorted(self._calls.items())}


#: the process-wide active injector (None = harness inert)
_ACTIVE: Optional[FaultInjector] = None


def inject(site: str) -> None:
    """The injection point: a no-op unless a plan is installed.  Called
    from production code; the only cost on the happy path is one global
    read (plus first-call site registration)."""
    if site not in _KNOWN_SITES:
        with _KNOWN_LOCK:
            _KNOWN_SITES.add(site)
    injector = _ACTIVE
    if injector is not None:
        injector.fire(site)


def known_sites() -> set:
    """Sites that executed at least once in this process."""
    with _KNOWN_LOCK:
        return set(_KNOWN_SITES)


def install(plan: FaultPlan) -> FaultInjector:
    """Install a plan process-wide; returns the injector for counters."""
    global _ACTIVE
    injector = FaultInjector(plan)
    _ACTIVE = injector
    return injector


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def active() -> Optional[FaultInjector]:
    return _ACTIVE


@contextlib.contextmanager
def injected(plan: FaultPlan):
    """Scoped installation: `with faults.injected(plan) as injector:`."""
    injector = install(plan)
    try:
        yield injector
    finally:
        uninstall()
