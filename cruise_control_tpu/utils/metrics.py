"""Lightweight metric registry: counters, gauges, meters, timers.

Reference: dropwizard MetricRegistry exported over JMX domain
`kafka.cruisecontrol` (CC/KafkaCruiseControlApp.java:39-41) with sensors
like `proposal-computation-timer` (GoalOptimizer.java:118),
`cluster-model-creation-timer` (LoadMonitor.java:180) and per-endpoint
request timers/meters (KafkaCruiseControlServlet.java:60-65); sensor list
doc docs/wiki "Sensors".  Here the registry is process-local and exported
as JSON through the STATE endpoint's `sensors` substate.
"""
from __future__ import annotations

import logging
import math
import threading
import time as _time
from typing import Callable, Dict, List, Optional

LOG = logging.getLogger(__name__)


class Counter:
    def __init__(self) -> None:
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def count(self) -> int:
        return self._v

    def to_json(self) -> dict:
        return {"type": "counter", "count": self._v}


class Meter:
    """Event rate: count + events/s over the process lifetime and a
    sliding recent window."""

    def __init__(self, time_fn: Callable[[], float] = _time.time,
                 window_s: float = 300.0) -> None:
        self._time = time_fn
        self._window_s = window_s
        self._lock = threading.Lock()
        self._count = 0
        self._start = time_fn()
        self._recent: List[float] = []

    def mark(self, n: int = 1) -> None:
        now = self._time()
        with self._lock:
            self._count += n
            self._recent.extend([now] * min(n, 100))
            cutoff = now - self._window_s
            while self._recent and self._recent[0] < cutoff:
                self._recent.pop(0)

    def to_json(self) -> dict:
        now = self._time()
        with self._lock:
            lifetime = max(now - self._start, 1e-9)
            recent = [t for t in self._recent if t >= now - self._window_s]
            return {"type": "meter", "count": self._count,
                    "meanRate": self._count / lifetime,
                    "recentRate": len(recent) / self._window_s}


class Timer:
    """Duration stats (count, mean, max, last, approximate p99 via a
    bounded reservoir)."""

    RESERVOIR = 256

    def __init__(self, time_fn: Callable[[], float] = _time.time) -> None:
        self._time = time_fn
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0
        self._max = 0.0
        self._last = 0.0
        self._samples: List[float] = []

    def update(self, duration_s: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += duration_s
            self._max = max(self._max, duration_s)
            self._last = duration_s
            if len(self._samples) < self.RESERVOIR:
                self._samples.append(duration_s)
            else:
                # deterministic reservoir: overwrite cyclically
                self._samples[self._count % self.RESERVOIR] = duration_s

    def time(self) -> "_TimerContext":
        return _TimerContext(self)

    def to_json(self) -> dict:
        with self._lock:
            if not self._count:
                return {"type": "timer", "count": 0}
            ordered = sorted(self._samples)
            p99 = ordered[min(len(ordered) - 1,
                              math.ceil(0.99 * len(ordered)) - 1)]
            return {"type": "timer", "count": self._count,
                    "meanMs": 1e3 * self._sum / self._count,
                    "maxMs": 1e3 * self._max, "lastMs": 1e3 * self._last,
                    "p99Ms": 1e3 * p99}


class _TimerContext:
    def __init__(self, timer: Timer) -> None:
        self._timer = timer

    def __enter__(self) -> "_TimerContext":
        self._t0 = self._timer._time()
        return self

    def __exit__(self, *exc) -> None:
        self._timer.update(self._timer._time() - self._t0)


class Gauge:
    def __init__(self, fn: Callable[[], float],
                 on_error: Optional[Callable] = None,
                 name: str = "") -> None:
        self._fn = fn
        self._on_error = on_error
        self._name = name

    def to_json(self) -> dict:
        try:
            return {"type": "gauge", "value": self._fn()}
        except Exception as exc:  # noqa: BLE001 - never break export
            # a broken gauge callable must not break the whole sensor
            # export, but silence hid real wiring bugs: the registry
            # counts it (sensor-export-errors meter) and logs once per
            # gauge name
            if self._on_error is not None:
                self._on_error(self._name, exc)
            return {"type": "gauge", "value": None}


class MetricRegistry:
    """Named sensors; one registry per CruiseControl instance."""

    def __init__(self, time_fn: Callable[[], float] = _time.time) -> None:
        self._time = time_fn
        self._lock = threading.Lock()
        self._sensors: Dict[str, object] = {}
        #: gauge names whose export failure was already logged (log once
        #: per gauge — a broken gauge fires on EVERY export)
        self._gauge_errors_logged: set = set()

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def meter(self, name: str) -> Meter:
        return self._get(name, lambda: Meter(self._time))

    def timer(self, name: str) -> Timer:
        return self._get(name, lambda: Timer(self._time))

    def update_timer(self, name: str, duration_s: float) -> None:
        """Record one duration sample into the named timer — for
        instrumentation that measures outside a with-block (e.g. the
        segment profiler publishing per-category solve time, see
        utils/profiling.SegmentProfiler.publish)."""
        self.timer(name).update(duration_s)

    def gauge(self, name: str, fn: Callable[[], float]) -> Gauge:
        with self._lock:
            g = self._sensors.get(name)
            if not isinstance(g, Gauge):
                g = Gauge(fn, on_error=self._on_gauge_error, name=name)
                self._sensors[name] = g
            return g

    def _on_gauge_error(self, name: str, exc: BaseException) -> None:
        """A gauge callable raised during export: meter it
        (`sensor-export-errors`) and log once per gauge name."""
        self.meter("sensor-export-errors").mark()
        first = False
        with self._lock:
            if name not in self._gauge_errors_logged:
                self._gauge_errors_logged.add(name)
                first = True
        if first:
            LOG.warning("gauge %r failed to export (%s: %s); exporting "
                        "null and counting into sensor-export-errors "
                        "(logged once per gauge)",
                        name, type(exc).__name__, exc)

    def _get(self, name: str, factory):
        with self._lock:
            s = self._sensors.get(name)
            if s is None:
                s = factory()
                self._sensors[name] = s
            return s

    def to_json(self) -> Dict[str, dict]:
        with self._lock:
            items = list(self._sensors.items())
        return {name: s.to_json() for name, s in sorted(items)}
