"""Lightweight metric registry: counters, gauges, meters, timers.

Reference: dropwizard MetricRegistry exported over JMX domain
`kafka.cruisecontrol` (CC/KafkaCruiseControlApp.java:39-41) with sensors
like `proposal-computation-timer` (GoalOptimizer.java:118),
`cluster-model-creation-timer` (LoadMonitor.java:180) and per-endpoint
request timers/meters (KafkaCruiseControlServlet.java:60-65); sensor list
doc docs/wiki "Sensors".  Here the registry is process-local and exported
as JSON through the STATE endpoint's `sensors` substate.
"""
from __future__ import annotations

import logging
import math
import re
import threading
import time as _time
from typing import Callable, Dict, List, Optional, Tuple

LOG = logging.getLogger(__name__)

#: prefix of every exported OpenMetrics family
OPENMETRICS_PREFIX = "cc_tpu_"

_INVALID_METRIC_CHARS = re.compile(r"[^a-zA-Z0-9_]")


def canonical_sensor_name(name: str) -> str:
    """THE canonical mapping from an internal sensor name (dashed,
    dotted, mixed-case — `proposal-computation-timer`,
    `REBALANCE-request-rate`) to its OpenMetrics family name
    (`cc_tpu_proposal_computation_timer`).  Dots and dashes would export
    as invalid (or silently colliding) Prometheus names; this mapping is
    applied ONCE, here, and checked for collisions at registry-register
    time — export and scrape docs always agree with it."""
    out = _INVALID_METRIC_CHARS.sub("_", name.strip()).lower()
    out = out.strip("_") or "sensor"
    if out[0].isdigit():
        out = "_" + out
    return OPENMETRICS_PREFIX + out


def openmetrics_sensor(name: str) -> Tuple[str, Dict[str, str]]:
    """(canonical family name, labels) for an EXPORT-side sensor key.
    The fleet registry tags tenant sensors `cluster.<id>.<sensor>`
    (fleet/registry.sensors_json); that prefix becomes a proper
    `cluster` label so one scrape sees every tenant as labeled series of
    one family instead of N differently-named metrics."""
    labels: Dict[str, str] = {}
    if name.startswith("cluster."):
        # split on the LAST dot: registry sensor names are dashed and
        # never dotted (the register-time canonical check would flag a
        # dotted twin), while fleet tenant ids MAY contain dots
        # ("kafka.prod.eu") — a first-dot split would truncate the
        # cluster label and corrupt the family name
        rest = name[len("cluster."):]
        cluster_id, _, bare = rest.rpartition(".")
        if cluster_id and bare:
            labels["cluster"] = cluster_id
            name = bare
    return canonical_sensor_name(name), labels


class Counter:
    def __init__(self) -> None:
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def count(self) -> int:
        return self._v

    def to_json(self) -> dict:
        return {"type": "counter", "count": self._v}


class Meter:
    """Event rate: count + events/s over the process lifetime and a
    sliding recent window."""

    def __init__(self, time_fn: Callable[[], float] = _time.time,
                 window_s: float = 300.0) -> None:
        self._time = time_fn
        self._window_s = window_s
        self._lock = threading.Lock()
        self._count = 0
        self._start = time_fn()
        self._recent: List[float] = []

    def mark(self, n: int = 1) -> None:
        now = self._time()
        with self._lock:
            self._count += n
            self._recent.extend([now] * min(n, 100))
            cutoff = now - self._window_s
            while self._recent and self._recent[0] < cutoff:
                self._recent.pop(0)

    def to_json(self) -> dict:
        now = self._time()
        with self._lock:
            lifetime = max(now - self._start, 1e-9)
            recent = [t for t in self._recent if t >= now - self._window_s]
            return {"type": "meter", "count": self._count,
                    "meanRate": self._count / lifetime,
                    "recentRate": len(recent) / self._window_s}


class Timer:
    """Duration stats (count, mean, max, last, approximate p99 via a
    bounded reservoir)."""

    RESERVOIR = 256

    def __init__(self, time_fn: Callable[[], float] = _time.time) -> None:
        self._time = time_fn
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0
        self._max = 0.0
        self._last = 0.0
        self._samples: List[float] = []

    def update(self, duration_s: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += duration_s
            self._max = max(self._max, duration_s)
            self._last = duration_s
            if len(self._samples) < self.RESERVOIR:
                self._samples.append(duration_s)
            else:
                # deterministic reservoir: overwrite cyclically
                self._samples[self._count % self.RESERVOIR] = duration_s

    def time(self) -> "_TimerContext":
        return _TimerContext(self)

    def to_json(self) -> dict:
        with self._lock:
            if not self._count:
                return {"type": "timer", "count": 0}
            ordered = sorted(self._samples)
            p99 = ordered[min(len(ordered) - 1,
                              math.ceil(0.99 * len(ordered)) - 1)]
            return {"type": "timer", "count": self._count,
                    "meanMs": 1e3 * self._sum / self._count,
                    "maxMs": 1e3 * self._max, "lastMs": 1e3 * self._last,
                    "p99Ms": 1e3 * p99}


class _TimerContext:
    def __init__(self, timer: Timer) -> None:
        self._timer = timer

    def __enter__(self) -> "_TimerContext":
        self._t0 = self._timer._time()
        return self

    def __exit__(self, *exc) -> None:
        self._timer.update(self._timer._time() - self._t0)


class Histogram:
    """Fixed-bucket latency histogram (seconds).  Cumulative bucket
    counts in `to_json` so the OpenMetrics exporter (obs/export.py) can
    render a real `_bucket{le=...}` family; the STATE endpoint shows the
    same JSON.  Buckets are fixed at construction — scrapes must never
    see a histogram whose bucket boundaries move."""

    #: default boundaries (seconds) spanning sub-ms queue waits to
    #: multi-minute cold solves
    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                       1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)

    def __init__(self, buckets: Optional[Tuple[float, ...]] = None) -> None:
        bounds = tuple(sorted(buckets or self.DEFAULT_BUCKETS))
        if not bounds or any(b <= 0 for b in bounds):
            raise ValueError("histogram buckets must be positive")
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)     # +Inf tail
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value_s: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += value_s
            for i, bound in enumerate(self._bounds):
                if value_s <= bound:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def to_json(self) -> dict:
        with self._lock:
            cumulative = {}
            running = 0
            for bound, n in zip(self._bounds, self._counts):
                running += n
                cumulative[repr(float(bound))] = running
            cumulative["+Inf"] = running + self._counts[-1]
            return {"type": "histogram", "count": self._count,
                    "sum": self._sum, "buckets": cumulative}


class Gauge:
    def __init__(self, fn: Callable[[], float],
                 on_error: Optional[Callable] = None,
                 name: str = "") -> None:
        self._fn = fn
        self._on_error = on_error
        self._name = name

    def to_json(self) -> dict:
        try:
            return {"type": "gauge", "value": self._fn()}
        except Exception as exc:  # noqa: BLE001 - never break export
            # a broken gauge callable must not break the whole sensor
            # export, but silence hid real wiring bugs: the registry
            # counts it (sensor-export-errors meter) and logs once per
            # gauge name
            if self._on_error is not None:
                self._on_error(self._name, exc)
            return {"type": "gauge", "value": None}


class MetricRegistry:
    """Named sensors; one registry per CruiseControl instance."""

    def __init__(self, time_fn: Callable[[], float] = _time.time,
                 bucket_overrides: Optional[
                     Dict[str, Tuple[float, ...]]] = None) -> None:
        self._time = time_fn
        self._lock = threading.Lock()
        #: per-sensor histogram bucket boundaries (seconds), keyed by
        #: sensor name or name PREFIX (config `obs.metrics.buckets.
        #: <name>`): `sched-wait-hist` covers every per-class
        #: `sched-wait-hist-<class>` histogram.  Applied at histogram
        #: CREATION only — a live histogram's boundaries never move
        #: under a scrape (set overrides before the first observation).
        self._bucket_overrides: Dict[str, Tuple[float, ...]] = dict(
            bucket_overrides or {})
        self._sensors: Dict[str, object] = {}
        #: canonical OpenMetrics family -> the raw sensor name that
        #: claimed it (collision check at register time: `a-b` and `a.b`
        #: would silently merge on the /metrics page otherwise)
        self._canonical: Dict[str, str] = {}
        #: gauge names whose export failure was already logged (log once
        #: per gauge — a broken gauge fires on EVERY export)
        self._gauge_errors_logged: set = set()

    def _check_canonical_locked(self, name: str) -> None:
        """Caller holds the lock with `name` not yet registered: reject
        a sensor whose canonical export name collides with a DIFFERENT
        already-registered sensor."""
        canonical = canonical_sensor_name(name)
        claimed = self._canonical.get(canonical)
        if claimed is not None and claimed != name:
            raise ValueError(
                f"sensor {name!r} collides with {claimed!r}: both "
                f"export as OpenMetrics family {canonical!r} — rename "
                f"one (utils/metrics.canonical_sensor_name)")
        self._canonical[canonical] = name

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def meter(self, name: str) -> Meter:
        return self._get(name, lambda: Meter(self._time))

    def timer(self, name: str) -> Timer:
        return self._get(name, lambda: Timer(self._time))

    def update_timer(self, name: str, duration_s: float) -> None:
        """Record one duration sample into the named timer — for
        instrumentation that measures outside a with-block (e.g. the
        segment profiler publishing per-category solve time, see
        utils/profiling.SegmentProfiler.publish)."""
        self.timer(name).update(duration_s)

    def set_bucket_overrides(
            self, overrides: Dict[str, Tuple[float, ...]]) -> None:
        """Install per-sensor histogram bucket boundaries (seconds).
        Only affects histograms created AFTER the call — existing
        histograms keep their boundaries (scrapes must never see a
        histogram whose bucket edges move)."""
        with self._lock:
            self._bucket_overrides.update(
                {k: tuple(sorted(float(b) for b in v))
                 for k, v in overrides.items()})

    def buckets_for(self, name: str) -> Optional[Tuple[float, ...]]:
        """The configured bucket boundaries for a histogram name: an
        exact-name override wins, else the LONGEST override key that
        prefixes the name (so `sched-wait-hist` covers
        `sched-wait-hist-user-interactive`), else None (defaults)."""
        with self._lock:
            overrides = dict(self._bucket_overrides)
        exact = overrides.get(name)
        if exact is not None:
            return exact
        best = None
        for key, bounds in overrides.items():
            if name.startswith(key) and (best is None
                                         or len(key) > len(best[0])):
                best = (key, bounds)
        return best[1] if best is not None else None

    def histogram(self, name: str,
                  buckets: Optional[Tuple[float, ...]] = None
                  ) -> Histogram:
        # resolve overrides BEFORE _get: the factory runs under the
        # registry lock and buckets_for takes it too (non-reentrant)
        resolved = buckets or self.buckets_for(name)
        return self._get(name, lambda: Histogram(resolved))

    def update_histogram(self, name: str, value_s: float) -> None:
        """Record one observation (seconds) into the named histogram —
        e.g. the scheduler's per-class queue-wait and solve-duration
        histograms exported through /metrics."""
        self.histogram(name).observe(value_s)

    def gauge(self, name: str, fn: Callable[[], float]) -> Gauge:
        with self._lock:
            g = self._sensors.get(name)
            if not isinstance(g, Gauge):
                if name not in self._sensors:
                    self._check_canonical_locked(name)
                g = Gauge(fn, on_error=self._on_gauge_error, name=name)
                self._sensors[name] = g
            return g

    def _on_gauge_error(self, name: str, exc: BaseException) -> None:
        """A gauge callable raised during export: meter it
        (`sensor-export-errors`) and log once per gauge name."""
        self.meter("sensor-export-errors").mark()
        first = False
        with self._lock:
            if name not in self._gauge_errors_logged:
                self._gauge_errors_logged.add(name)
                first = True
        if first:
            LOG.warning("gauge %r failed to export (%s: %s); exporting "
                        "null and counting into sensor-export-errors "
                        "(logged once per gauge)",
                        name, type(exc).__name__, exc)

    def peek(self, name: str):
        """The named sensor, or None WITHOUT creating it — read-side
        consumers (the SLO evaluator polling histograms that may not
        have observed anything yet) must not materialize empty sensors
        as a side effect of looking."""
        with self._lock:
            return self._sensors.get(name)

    def _get(self, name: str, factory):
        with self._lock:
            s = self._sensors.get(name)
            if s is None:
                self._check_canonical_locked(name)
                s = factory()
                self._sensors[name] = s
            return s

    def to_json(self) -> Dict[str, dict]:
        with self._lock:
            items = list(self._sensors.items())
        return {name: s.to_json() for name, s in sorted(items)}
