"""Metric-anomaly detector.

Reference CC/detector/MetricAnomalyDetector.java: runs the configured
MetricAnomalyFinder plugins (default: the percentile finder from core) over
the broker metric history and queues every anomaly found.
"""
from __future__ import annotations

from typing import Callable, Hashable, List, Mapping, Sequence, Tuple

from cruise_control_tpu.core.aggregator import ValuesAndExtrapolations
from cruise_control_tpu.core.anomaly import (MetricAnomaly,
                                             MetricAnomalyFinder)

#: supplies (history_by_broker, current_window_by_broker)
HistorySupplier = Callable[[], Tuple[
    Mapping[Hashable, ValuesAndExtrapolations],
    Mapping[Hashable, ValuesAndExtrapolations]]]


class MetricAnomalyDetector:
    def __init__(self, history_supplier: HistorySupplier,
                 finders: Sequence[MetricAnomalyFinder],
                 report_fn: Callable[[MetricAnomaly], None],
                 anomaly_cls=None) -> None:
        self._supplier = history_supplier
        self._finders = list(finders)
        self._report = report_fn
        #: reference metric.anomaly.class — anomalies a finder returns
        #: are re-wrapped when an override is configured
        self._anomaly_cls = anomaly_cls

    def detect_now(self) -> List[MetricAnomaly]:
        history, current = self._supplier()
        if not history or not current:
            return []
        out: List[MetricAnomaly] = []
        for finder in self._finders:
            for anomaly in finder.metric_anomalies(history, current):
                out.append(anomaly)
                self._report(anomaly)
        return out
