"""SLO burn detector: the obs/slo.py evaluator's anomaly-plane edge.

A scheduled detector (anomaly_detector.register_detector, same contract
as the goal-violation/disk/topic detectors): every tick it forces an
SLO evaluation and reports ONE SloBurn anomaly per class per breach
EPISODE — a class whose burn crossed `slo.burn.alert.threshold` fires
once, then stays armed-off until its burn drops back under 1.0 (budget
earning again), so a sustained incident does not spam the notifier on
every tick while a relapse after recovery alerts again.

Notification-only by design: the SelfHealingNotifier default leaves
SLO_BURN self-healing disabled (there is nothing mechanical to heal —
the runbook in docs/OPERATIONS.md §5 is the fix), so the anomaly lands
as an alert with the queue-wait vs device-time decomposition operators
triage from.
"""
from __future__ import annotations

import logging
import time as _time
from typing import Callable, Optional

from cruise_control_tpu.detector.anomalies import SloBurn

LOG = logging.getLogger(__name__)


class SloBurnDetector:
    """See module docstring."""

    def __init__(self, evaluator, report_fn: Callable[[SloBurn], None],
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self._evaluator = evaluator
        self._report = report_fn
        self._time = time_fn or _time.time
        #: classes currently inside a reported breach episode
        self._breached: set = set()
        self.reported = 0

    def detect_now(self) -> None:
        status = self._evaluator.evaluate(force=True)
        if not status.get("enabled", False):
            return
        alert_at = status["alertThreshold"]
        for klass, cls in status.get("classes", {}).items():
            burn = float(cls.get("burn", 0.0))
            if burn >= alert_at and klass not in self._breached:
                self._breached.add(klass)
                self.reported += 1
                anomaly = SloBurn(
                    scheduler_class=klass,
                    burn=burn,
                    queue_wait_burn=float(cls.get("queueWaitBurn", 0.0)),
                    device_time_burn=float(cls.get("deviceTimeBurn", 0.0)),
                    window_s=float(status.get("windowS", 0.0)),
                    alert_threshold=float(alert_at),
                    objective=dict(cls.get("objective", {})),
                    description=(f"{cls.get('windowSolves', 0)} solves "
                                 f"in window"),
                    detected_ms=self._time() * 1000.0)
                LOG.warning("SLO burn: %s", anomaly)
                self._report(anomaly)
            elif burn < 1.0:
                # episode over only once the budget is earning again —
                # hovering between 1.0 and the alert threshold neither
                # re-fires nor re-arms
                self._breached.discard(klass)

    def to_json(self) -> dict:
        return {"breachedClasses": sorted(self._breached),
                "reported": self.reported}
