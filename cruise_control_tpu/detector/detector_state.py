"""Anomaly-detector state for the STATE endpoint.

Reference CC/detector/AnomalyDetectorState.java:1-403 — ring buffers of
recent anomalies per type with their handling status, plus self-healing
enabled/disabled flags and counters.
"""
from __future__ import annotations

import collections
import dataclasses
import enum
import threading
from typing import Deque, Dict, List

from cruise_control_tpu.core.anomaly import Anomaly, AnomalyType


class AnomalyState(enum.Enum):
    DETECTED = "DETECTED"
    CHECK_WITH_DELAY = "CHECK_WITH_DELAY"
    IGNORED = "IGNORED"
    FIX_STARTED = "FIX_STARTED"
    FIX_FAILED_TO_START = "FIX_FAILED_TO_START"
    LOAD_MONITOR_NOT_READY = "LOAD_MONITOR_NOT_READY"
    COMPLETENESS_NOT_READY = "COMPLETENESS_NOT_READY"


@dataclasses.dataclass
class AnomalyRecord:
    anomaly_id: str
    anomaly_type: AnomalyType
    description: str
    status: AnomalyState
    detected_ms: float
    status_update_ms: float


class AnomalyDetectorState:
    def __init__(self, num_cached_recent_anomaly_states: int = 10) -> None:
        self._lock = threading.Lock()
        self._recent: Dict[AnomalyType, Deque[AnomalyRecord]] = {
            t: collections.deque(maxlen=num_cached_recent_anomaly_states)
            for t in AnomalyType}
        self._metrics: Dict[str, int] = collections.defaultdict(int)

    def on_detected(self, anomaly: Anomaly, now_ms: float) -> None:
        with self._lock:
            self._recent[anomaly.anomaly_type].append(AnomalyRecord(
                anomaly.anomaly_id, anomaly.anomaly_type, str(anomaly),
                AnomalyState.DETECTED, now_ms, now_ms))
            self._metrics[f"{anomaly.anomaly_type.name}-detected"] += 1

    def on_status(self, anomaly: Anomaly, status: AnomalyState,
                  now_ms: float) -> None:
        with self._lock:
            for rec in self._recent[anomaly.anomaly_type]:
                if rec.anomaly_id == anomaly.anomaly_id:
                    rec.status = status
                    rec.status_update_ms = now_ms
                    break
            self._metrics[f"{anomaly.anomaly_type.name}-"
                          f"{status.name.lower()}"] += 1

    def recent_anomalies(self, anomaly_type: AnomalyType
                         ) -> List[AnomalyRecord]:
        with self._lock:
            return list(self._recent[anomaly_type])

    def metrics(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._metrics)

    def to_json(self, self_healing_enabled: Dict[AnomalyType, bool]) -> dict:
        with self._lock:
            return {
                "selfHealingEnabled": [t.name for t, on in
                                       self_healing_enabled.items() if on],
                "selfHealingDisabled": [t.name for t, on in
                                        self_healing_enabled.items()
                                        if not on],
                "recentAnomalies": {
                    t.name: [{
                        "anomalyId": r.anomaly_id,
                        "description": r.description,
                        "status": r.status.value,
                        "detectionMs": r.detected_ms,
                        "statusUpdateMs": r.status_update_ms,
                    } for r in recs]
                    for t, recs in self._recent.items()},
                "metrics": dict(self._metrics),
            }
