"""Goal-violation detector.

Reference CC/detector/GoalViolationDetector.java:49-277: periodically builds
a cluster model and evaluates a separate *detection* goal list against it —
no optimization, just the per-goal violation predicate — reporting a
GoalViolations anomaly and a balancedness score [0, 100].

TPU note: violation predicates are the goals' `violated_brokers` kernels
(vectorized reductions over broker-load tensors), so a detection sweep is a
single fused device computation per goal rather than the reference's
per-broker Java loops.
"""
from __future__ import annotations

import logging
import time as _time
from typing import Callable, List, Optional, Sequence

import numpy as np

from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                 OptimizationOptions,
                                                 make_context,
                                                 make_round_cache)
from cruise_control_tpu.analyzer.goals.base import Goal
from cruise_control_tpu.detector.anomalies import FixFn, GoalViolations

LOG = logging.getLogger(__name__)


def balancedness_score(goals: Sequence[Goal], violated: Sequence[str],
                       priority_weight: float = 1.1,
                       strictness_weight: float = 1.5) -> float:
    """[0, 100]: weighted fraction of satisfied goals (reference
    GoalViolationDetector balancedness + AnomalyDetector.java:176-178 gauge;
    weights from goal.balancedness.priority.weight /
    goal.balancedness.strictness.weight).  Hard goals weigh
    `strictness_weight`× more; higher-priority goals weigh more through
    `priority_weight^rank`."""
    from cruise_control_tpu.analyzer.goals.base import \
        balancedness_cost_by_goal
    if not goals:
        return 100.0
    costs = balancedness_cost_by_goal(
        [g.name for g in goals], {g.name for g in goals if g.is_hard},
        priority_weight, strictness_weight)
    # sum the SATISFIED goals' costs (not 100 - violated sum) so the
    # all-violated score is exactly 0.0
    violated_set = set(violated)
    kept = sum(c for n, c in costs.items() if n not in violated_set)
    total = sum(costs.values())
    return 100.0 * kept / total if total else 100.0


class GoalViolationDetector:
    """Scheduled detector; `detect_now` runs one sweep."""

    def __init__(self, load_monitor,
                 detection_goals: Sequence[Goal],
                 report_fn: Callable[[GoalViolations], None],
                 fix_fn: Optional[FixFn] = None,
                 constraint: Optional[BalancingConstraint] = None,
                 options: Optional[OptimizationOptions] = None,
                 allow_capacity_estimation: bool = True,
                 anomaly_cls=None,
                 model_fn: Optional[Callable] = None,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self._load_monitor = load_monitor
        #: model materializer: the facade injects its store-aware
        #: gateway (facade._model_for_solve) so detection sweeps ride
        #: the device-resident model instead of paying a rebuild per
        #: sweep; standalone constructions default to the monitor's
        #: builder (the single-store lint rule pins the CALL sites)
        self._model_fn = model_fn or load_monitor.cluster_model
        #: reference anomaly.detection.allow.capacity.estimation
        self._allow_capacity_estimation = allow_capacity_estimation
        #: reference goal.violations.class
        self._anomaly_cls = anomaly_cls or GoalViolations
        self._goals = list(detection_goals)
        self._report = report_fn
        self._fix_fn = fix_fn
        self._constraint = constraint or BalancingConstraint()
        self._options = options or OptimizationOptions(
            is_triggered_by_goal_violation=True)
        self._time = time_fn or _time.time
        self._last_score: float = 100.0

    @property
    def last_balancedness_score(self) -> float:
        return self._last_score

    def detect_now(self) -> Optional[GoalViolations]:
        from cruise_control_tpu.core.aggregator import (
            NotEnoughValidWindowsError)
        try:
            state, topology = self._model_fn(
                allow_capacity_estimation=self._allow_capacity_estimation)
        except NotEnoughValidWindowsError as exc:
            # expected during warm-up: not an error
            LOG.debug("skipping goal-violation sweep: %s", exc)
            return None
        except Exception:  # noqa: BLE001 - keep the schedule alive
            LOG.exception(
                "goal-violation sweep failed to build the cluster model")
            return None
        ctx = make_context(state, self._constraint, self._options, topology)
        cache = make_round_cache(state)
        # a violation is unfixable when no alive broker may receive
        # replicas (nothing the optimizer may touch) — goal-independent
        can_move = bool((np.asarray(state.broker_alive)
                         & np.asarray(ctx.broker_dest_ok)).any())
        fixable: List[str] = []
        unfixable: List[str] = []
        for goal in self._goals:
            violated = bool(np.asarray(
                goal.violated_brokers(state, ctx, cache)).any())
            if violated:
                (fixable if can_move else unfixable).append(goal.name)
        self._last_score = balancedness_score(
            self._goals, fixable + unfixable)
        if not fixable and not unfixable:
            return None
        anomaly = self._anomaly_cls(
            fixable_violated_goals=fixable,
            unfixable_violated_goals=unfixable,
            fix_fn=self._fix_fn,
            detected_ms=self._time() * 1000.0)
        self._report(anomaly)
        return anomaly
