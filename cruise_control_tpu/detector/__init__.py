"""Detector plane: anomaly detection and self-healing (SURVEY.md §2.6)."""
from cruise_control_tpu.detector.anomalies import (BrokerFailures,
                                                   DiskFailures,
                                                   GoalViolations,
                                                   SlowBrokers, TopicAnomaly)
from cruise_control_tpu.detector.anomaly_detector import AnomalyDetector
from cruise_control_tpu.detector.broker_failure import (BrokerFailureDetector,
                                                        FailedBrokerStore,
                                                        FileFailedBrokerStore)
from cruise_control_tpu.detector.detector_state import (AnomalyDetectorState,
                                                        AnomalyState)
from cruise_control_tpu.detector.disk_failure import DiskFailureDetector
from cruise_control_tpu.detector.goal_violation import (GoalViolationDetector,
                                                        balancedness_score)
from cruise_control_tpu.detector.metric_anomaly import MetricAnomalyDetector
from cruise_control_tpu.detector.notifier import (AnomalyNotificationResult,
                                                  AnomalyNotifier,
                                                  NoopNotifier,
                                                  NotificationAction,
                                                  SelfHealingNotifier,
                                                  WebhookSelfHealingNotifier)
from cruise_control_tpu.detector.slow_broker import (SlowBrokerFinder,
                                                     SlowBrokerFinderConfig)
from cruise_control_tpu.detector.topic_anomaly import (
    PartitionSizeAnomalyFinder, TopicReplicationFactorAnomalyFinder)

__all__ = [
    "AnomalyDetector", "AnomalyDetectorState", "AnomalyState",
    "AnomalyNotifier", "AnomalyNotificationResult", "NotificationAction",
    "NoopNotifier", "SelfHealingNotifier", "WebhookSelfHealingNotifier",
    "BrokerFailureDetector", "FailedBrokerStore", "FileFailedBrokerStore",
    "DiskFailureDetector", "GoalViolationDetector", "balancedness_score",
    "MetricAnomalyDetector", "SlowBrokerFinder", "SlowBrokerFinderConfig",
    "TopicReplicationFactorAnomalyFinder", "PartitionSizeAnomalyFinder",
    "BrokerFailures", "DiskFailures", "GoalViolations", "SlowBrokers",
    "TopicAnomaly",
]
