"""Anomaly-detector coordinator.

Reference CC/detector/AnomalyDetector.java:50-564: detectors push anomalies
into a priority queue (priority = AnomalyType order, FIFO within type); the
handler takes each anomaly, consults the notifier (FIX / CHECK-later /
IGNORE), and for FIX starts the anomaly's self-healing runnable — unless the
load monitor isn't ready or another fix is in flight.  Scheduled detectors
(goal-violation, metric, disk, topic) run at configurable intervals with
jitter; the broker-failure detector is event-driven.

Re-design: detection sweeps and queue handling are explicit `*_once` methods
driven either by the built-in scheduler thread (wall-clock deployments) or
directly by tests/demos with a virtual clock — same state machine either
way.
"""
from __future__ import annotations

import heapq
import itertools
import logging
import random
import threading
import time as _time
from typing import Callable, Dict, List, Optional, Tuple

from cruise_control_tpu.core.anomaly import Anomaly, AnomalyType
from cruise_control_tpu.detector.detector_state import (AnomalyDetectorState,
                                                        AnomalyState)
from cruise_control_tpu.detector.notifier import (AnomalyNotificationResult,
                                                  AnomalyNotifier,
                                                  NoopNotifier)

LOG = logging.getLogger(__name__)

#: a detector with a `detect_now()` method
ScheduledDetector = object


class AnomalyDetector:
    def __init__(self,
                 notifier: Optional[AnomalyNotifier] = None,
                 ready_fn: Optional[Callable[[], bool]] = None,
                 fix_in_progress_fn: Optional[Callable[[], bool]] = None,
                 num_cached_recent_anomaly_states: int = 10,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self._notifier = notifier or NoopNotifier()
        #: load-monitor readiness gate (reference checks LoadMonitor state)
        self._ready = ready_fn or (lambda: True)
        #: executor-busy gate (one self-healing fix at a time)
        self._fix_in_progress = fix_in_progress_fn or (lambda: False)
        self._time = time_fn or _time.time
        self._lock = threading.Lock()
        self._seq = itertools.count()
        #: heap of (type priority, seq, anomaly)
        self._queue: List[Tuple[int, int, Anomaly]] = []
        #: deferred CHECK-later anomalies: (due_ms, seq, anomaly)
        self._deferred: List[Tuple[float, int, Anomaly]] = []
        self.state = AnomalyDetectorState(num_cached_recent_anomaly_states)
        self._detectors: List[Tuple[ScheduledDetector, float, float]] = []
        self._scheduler: Optional[threading.Thread] = None
        self._shutdown = threading.Event()

    # ------------------------------------------------------------------
    # intake (detectors call this as their report_fn)
    # ------------------------------------------------------------------
    def report(self, anomaly: Anomaly) -> None:
        now_ms = self._time() * 1000.0
        with self._lock:
            heapq.heappush(self._queue,
                           (anomaly.anomaly_type.value, next(self._seq),
                            anomaly))
        self.state.on_detected(anomaly, now_ms)

    @property
    def num_pending(self) -> int:
        with self._lock:
            return len(self._queue) + len(self._deferred)

    # ------------------------------------------------------------------
    # scheduled detection
    # ------------------------------------------------------------------
    def register_detector(self, detector: ScheduledDetector,
                          interval_s: float) -> None:
        """Register an object with detect_now() to run every interval_s
        (first run jittered into [0, interval) like the reference's
        scheduleAtFixedRate initial delays :190-222)."""
        first_due = self._time() + random.random() * interval_s
        self._detectors.append([detector, interval_s, first_due])

    def run_detection_once(self) -> None:
        """Run every registered detector immediately (test/demo surface)."""
        for entry in self._detectors:
            entry[0].detect_now()

    def _run_due_detections(self) -> None:
        now = self._time()
        for entry in self._detectors:
            detector, interval, due = entry
            if now >= due:
                try:
                    detector.detect_now()
                except Exception:  # noqa: BLE001 - keep the schedule alive
                    LOG.exception("detector %s failed",
                                  type(detector).__name__)
                entry[2] = now + interval

    # ------------------------------------------------------------------
    # handling (reference AnomalyHandlerTask :322-470)
    # ------------------------------------------------------------------
    def process_once(self) -> Optional[AnomalyState]:
        """Handle the highest-priority pending anomaly; returns its final
        handling status, or None if nothing was due."""
        now_ms = self._time() * 1000.0
        with self._lock:
            while self._deferred and self._deferred[0][0] <= now_ms:
                _, seq, anomaly = heapq.heappop(self._deferred)
                heapq.heappush(self._queue,
                               (anomaly.anomaly_type.value, seq, anomaly))
            if not self._queue:
                return None
            _, _, anomaly = heapq.heappop(self._queue)
        return self._handle(anomaly, now_ms)

    def process_all(self) -> List[AnomalyState]:
        out = []
        while True:
            st = self.process_once()
            if st is None:
                return out
            out.append(st)

    def _handle(self, anomaly: Anomaly, now_ms: float) -> AnomalyState:
        action = self._notifier.on_anomaly(anomaly)
        if action.result == AnomalyNotificationResult.IGNORE:
            status = AnomalyState.IGNORED
        elif action.result == AnomalyNotificationResult.CHECK:
            with self._lock:
                heapq.heappush(self._deferred,
                               (now_ms + action.delay_ms, next(self._seq),
                                anomaly))
            status = AnomalyState.CHECK_WITH_DELAY
        else:  # FIX
            if not self._ready():
                # monitor still warming up: keep the anomaly alive —
                # event-driven detectors (broker failures) won't re-report
                with self._lock:
                    heapq.heappush(self._deferred,
                                   (now_ms + 10_000.0, next(self._seq),
                                    anomaly))
                status = AnomalyState.LOAD_MONITOR_NOT_READY
            elif self._fix_in_progress():
                # re-check shortly: another fix is executing
                with self._lock:
                    heapq.heappush(self._deferred,
                                   (now_ms + 10_000.0, next(self._seq),
                                    anomaly))
                status = AnomalyState.CHECK_WITH_DELAY
            else:
                try:
                    started = anomaly.fix()
                except Exception:  # noqa: BLE001 - fix failure is a status
                    LOG.exception("fix for %s raised", anomaly.anomaly_id)
                    started = False
                status = (AnomalyState.FIX_STARTED if started
                          else AnomalyState.FIX_FAILED_TO_START)
        self.state.on_status(anomaly, status, now_ms)
        return status

    # ------------------------------------------------------------------
    # background scheduler (wall-clock deployments)
    # ------------------------------------------------------------------
    def start(self, tick_s: float = 1.0) -> None:
        if self._scheduler is not None:
            return
        self._shutdown.clear()

        def loop() -> None:
            while not self._shutdown.is_set():
                try:
                    self._run_due_detections()
                    while self.process_once() is not None:
                        pass
                except Exception:  # noqa: BLE001
                    LOG.exception("anomaly handler iteration failed")
                self._shutdown.wait(tick_s)

        self._scheduler = threading.Thread(target=loop,
                                           name="anomaly-detector",
                                           daemon=True)
        self._scheduler.start()

    def shutdown(self) -> None:
        self._shutdown.set()
        if self._scheduler is not None:
            self._scheduler.join(timeout=10.0)
            self._scheduler = None

    # ------------------------------------------------------------------
    def self_healing_enabled(self) -> Dict[AnomalyType, bool]:
        return self._notifier.self_healing_enabled()

    def set_self_healing_for(self, anomaly_type: AnomalyType,
                             enabled: bool) -> bool:
        return self._notifier.set_self_healing_for(anomaly_type, enabled)

    def to_json(self) -> dict:
        return self.state.to_json(self._notifier.self_healing_enabled())
