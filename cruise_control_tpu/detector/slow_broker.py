"""Slow-broker finder.

Reference CC/detector/SlowBrokerFinder.java:39-471 — two-signal detection:
(1) the raw log-flush-time metric against the broker's own history
percentile, and (2) the *derived* per-byte flush cost (flush time divided by
bytes-in rate) against both own history and the peer population percentile.
A broker must trip BOTH signals to be suspected.  Each suspicion raises the
broker's slowness score; scores decay when healthy.  Escalation: brokers
over the demotion score get a demote recommendation; persistently slow
brokers (score over the removal threshold) get a removal recommendation.

Vectorized re-design: histories arrive as arrays [broker, window]; all
percentile math is batched numpy (the monitor plane already keeps these as
device-friendly arrays; host numpy is fine at O(brokers × windows)).
"""
from __future__ import annotations

import dataclasses
import time as _time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from cruise_control_tpu.detector.anomalies import SlowBrokers


@dataclasses.dataclass
class SlowBrokerFinderConfig:
    """Reference config keys slow.broker.* (SlowBrokerFinder.java:54-90)."""

    #: own-history percentile the latest value must exceed
    history_percentile: float = 90.0
    #: own-history margin multiplier on that percentile
    history_margin: float = 3.0
    #: peer-population percentile the latest value must exceed
    peer_percentile: float = 50.0
    #: peer margin multiplier
    peer_margin: float = 3.0
    #: score added per detection; decayed by 1 per clean sweep
    score_per_detection: float = 1.0
    #: demote when score reaches this
    demotion_score: float = 5.0
    #: remove when score reaches this
    removal_score: float = 10.0
    #: ignore brokers whose bytes-in is below this (idle brokers flush slow)
    min_bytes_in_rate: float = 1024.0
    #: absolute log-flush-time floor: percentile detections only count
    #: when the latest flush time also exceeds this (reference
    #: slow.broker.log.flush.time.threshold.ms, ANDed via retainAll)
    log_flush_time_threshold_ms: float = 1000.0
    #: whether removal-level escalation may run its fix (reference
    #: self.healing.slow.broker.removal.enabled — demotion still applies)
    allow_removal: bool = True


#: self-healing factory: given the slow broker ids, start a fix; True if
#: one was started (lets the fix target exactly the brokers detected)
FixFactory = Callable[[List[int]], bool]


def _as_factory(fn) -> Optional[FixFactory]:
    """Accept either a plain FixFn (legacy, ignores broker ids) or a
    FixFactory."""
    if fn is None:
        return None
    import inspect
    try:
        takes_arg = len(inspect.signature(fn).parameters) >= 1
    except (TypeError, ValueError):
        takes_arg = False
    return fn if takes_arg else (lambda ids: fn())


class SlowBrokerDetector:
    """Scheduled adapter: assembles [broker, window] flush-time and
    bytes-in histories from the broker metric aggregator and runs the
    finder (the reference MetricAnomalyDetector feeds SlowBrokerFinder the
    same broker metric history)."""

    def __init__(self, broker_aggregator, finder: "SlowBrokerFinder") -> None:
        self._aggregator = broker_aggregator
        self._finder = finder
        from cruise_control_tpu.monitor import metricdef as MD
        bdef = MD.broker_metric_def()
        self._flush_id = bdef.metric_id(MD.BROKER_LOG_FLUSH_TIME_MS_999TH)
        self._lin_id = bdef.metric_id(MD.LEADER_BYTES_IN)
        self._rin_id = bdef.metric_id(MD.REPLICATION_BYTES_IN_RATE)

    def detect_now(self) -> Optional[SlowBrokers]:
        from cruise_control_tpu.core.aggregator import (
            NotEnoughValidWindowsError)
        try:
            result = self._aggregator.aggregate(-np.inf, np.inf)
        except NotEnoughValidWindowsError:
            return None   # warm-up: no broker history yet
        entities = sorted(result.entity_values,
                          key=lambda e: e.broker_id)
        if not entities:
            return None
        flush = np.stack([
            result.entity_values[e].values[:, self._flush_id]
            for e in entities])
        bytes_in = np.stack([
            result.entity_values[e].values[:, self._lin_id]
            + result.entity_values[e].values[:, self._rin_id]
            for e in entities])
        return self._finder.detect_now(
            [e.broker_id for e in entities], flush, bytes_in)


class SlowBrokerFinder:
    """Feed with per-sweep metric arrays; emits SlowBrokers anomalies."""

    def __init__(self, report_fn: Callable[[SlowBrokers], None],
                 config: Optional[SlowBrokerFinderConfig] = None,
                 demote_fix_fn=None,
                 remove_fix_fn=None,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self._cfg = config or SlowBrokerFinderConfig()
        self._report = report_fn
        self._demote_fix = _as_factory(demote_fix_fn)
        self._remove_fix = _as_factory(remove_fix_fn)
        self._time = time_fn or _time.time
        self._scores: Dict[int, float] = {}
        self._first_detect_ms: Dict[int, float] = {}

    @property
    def slowness_scores(self) -> Dict[int, float]:
        return dict(self._scores)

    # ------------------------------------------------------------------
    def detect_now(self, broker_ids: Sequence[int],
                   flush_time_history: np.ndarray,
                   bytes_in_history: np.ndarray) -> Optional[SlowBrokers]:
        """One sweep.  `flush_time_history`/`bytes_in_history` are
        [broker, window] with the LATEST window last; detection compares the
        latest window against history (all earlier windows).
        """
        cfg = self._cfg
        flush = np.asarray(flush_time_history, dtype=np.float64)
        bytes_in = np.asarray(bytes_in_history, dtype=np.float64)
        if flush.ndim != 2 or flush.shape[1] < 2:
            return None
        latest_flush = flush[:, -1]
        hist_flush = flush[:, :-1]
        per_byte = flush / np.maximum(bytes_in, 1.0)
        latest_pb = per_byte[:, -1]
        hist_pb = per_byte[:, :-1]

        # signal 1: raw flush time vs own history
        own_thresh = np.percentile(hist_flush, cfg.history_percentile,
                                   axis=1) * cfg.history_margin
        sig1 = latest_flush > own_thresh
        # signal 2: per-byte cost vs own history AND vs current peers
        own_pb_thresh = np.percentile(hist_pb, cfg.history_percentile,
                                      axis=1) * cfg.history_margin
        peer_thresh = np.percentile(latest_pb, cfg.peer_percentile) \
            * cfg.peer_margin
        sig2 = (latest_pb > own_pb_thresh) & (latest_pb > peer_thresh)
        # signal 3: the absolute flush-time floor is a NECESSARY condition
        # ANDed with the percentile detections (reference SlowBrokerFinder
        # retainAll over slow.broker.log.flush.time.threshold.ms)
        sig3 = latest_flush > cfg.log_flush_time_threshold_ms
        active = bytes_in[:, -1] >= cfg.min_bytes_in_rate
        suspected = sig1 & sig2 & sig3 & active

        now_ms = self._time() * 1000.0
        # brokers that stopped reporting (dead/removed) drop their scores —
        # otherwise a saturated score re-raises the anomaly forever
        present = set(broker_ids)
        for bid in [b for b in self._scores if b not in present]:
            del self._scores[bid]
            self._first_detect_ms.pop(bid, None)
        for i, bid in enumerate(broker_ids):
            if suspected[i]:
                self._scores[bid] = (self._scores.get(bid, 0.0)
                                     + cfg.score_per_detection)
                self._first_detect_ms.setdefault(bid, now_ms)
            elif bid in self._scores:
                self._scores[bid] -= cfg.score_per_detection
                if self._scores[bid] <= 0:
                    del self._scores[bid]
                    self._first_detect_ms.pop(bid, None)

        to_remove = {b: self._first_detect_ms[b]
                     for b, s in self._scores.items()
                     if s >= cfg.removal_score}
        to_demote = {b: self._first_detect_ms[b]
                     for b, s in self._scores.items()
                     if cfg.demotion_score <= s < cfg.removal_score}
        if to_remove:
            ids = sorted(to_remove)
            fix = (None if self._remove_fix is None
                   or not cfg.allow_removal
                   else (lambda f=self._remove_fix, i=ids: f(i)))
            anomaly = SlowBrokers(to_remove, remove_slow_brokers=True,
                                  fix_fn=fix, detected_ms=now_ms)
        elif to_demote:
            ids = sorted(to_demote)
            fix = (None if self._demote_fix is None
                   else (lambda f=self._demote_fix, i=ids: f(i)))
            anomaly = SlowBrokers(to_demote, remove_slow_brokers=False,
                                  fix_fn=fix, detected_ms=now_ms)
        else:
            return None
        self._report(anomaly)
        return anomaly
