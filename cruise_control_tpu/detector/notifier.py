"""Anomaly notifier SPI.

Reference CC/detector/notifier/: AnomalyNotifier decides per anomaly whether
to FIX now, CHECK again after a delay, or IGNORE.  SelfHealingNotifier
(SelfHealingNotifier.java:1-306) adds per-type self-healing enable flags and
a broker-failure grace period (alert threshold, then auto-fix threshold).
SlackSelfHealingNotifier (SlackSelfHealingNotifier.java:1-94) posts
alerts through a webhook; here the transport is an injected callable so the
framework stays dependency-free (zero egress in CI).
"""
from __future__ import annotations

import dataclasses
import enum
import logging
import time as _time
from collections import OrderedDict
from typing import Callable, Dict, Optional

from cruise_control_tpu.core.anomaly import Anomaly, AnomalyType
from cruise_control_tpu.detector.anomalies import BrokerFailures

LOG = logging.getLogger(__name__)


class AnomalyNotificationResult(enum.Enum):
    FIX = "FIX"
    CHECK = "CHECK"
    IGNORE = "IGNORE"


@dataclasses.dataclass(frozen=True)
class NotificationAction:
    result: AnomalyNotificationResult
    #: for CHECK: re-examine after this many ms
    delay_ms: float = 0.0

    @staticmethod
    def fix() -> "NotificationAction":
        return NotificationAction(AnomalyNotificationResult.FIX)

    @staticmethod
    def check(delay_ms: float) -> "NotificationAction":
        return NotificationAction(AnomalyNotificationResult.CHECK, delay_ms)

    @staticmethod
    def ignore() -> "NotificationAction":
        return NotificationAction(AnomalyNotificationResult.IGNORE)


class AnomalyNotifier:
    """SPI — reference AnomalyNotifier.java."""

    def on_anomaly(self, anomaly: Anomaly) -> NotificationAction:
        raise NotImplementedError

    def self_healing_enabled(self) -> Dict[AnomalyType, bool]:
        return {t: False for t in AnomalyType}

    def set_self_healing_for(self, anomaly_type: AnomalyType,
                             enabled: bool) -> bool:
        """Returns the previous value."""
        return False


class NoopNotifier(AnomalyNotifier):
    """Ignore everything (reference NoopNotifier.java)."""

    def on_anomaly(self, anomaly: Anomaly) -> NotificationAction:
        return NotificationAction.ignore()


class SelfHealingNotifier(AnomalyNotifier):
    """Grace-period + per-type-gated self-healing
    (reference SelfHealingNotifier.java).

    Broker failures honor two thresholds from the first failure time:
    before `alert_threshold_ms` nothing happens (transient restarts);
    between the thresholds an alert fires and the anomaly is re-CHECKed;
    after `auto_fix_threshold_ms` the fix starts.  Other anomaly types fix
    immediately when their type's self-healing is enabled.
    """

    DEFAULT_ALERT_THRESHOLD_MS = 15 * 60 * 1000.0
    DEFAULT_AUTO_FIX_THRESHOLD_MS = 30 * 60 * 1000.0

    def __init__(self,
                 self_healing_enabled: Optional[Dict[AnomalyType, bool]] = None,
                 broker_failure_alert_threshold_ms: float =
                 DEFAULT_ALERT_THRESHOLD_MS,
                 broker_failure_auto_fix_threshold_ms: float =
                 DEFAULT_AUTO_FIX_THRESHOLD_MS,
                 alert_fn: Optional[Callable[[Anomaly, bool], None]] = None,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self._enabled: Dict[AnomalyType, bool] = {
            t: False for t in AnomalyType}
        if self_healing_enabled:
            self._enabled.update(self_healing_enabled)
        self._alert_ms = broker_failure_alert_threshold_ms
        self._fix_ms = broker_failure_auto_fix_threshold_ms
        if self._fix_ms < self._alert_ms:
            raise ValueError("auto-fix threshold must be >= alert threshold")
        self._alert_fn = alert_fn
        self._time = time_fn or _time.time
        # anomaly ids already alerted — deduped so deferred re-checks don't
        # alert again; bounded FIFO so long-lived processes don't leak
        self._alerted: "OrderedDict[str, bool]" = OrderedDict()
        self._max_alerted = 4096

    def _first_alert(self, anomaly: Anomaly) -> bool:
        """True exactly once per anomaly id."""
        if anomaly.anomaly_id in self._alerted:
            return False
        self._alerted[anomaly.anomaly_id] = True
        while len(self._alerted) > self._max_alerted:
            self._alerted.popitem(last=False)
        return True

    # ------------------------------------------------------------------
    def self_healing_enabled(self) -> Dict[AnomalyType, bool]:
        return dict(self._enabled)

    def set_self_healing_for(self, anomaly_type: AnomalyType,
                             enabled: bool) -> bool:
        old = self._enabled[anomaly_type]
        self._enabled[anomaly_type] = enabled
        return old

    # ------------------------------------------------------------------
    def on_anomaly(self, anomaly: Anomaly) -> NotificationAction:
        if isinstance(anomaly, BrokerFailures):
            return self._on_broker_failure(anomaly)
        heal = self._enabled.get(anomaly.anomaly_type, False)
        if self._first_alert(anomaly):
            self._alert(anomaly, auto_fix=heal)
        return (NotificationAction.fix() if heal
                else NotificationAction.ignore())

    def _on_broker_failure(self, anomaly: BrokerFailures
                           ) -> NotificationAction:
        now_ms = self._time() * 1000.0
        if not anomaly.failed_brokers_by_time_ms:
            return NotificationAction.ignore()
        earliest = min(anomaly.failed_brokers_by_time_ms.values())
        alert_at = earliest + self._alert_ms
        fix_at = earliest + self._fix_ms
        if now_ms < alert_at:
            return NotificationAction.check(alert_at - now_ms)
        heal = self._enabled.get(AnomalyType.BROKER_FAILURE, False)
        if self._first_alert(anomaly):
            self._alert(anomaly, auto_fix=heal)
        if not heal:
            return NotificationAction.ignore()
        if now_ms < fix_at:
            return NotificationAction.check(fix_at - now_ms)
        return NotificationAction.fix()

    def _alert(self, anomaly: Anomaly, auto_fix: bool) -> None:
        LOG.warning("anomaly alert: %s (self-healing=%s)", anomaly, auto_fix)
        if self._alert_fn is not None:
            try:
                self._alert_fn(anomaly, auto_fix)
            except Exception:  # noqa: BLE001 - alerts must not break healing
                LOG.exception("alert delivery failed")


class WebhookSelfHealingNotifier(SelfHealingNotifier):
    """Alert via an injected webhook poster
    (reference SlackSelfHealingNotifier.java posts JSON to a Slack webhook;
    `post_fn(payload_dict)` abstracts the HTTP call)."""

    def __init__(self, post_fn: Callable[[dict], None], **kwargs) -> None:
        def alert(anomaly: Anomaly, auto_fix: bool) -> None:
            post_fn({
                "text": f"{anomaly.anomaly_type.name}: {anomaly}",
                "anomalyId": anomaly.anomaly_id,
                "selfHealing": auto_fix,
            })
        super().__init__(alert_fn=alert, **kwargs)
