"""Disk-failure detector (JBOD).

Reference CC/detector/DiskFailureDetector.java:1-123: periodically calls
describeLogDirs on alive brokers and raises a DiskFailures anomaly for any
offline logdir.
"""
from __future__ import annotations

import time as _time
from typing import Callable, Dict, List, Optional

from cruise_control_tpu.cluster.admin import ClusterAdminClient
from cruise_control_tpu.detector.anomalies import DiskFailures, FixFn


class DiskFailureDetector:
    def __init__(self, admin: ClusterAdminClient,
                 report_fn: Callable[[DiskFailures], None],
                 fix_fn: Optional[FixFn] = None,
                 anomaly_cls=None,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self._admin = admin
        self._report = report_fn
        self._fix_fn = fix_fn
        #: reference disk.failures.class
        self._anomaly_cls = anomaly_cls or DiskFailures
        self._time = time_fn or _time.time

    def detect_now(self) -> Optional[DiskFailures]:
        snapshot = self._admin.describe_cluster()
        logdirs = self._admin.describe_log_dirs(
            sorted(snapshot.alive_broker_ids))
        failed: Dict[int, List[str]] = {}
        for broker_id, dirs in logdirs.items():
            offline = [d.path for d in dirs if d.offline]
            if offline:
                failed[broker_id] = offline
        if not failed:
            return None
        anomaly = self._anomaly_cls(
            failed_disks_by_broker=failed, fix_fn=self._fix_fn,
            detected_ms=self._time() * 1000.0)
        self._report(anomaly)
        return anomaly
