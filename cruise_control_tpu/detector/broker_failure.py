"""Broker-failure detector.

Reference CC/detector/BrokerFailureDetector.java:44-237: subscribes to the
cluster's liveness watch (ZK /brokers/ids child watch there; the
ClusterAdminClient liveness listener here), keeps the set of failed brokers
with their first-observed failure time, persists that table so failure ages
survive restarts (reference persisted a ZK znode; here a pluggable store,
default file-backed JSON), and gates fixability on count/percentage
thresholds.
"""
from __future__ import annotations

import json
import logging
import threading
import time as _time
from typing import Callable, Dict, Optional, Set

from cruise_control_tpu.cluster.admin import ClusterAdminClient
from cruise_control_tpu.detector.anomalies import BrokerFailures, FixFn
from cruise_control_tpu.utils import persist

LOG = logging.getLogger(__name__)


class FailedBrokerStore:
    """Persistence SPI for failure times (reference's ZK-path persistence)."""

    def load(self) -> Dict[int, float]:
        return {}

    def save(self, failed: Dict[int, float]) -> None:
        pass


class FileFailedBrokerStore(FailedBrokerStore):
    def __init__(self, path: str) -> None:
        self._path = path

    def load(self) -> Dict[int, float]:
        try:
            with open(self._path) as f:
                return {int(k): float(v) for k, v in json.load(f).items()}
        except (OSError, ValueError):
            return {}

    def save(self, failed: Dict[int, float]) -> None:
        # shared durable-write helper (utils/persist.py): atomic
        # publication so a crash mid-save never truncates the table
        persist.atomic_write_json(
            self._path, {str(k): v for k, v in failed.items()})


class BrokerFailureDetector:
    """Event-driven detector; reports via a queue-insert callback."""

    def __init__(self, admin: ClusterAdminClient,
                 report_fn: Callable[[BrokerFailures], None],
                 fix_fn: Optional[FixFn] = None,
                 store: Optional[FailedBrokerStore] = None,
                 fixable_max_count: int = 10,
                 fixable_max_ratio: float = 0.4,
                 detection_backoff_s: float = 300.0,
                 anomaly_cls=None,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self._admin = admin
        self._report = report_fn
        self._fix_fn = fix_fn
        self._store = store or FailedBrokerStore()
        self._fixable_max_count = fixable_max_count
        self._fixable_max_ratio = fixable_max_ratio
        #: min delay between full re-detections for the SAME failure set
        #: (reference broker.failure.detection.backoff.ms)
        self._detection_backoff_s = detection_backoff_s
        self._last_detect_s = -float("inf")
        #: reference broker.failures.class
        self._anomaly_cls = anomaly_cls or BrokerFailures
        self._time = time_fn or _time.time
        self._lock = threading.Lock()
        self._failed: Dict[int, float] = self._store.load()
        self._listener = self._on_liveness_change
        self._started = False

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._admin.add_liveness_listener(self._listener)
        self._started = True
        self.detect_now(force=True)  # catch pre-watch failures

    def shutdown(self) -> None:
        if self._started:
            self._admin.remove_liveness_listener(self._listener)
            self._started = False

    def failed_brokers(self) -> Dict[int, float]:
        with self._lock:
            return dict(self._failed)

    # ------------------------------------------------------------------
    def detect_now(self, force: bool = False) -> None:
        # scheduled sweeps back off between full re-detections; the
        # event-driven liveness listener is never throttled (reference
        # broker.failure.detection.backoff.ms)
        now = self._time()
        if not force and now - self._last_detect_s \
                < self._detection_backoff_s:
            return
        self._last_detect_s = now
        snapshot = self._admin.describe_cluster()
        self._update(snapshot.alive_broker_ids, snapshot.all_broker_ids)

    def _on_liveness_change(self, alive: Set[int]) -> None:
        snapshot = self._admin.describe_cluster()
        self._update(alive, snapshot.all_broker_ids)

    def _update(self, alive: Set[int], all_ids: Set[int]) -> None:
        now_ms = self._time() * 1000.0
        with self._lock:
            dead = set(all_ids) - set(alive)
            # new failures keep their first-observed time
            for b in dead:
                self._failed.setdefault(b, now_ms)
            # recovered brokers drop out
            for b in list(self._failed):
                if b not in dead:
                    del self._failed[b]
            failed = dict(self._failed)
            self._store.save(failed)
            total = max(1, len(all_ids))
        if failed:
            fixable = (len(failed) <= self._fixable_max_count
                       and len(failed) / total <= self._fixable_max_ratio)
            if not fixable:
                LOG.warning(
                    "%d/%d brokers failed — beyond self-healing thresholds, "
                    "reporting without fix", len(failed), total)
            self._report(self._anomaly_cls(
                failed_brokers_by_time_ms=failed,
                fix_fn=self._fix_fn if fixable else None,
                detected_ms=now_ms))
