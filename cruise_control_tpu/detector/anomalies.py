"""Concrete anomaly types.

Reference CC/detector/ anomaly classes (GoalViolations.java:1-130,
BrokerFailures.java, DiskFailures.java, SlowBrokers.java,
TopicReplicationFactorAnomaly.java): each anomaly carries enough context to
describe itself and a fix callable that routes through the normal
optimize+execute path (self-healing reuses the rebalance machinery,
SURVEY.md §3.5).  Fix callables are injected by whoever wires the detector
(the facade), keeping the detector plane free of circular dependencies.
"""
from __future__ import annotations

import dataclasses
import uuid as _uuid
from typing import Callable, Dict, List, Optional

from cruise_control_tpu.core.anomaly import Anomaly, AnomalyType

#: a self-healing action: returns True if a fix was started
FixFn = Callable[[], bool]


def _new_id(prefix: str) -> str:
    return f"{prefix}-{_uuid.uuid4().hex[:12]}"


@dataclasses.dataclass
class GoalViolations(Anomaly):
    """Detection goals found violations (reference GoalViolations.java).

    `fixable_violated_goals` get self-healed by one rebalance run over the
    full configured goal list; `unfixable` ones are only reported."""

    fixable_violated_goals: List[str]
    unfixable_violated_goals: List[str]
    fix_fn: Optional[FixFn] = None
    detected_ms: float = 0.0
    _id: str = dataclasses.field(default_factory=lambda: _new_id("goal-viol"))

    @property
    def anomaly_type(self) -> AnomalyType:
        return AnomalyType.GOAL_VIOLATION

    @property
    def anomaly_id(self) -> str:
        return self._id

    def fix(self) -> bool:
        if self.fix_fn is None or not self.fixable_violated_goals:
            return False
        return self.fix_fn()

    def __str__(self) -> str:
        return (f"GoalViolations(fixable={self.fixable_violated_goals}, "
                f"unfixable={self.unfixable_violated_goals})")


@dataclasses.dataclass
class BrokerFailures(Anomaly):
    """Dead brokers with their first-observed failure times
    (reference BrokerFailures.java)."""

    failed_brokers_by_time_ms: Dict[int, float]
    fix_fn: Optional[FixFn] = None
    detected_ms: float = 0.0
    _id: str = dataclasses.field(
        default_factory=lambda: _new_id("broker-failure"))

    @property
    def anomaly_type(self) -> AnomalyType:
        return AnomalyType.BROKER_FAILURE

    @property
    def anomaly_id(self) -> str:
        return self._id

    def fix(self) -> bool:
        if self.fix_fn is None or not self.failed_brokers_by_time_ms:
            return False
        return self.fix_fn()

    def __str__(self) -> str:
        return f"BrokerFailures({sorted(self.failed_brokers_by_time_ms)})"


@dataclasses.dataclass
class DiskFailures(Anomaly):
    """Offline logdirs by broker (reference DiskFailures.java)."""

    failed_disks_by_broker: Dict[int, List[str]]
    fix_fn: Optional[FixFn] = None
    detected_ms: float = 0.0
    _id: str = dataclasses.field(
        default_factory=lambda: _new_id("disk-failure"))

    @property
    def anomaly_type(self) -> AnomalyType:
        return AnomalyType.DISK_FAILURE

    @property
    def anomaly_id(self) -> str:
        return self._id

    def fix(self) -> bool:
        if self.fix_fn is None or not self.failed_disks_by_broker:
            return False
        return self.fix_fn()

    def __str__(self) -> str:
        return f"DiskFailures({self.failed_disks_by_broker})"


@dataclasses.dataclass
class SlowBrokers(Anomaly):
    """Brokers judged slow by the slowness score, with the recommended
    remediation (reference SlowBrokers.java + SlowBrokerFinder escalation:
    demote first, remove when persistent)."""

    slow_brokers_by_time_ms: Dict[int, float]
    remove_slow_brokers: bool        # False => demote
    fix_fn: Optional[FixFn] = None
    detected_ms: float = 0.0
    _id: str = dataclasses.field(
        default_factory=lambda: _new_id("slow-broker"))

    @property
    def anomaly_type(self) -> AnomalyType:
        return AnomalyType.METRIC_ANOMALY

    @property
    def anomaly_id(self) -> str:
        return self._id

    def fix(self) -> bool:
        if self.fix_fn is None or not self.slow_brokers_by_time_ms:
            return False
        return self.fix_fn()

    def __str__(self) -> str:
        verb = "remove" if self.remove_slow_brokers else "demote"
        return f"SlowBrokers({sorted(self.slow_brokers_by_time_ms)}, {verb})"


@dataclasses.dataclass
class SolverDegraded(Anomaly):
    """The goal solver degraded: a rung descent on the degradation
    ladder (fused → eager → CPU) or a circuit-breaker trip
    (analyzer/degradation.py).  Notification-only — the ladder itself is
    the remediation; this anomaly routes the event through the normal
    notifier plane (webhook/log) so operators see solver trouble exactly
    like cluster trouble."""

    from_rung: str
    to_rung: Optional[str]          # None: the bottom rung itself failed
    failure_kind: str               # degradation.FailureKind value
    breaker_tripped: bool
    description: str = ""
    detected_ms: float = 0.0
    _id: str = dataclasses.field(
        default_factory=lambda: _new_id("solver-degraded"))

    @property
    def anomaly_type(self) -> AnomalyType:
        return AnomalyType.SOLVER_DEGRADATION

    @property
    def anomaly_id(self) -> str:
        return self._id

    def fix(self) -> bool:
        return False   # the ladder already degraded/recovered by itself

    def __str__(self) -> str:
        if self.breaker_tripped:
            arrow = (f"breaker OPEN, pinned at "
                     f"{self.to_rung or self.from_rung}")
        elif self.to_rung:
            arrow = f"{self.from_rung}->{self.to_rung}"
        else:
            arrow = f"{self.from_rung} (bottom rung failed)"
        return (f"SolverDegraded({arrow}, kind={self.failure_kind}, "
                f"breakerTripped={self.breaker_tripped}, "
                f"{self.description})")


@dataclasses.dataclass
class MeshDegraded(Anomaly):
    """The solve mesh degraded: a watchdog fire (wedged dispatch), a
    condemned chip, or a span shrink in the mesh supervisor
    (parallel/health.MeshSupervisor).  Notification-only — the span
    ladder MESH8→MESH4→MESH2→FUSED is itself the remediation and probe
    recovery climbs back when the chips return; this anomaly routes
    the event (and its flight-recorder dump) through the notifier
    plane so operators see substrate trouble exactly like cluster
    trouble."""

    from_span: int
    to_span: int
    condemned_devices: List[int]
    watchdog_fired: bool
    failure_kind: str               # degradation.FailureKind value
    description: str = ""
    detected_ms: float = 0.0
    _id: str = dataclasses.field(
        default_factory=lambda: _new_id("mesh-degraded"))

    @property
    def anomaly_type(self) -> AnomalyType:
        return AnomalyType.MESH_DEGRADATION

    @property
    def anomaly_id(self) -> str:
        return self._id

    def fix(self) -> bool:
        return False   # the span ladder already shrank/recovered

    def __str__(self) -> str:
        return (f"MeshDegraded(span {self.from_span}->{self.to_span}, "
                f"condemned={self.condemned_devices or []}, "
                f"watchdogFired={self.watchdog_fired}, "
                f"kind={self.failure_kind}, {self.description})")


@dataclasses.dataclass
class ExecutionRecovery(Anomaly):
    """An interrupted execution was reconciled at startup
    (executor/recovery.py), or the executor journal degraded to
    journal-less operation mid-execution.  Notification-only — the
    recovery already resumed/aborted the execution; this anomaly routes
    the evidence through the notifier plane so operators see a process
    bounce mid-rebalance exactly like cluster trouble."""

    uuid: str
    mode: str                        # resume | abort | journal-degraded
    resumed: bool
    tasks_terminal: int = 0
    tasks_adopted: int = 0
    tasks_pending: int = 0
    cleared_throttle_brokers: List[int] = dataclasses.field(
        default_factory=list)
    journal_degraded: bool = False
    description: str = ""
    detected_ms: float = 0.0
    _id: str = dataclasses.field(
        default_factory=lambda: _new_id("execution-recovery"))

    @property
    def anomaly_type(self) -> AnomalyType:
        return AnomalyType.EXECUTION_RECOVERY

    @property
    def anomaly_id(self) -> str:
        return self._id

    def fix(self) -> bool:
        return False   # recovery already settled the execution

    def __str__(self) -> str:
        if self.journal_degraded:
            return (f"ExecutionRecovery(journal degraded to "
                    f"journal-less execution: {self.description})")
        return (f"ExecutionRecovery({self.uuid}, mode={self.mode}, "
                f"resumed={self.resumed}, terminal={self.tasks_terminal}"
                f", adopted={self.tasks_adopted}, "
                f"pending={self.tasks_pending}, "
                f"clearedThrottles={self.cleared_throttle_brokers}, "
                f"{self.description})")


@dataclasses.dataclass
class SloBurn(Anomaly):
    """A scheduler class is burning its SLO error budget faster than
    the alert threshold (obs/slo.py: burn computed live from the
    sched-*-hist histograms over a sliding window).  Notification-only
    — there is no automated fix; the runbook (docs/OPERATIONS.md §5
    "SLO burn") distinguishes queue-wait burn (admission pressure:
    shed SCENARIO_SWEEP, raise capacity) from device-time burn (solves
    got slower: ladder rung, cache storms, model growth).  One anomaly
    per breach EPISODE: the detector re-arms only after the burn drops
    back under 1.0 (detector/slo_burn.py)."""

    scheduler_class: str
    burn: float
    queue_wait_burn: float
    device_time_burn: float
    window_s: float
    alert_threshold: float
    objective: Dict[str, float] = dataclasses.field(default_factory=dict)
    description: str = ""
    detected_ms: float = 0.0
    _id: str = dataclasses.field(
        default_factory=lambda: _new_id("slo-burn"))

    @property
    def anomaly_type(self) -> AnomalyType:
        return AnomalyType.SLO_BURN

    @property
    def anomaly_id(self) -> str:
        return self._id

    def fix(self) -> bool:
        return False   # operational remediation only (runbook)

    def __str__(self) -> str:
        dominant = ("queue-wait" if self.queue_wait_burn
                    >= self.device_time_burn else "device-time")
        return (f"SloBurn({self.scheduler_class}: burn={self.burn:.2f}x "
                f"budget over {self.window_s:.0f}s [{dominant}-driven: "
                f"queueWait={self.queue_wait_burn:.2f} "
                f"deviceTime={self.device_time_burn:.2f}], alert at "
                f"{self.alert_threshold:.1f}x, {self.description})")


@dataclasses.dataclass
class TopicAnomaly(Anomaly):
    """Topics violating a policy — e.g. replication factor != target
    (reference TopicReplicationFactorAnomaly.java) or oversized partitions
    (PartitionSizeAnomalyFinder)."""

    description: str
    topics: List[str]
    fix_fn: Optional[FixFn] = None
    detected_ms: float = 0.0
    _id: str = dataclasses.field(
        default_factory=lambda: _new_id("topic-anomaly"))

    @property
    def anomaly_type(self) -> AnomalyType:
        return AnomalyType.TOPIC_ANOMALY

    @property
    def anomaly_id(self) -> str:
        return self._id

    def fix(self) -> bool:
        if self.fix_fn is None:
            return False
        return self.fix_fn()

    def __str__(self) -> str:
        return f"TopicAnomaly({self.description}, topics={self.topics})"
