"""Topic anomaly finders.

Reference CC/detector/TopicReplicationFactorAnomalyFinder.java:1-286 (topics
whose replication factor differs from the target, with min.insync.replicas
read from topic configs as a floor) and PartitionSizeAnomalyFinder.java:1-129
(partitions larger than a threshold).
"""
from __future__ import annotations

import time as _time
from typing import Callable, Dict, List, Optional

from cruise_control_tpu.cluster.admin import ClusterAdminClient
from cruise_control_tpu.detector.anomalies import FixFn, TopicAnomaly


class TopicReplicationFactorAnomalyFinder:
    def __init__(self, admin: ClusterAdminClient,
                 report_fn: Callable[[TopicAnomaly], None],
                 target_replication_factor: int = 3,
                 min_isr_margin: int = 1,
                 fix_fn: Optional[FixFn] = None,
                 topic_pattern: Optional[str] = None,
                 topic_config_provider=None,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self._admin = admin
        #: reference topic.config.provider.class (min.insync.replicas
        #: lookups go through the provider SPI)
        self._topic_configs = (topic_config_provider.topic_configs
                               if topic_config_provider is not None
                               else admin.topic_configs)
        self._report = report_fn
        self._target_rf = target_replication_factor
        #: required headroom above min.insync.replicas (reference
        #: topic.replication.factor.margin)
        self._min_isr_margin = min_isr_margin
        self._fix_fn = fix_fn
        self._pattern = topic_pattern
        self._time = time_fn or _time.time

    def detect_now(self) -> Optional[TopicAnomaly]:
        import re
        snapshot = self._admin.describe_cluster()
        pat = re.compile(self._pattern) if self._pattern else None
        bad: Dict[str, int] = {}
        for topic in sorted(snapshot.topics):
            if pat is not None and not pat.match(topic):
                continue
            # min.insync.replicas floors the acceptable RF (reference reads
            # topic configs for minISR before flagging under-replication)
            try:
                # cc-lint: disable=D301 -- Kafka TOPIC config lookup on
                # the admin client, not a framework ConfigDef key
                min_isr = int(self._topic_configs(topic).get(
                    "min.insync.replicas", 1))
            except (TypeError, ValueError):
                min_isr = 1
            target = max(self._target_rf, min_isr + self._min_isr_margin)
            rfs = {len(p.replicas) for p in snapshot.partitions_of(topic)}
            if any(rf != target for rf in rfs):
                bad[topic] = target
        if not bad:
            return None
        anomaly = TopicAnomaly(
            description=(f"topics with replication factor != target: "
                         f"{sorted(bad)}"),
            topics=sorted(bad), fix_fn=self._fix_fn,
            detected_ms=self._time() * 1000.0)
        self._report(anomaly)
        return anomaly


class PartitionSizeAnomalyFinder:
    def __init__(self, admin: ClusterAdminClient,
                 report_fn: Callable[[TopicAnomaly], None],
                 size_threshold_bytes: float = 1 << 40,
                 partition_size_fn: Optional[Callable[[str, int], float]]
                 = None,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self._admin = admin
        self._report = report_fn
        self._threshold = size_threshold_bytes
        self._size_fn = partition_size_fn
        self._time = time_fn or _time.time

    def detect_now(self) -> Optional[TopicAnomaly]:
        if self._size_fn is None:
            return None
        snapshot = self._admin.describe_cluster()
        oversized: List[str] = []
        for p in snapshot.partitions:
            if self._size_fn(p.tp.topic, p.tp.partition) > self._threshold:
                oversized.append(str(p.tp))
        if not oversized:
            return None
        anomaly = TopicAnomaly(
            description=f"partitions over {self._threshold:.0f} bytes: "
                        f"{oversized[:20]}",
            topics=sorted({s.rsplit('-', 1)[0] for s in oversized}),
            detected_ms=self._time() * 1000.0)
        self._report(anomaly)
        return anomaly
