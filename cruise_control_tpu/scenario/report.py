"""Scenario ranking + diff against the base solve.

Turns a `ScenarioBatchResult` into the SCENARIOS endpoint's response
body: scenarios ranked best-first (feasible before infeasible, then by
balancedness, then by movement cost — a better-balanced outcome that
moves less data wins), each carrying a delta block against the base
solve (the no-op scenario the facade prepends) so an operator reads
"what does this buy me over doing nothing" directly.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from cruise_control_tpu.scenario.engine import (BASE_SCENARIO_NAME,
                                                ScenarioBatchResult,
                                                ScenarioOutcome)


def balancedness_score(goal_names: List[str], hard_goal_names: frozenset,
                       violated_after: List[str],
                       weights: Tuple[float, float]) -> float:
    """[0, 100] — the OptimizerResult.balancedness_score formula over
    plain lists (the batched path has no OptimizerResult per scenario)."""
    from cruise_control_tpu.analyzer.goals.base import \
        balancedness_cost_by_goal
    if not goal_names:
        return 100.0
    pw, sw = weights
    costs = balancedness_cost_by_goal(goal_names, hard_goal_names, pw, sw)
    violated = set(violated_after)
    kept = sum(c for n, c in costs.items() if n not in violated)
    total = sum(costs.values())
    return 100.0 * kept / total if total else 100.0


def rank(outcomes: List[ScenarioOutcome]) -> List[ScenarioOutcome]:
    """Best first.  The base scenario ranks with everything else — if
    doing nothing beats every what-if, the report should say so."""
    def key(o: ScenarioOutcome):
        return (not o.feasible,
                len(o.violated_goals_after),
                -o.balancedness,
                o.data_to_move,
                o.num_replica_moves,
                o.spec.name)
    return sorted(outcomes, key=key)


def _stat(value) -> Optional[float]:
    if value is None:
        return None
    v = float(np.asarray(value))
    return None if not np.isfinite(v) else round(v, 6)


def _stats_json(stats) -> dict:
    if stats is None:
        return {}
    util_std = np.asarray(stats.util_std, dtype=float)
    util_max = np.asarray(stats.util_max, dtype=float)
    names = ("cpu", "nw_in", "nw_out", "disk")
    return {
        "utilStd": {n: _stat(util_std[i]) for i, n in enumerate(names)},
        "utilMax": {n: _stat(util_max[i]) for i, n in enumerate(names)},
        "replicaCountStd": _stat(stats.replica_count_std),
        "leaderCountStd": _stat(stats.leader_count_std),
        "numAliveBrokers": int(np.asarray(stats.num_alive_brokers)),
        "numOfflineReplicas": int(np.asarray(stats.num_offline_replicas)),
    }


def outcome_json(o: ScenarioOutcome, base: Optional[ScenarioOutcome],
                 verbose: bool = False) -> dict:
    out: dict = {
        "name": o.spec.name,
        "feasible": o.feasible,
        "rung": o.rung,
        "balancedness": round(o.balancedness, 3),
        "numReplicaMoves": o.num_replica_moves,
        "numLeadershipMoves": o.num_leadership_moves,
        "dataToMoveMB": round(o.data_to_move / 1e6, 3),
        "violatedGoalsBefore": list(o.violated_goals_before),
        "violatedGoalsAfter": list(o.violated_goals_after),
        "statsAfter": _stats_json(o.stats_after),
    }
    if not o.feasible:
        out["reason"] = o.reason
    if base is not None and base is not o:
        out["vsBase"] = {
            "balancednessDelta": round(o.balancedness - base.balancedness,
                                       3),
            "violatedGoalsAfterDelta": (len(o.violated_goals_after)
                                        - len(base.violated_goals_after)),
            "dataToMoveDeltaMB": round(
                (o.data_to_move - base.data_to_move) / 1e6, 3),
            "numReplicaMovesDelta": (o.num_replica_moves
                                     - base.num_replica_moves),
        }
    if verbose:
        out["violatedBrokerCounts"] = {
            g: list(c) for g, c in o.violated_broker_counts.items()}
        out["roundsByGoal"] = dict(o.rounds_by_goal)
        out["statsBefore"] = _stats_json(o.stats_before)
        out["proposals"] = [p.to_json() for p in o.proposals]
    else:
        out["numProposals"] = len(o.proposals)
    return out


def batch_report(result: ScenarioBatchResult,
                 verbose: bool = False) -> Dict:
    """The SCENARIOS 200 response body (dry-run analysis; never carries
    an execution id — the engine cannot execute)."""
    base = result.outcome(BASE_SCENARIO_NAME)
    ranked = rank(result.outcomes)
    return {
        "scenarios": [outcome_json(o, base, verbose=verbose)
                      for o in ranked if o.spec.name != BASE_SCENARIO_NAME],
        "base": (outcome_json(base, None, verbose=verbose)
                 if base is not None else None),
        "batch": {
            "numScenarios": len(result.outcomes),
            "rung": result.rung,
            "oomHalvings": result.oom_halvings,
            "deviceBatchSizes": list(result.batch_sizes),
            "compileS": round(result.compile_s, 3),
            "solveS": round(result.solve_s, 3),
            "durationS": round(result.duration_s, 3),
        },
        "dryRun": True,
        "version": 1,
    }
