"""Batched what-if scenario engine.

`spec` — declarative ScenarioSpec + JSON schema; `compiler` — K specs ->
one padded, stacked tensor batch; `engine` — vmapped runs of the fused
goal pipeline with OOM halving and ladder degradation; `report` —
ranking + diff against the base solve.  See docs/SCENARIOS.md.
"""
from cruise_control_tpu.scenario.engine import (BASE_SCENARIO_NAME,
                                                ScenarioBatchResult,
                                                ScenarioEngine,
                                                ScenarioOutcome)
from cruise_control_tpu.scenario.spec import (SCENARIO_SPEC_SCHEMA,
                                              SCENARIOS_REQUEST_SCHEMA,
                                              BrokerAdd, ScenarioSpec,
                                              ScenarioSpecError,
                                              candidate_broker_sets,
                                              parse_scenarios_payload)

__all__ = [
    "BASE_SCENARIO_NAME", "BrokerAdd", "SCENARIO_SPEC_SCHEMA",
    "SCENARIOS_REQUEST_SCHEMA", "ScenarioBatchResult", "ScenarioEngine",
    "ScenarioOutcome", "ScenarioSpec", "ScenarioSpecError",
    "candidate_broker_sets", "parse_scenarios_payload",
]
