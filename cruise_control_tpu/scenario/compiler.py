"""Scenario compiler: K specs + one base ClusterModel -> one stacked
tensor batch.

Pure host-side assembly (numpy; zero device dispatch): each spec is
materialized into a variant `ClusterState` sharing ONE padded shape with
every other variant of the batch — heterogeneous scenarios (different
broker counts, new racks/hosts) pad the broker/rack/host axes to the
batch maximum, reusing the leading-axis padding helper of
`parallel/mesh.py` — and the variants then stack along a new leading
scenario axis so the engine can `vmap` the fused goal pipeline over them
(one compile amortized over K scenarios).

Padded broker rows are dead (`broker_alive=False`) with zero capacity
and hold no replicas: every statistic and goal masks on `broker_alive`,
so a scenario with fewer brokers can never leak padded-broker load into
its stats (pinned in tests/test_scenario.py).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                 OptimizationContext,
                                                 OptimizationOptions,
                                                 make_context)
from cruise_control_tpu.common.resources import NUM_RESOURCES
from cruise_control_tpu.model.builder import ClusterTopology
from cruise_control_tpu.model.state import ClusterState
from cruise_control_tpu.scenario.spec import ScenarioSpec, ScenarioSpecError


@dataclasses.dataclass
class CompiledBatch:
    """K materialized variants of one base model, ready to stack.

    `states`/`contexts` are LISTS of per-scenario pytrees with identical
    shapes and static fields; `stack()` turns them into the leading-axis
    batch the engine vmaps over.  `topologies` carries the per-scenario
    name<->index maps (added brokers extend them) for the host-side
    proposal diff."""

    specs: List[ScenarioSpec]
    states: List[ClusterState]
    contexts: List[OptimizationContext]
    topologies: List[ClusterTopology]
    num_brokers: int
    #: i32[P, RF] host-side partition->replica rows: replica/partition
    #: membership is scenario-invariant (specs mutate brokers and loads,
    #: never membership), so ONE table serves every scenario's diff
    partition_rows: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros((0, 1), np.int32))
    #: scenario batches share one base model: replica membership (and the
    #: initial placement) is identical across the batch, so the engine
    #: fetches placement row 0 once for every lane's diff.  Cross-tenant
    #: FLEET batches (fleet/router.py) stack DIFFERENT base models: set
    #: False and provide `partition_rows_per` so each lane diffs against
    #: its own membership/placement.
    shared_membership: bool = True
    #: per-lane partition->replica rows when membership differs per lane
    partition_rows_per: Optional[List[np.ndarray]] = None

    def stack(self) -> Tuple[ClusterState, OptimizationContext]:
        import jax
        import jax.numpy as jnp
        stacked_state = jax.tree.map(lambda *xs: jnp.stack(xs),
                                     *self.states)
        stacked_ctx = jax.tree.map(lambda *xs: jnp.stack(xs),
                                   *self.contexts)
        return stacked_state, stacked_ctx

    def rows_of(self, i: int) -> np.ndarray:
        """Partition->replica rows for lane i's host diff."""
        if self.partition_rows_per is not None:
            return self.partition_rows_per[i]
        return self.partition_rows

    def with_table_slots(self, slots: int) -> "CompiledBatch":
        """Same batch with every context re-widened to `slots` (the
        fleet router's _TableOverflow re-run; mirrors the
        table_slots_override re-compile in compile_batch)."""
        return dataclasses.replace(
            self, contexts=[c if c.table_slots == slots
                            else dataclasses.replace(c, table_slots=slots)
                            for c in self.contexts])

    def slice(self, start: int, stop: Optional[int]) -> "CompiledBatch":
        """Sub-batch view (the OOM-halving retry re-dispatches halves
        without re-materializing anything)."""
        return CompiledBatch(
            specs=self.specs[start:stop], states=self.states[start:stop],
            contexts=self.contexts[start:stop],
            topologies=self.topologies[start:stop],
            num_brokers=self.num_brokers,
            partition_rows=self.partition_rows,
            shared_membership=self.shared_membership,
            partition_rows_per=(None if self.partition_rows_per is None
                                else self.partition_rows_per[start:stop]))


def _batch_geometry(base_state: ClusterState, topology: ClusterTopology,
                    specs: Sequence[ScenarioSpec]):
    """Shared padded sizes for the batch: broker count, rack/host counts
    (hypothetical brokers may introduce new racks; each gets its own
    host), and the per-spec hypothetical-broker orderings."""
    base_b = base_state.num_brokers
    known = set(topology.broker_ids)
    rack_index = {r: i for i, r in enumerate(topology.rack_ids)}
    new_racks: List[str] = []
    max_new = 0
    for spec in specs:
        hypothetical = [a for a in spec.add_brokers
                        if a.broker_id not in known]
        max_new = max(max_new, len(hypothetical))
        for a in hypothetical:
            if (a.rack is not None and a.rack not in rack_index
                    and a.rack not in new_racks):
                new_racks.append(a.rack)
    for i, r in enumerate(new_racks):
        rack_index[r] = len(topology.rack_ids) + i
    return (base_b + max_new, rack_index,
            base_state.num_racks + len(new_racks),
            base_state.num_hosts + max_new)


def _pad_broker_axis(arrays: dict, pad: int) -> dict:
    # dead-row convention shared with the mesh padding and the fleet
    # shape buckets (parallel/mesh.DEAD_ROW_FILLS): one fill table, so
    # the three padders cannot drift apart
    from cruise_control_tpu.parallel.mesh import pad_field
    return {k: pad_field(k, v, pad) for k, v in arrays.items()}


def materialize(base_state: ClusterState, topology: ClusterTopology,
                spec: ScenarioSpec, num_brokers: int, rack_index: dict,
                num_racks: int, num_hosts: int
                ) -> Tuple[ClusterState, ClusterTopology,
                           OptimizationOptions]:
    """One variant (state, topology, per-scenario options) at the shared
    padded geometry.  Everything is host-side numpy; the caller stacks
    and ships the batch in one go."""
    import jax.numpy as jnp

    spec.validate(topology)
    base_b = base_state.num_brokers
    pad = num_brokers - base_b
    broker_index = dict(topology.broker_index)
    broker_ids = list(topology.broker_ids)
    host_names = list(topology.host_names)
    rack_ids = sorted(rack_index, key=rack_index.get)

    arrays = _pad_broker_axis(
        dict(broker_alive=np.asarray(base_state.broker_alive),
             broker_new=np.asarray(base_state.broker_new),
             broker_demoted=np.asarray(base_state.broker_demoted),
             broker_bad_disks=np.asarray(base_state.broker_bad_disks),
             broker_capacity=np.asarray(base_state.broker_capacity,
                                        dtype=np.float32),
             broker_rack=np.asarray(base_state.broker_rack),
             broker_host=np.asarray(base_state.broker_host)), pad)
    arrays = {k: np.array(v) for k, v in arrays.items()}
    alive = arrays["broker_alive"]
    mean_cap = (np.asarray(base_state.broker_capacity)[alive[:base_b]]
                .mean(axis=0) if alive[:base_b].any()
                else np.zeros(NUM_RESOURCES))

    # broker additions: known ids are marked new in place (freshly joined,
    # ADD_BROKER semantics); unknown ids take the next padded slot
    from cruise_control_tpu.scenario.spec import RESOURCE_NAMES
    next_slot = base_b
    added_ids: List[int] = []
    for add in spec.add_brokers:
        added_ids.append(add.broker_id)
        if add.broker_id in topology.broker_index:
            b = topology.broker_index[add.broker_id]
            if add.capacity:
                for name, v in add.capacity.items():
                    arrays["broker_capacity"][b,
                                              RESOURCE_NAMES.index(name)] = v
        else:
            if next_slot >= num_brokers:
                raise ScenarioSpecError(
                    f"{spec.name}: more hypothetical brokers than the "
                    f"batch geometry allows")
            b = next_slot
            next_slot += 1
            broker_index[add.broker_id] = b
            broker_ids.append(add.broker_id)
            host_names.append(f"scenario-host-{add.broker_id}")
            arrays["broker_alive"][b] = True
            rack = (rack_index[add.rack] if add.rack is not None
                    else b % max(len(topology.rack_ids), 1))
            arrays["broker_rack"][b] = rack
            arrays["broker_host"][b] = base_state.num_hosts + (b - base_b)
            cap = np.asarray(mean_cap, dtype=np.float32).copy()
            if add.capacity:
                for name, v in add.capacity.items():
                    cap[RESOURCE_NAMES.index(name)] = v
            arrays["broker_capacity"][b] = cap
        arrays["broker_new"][b] = True

    replica_offline = np.array(np.asarray(base_state.replica_offline))
    original_offline = np.array(
        np.asarray(base_state.replica_original_offline))
    replica_broker = np.asarray(base_state.replica_broker)
    replica_valid = np.asarray(base_state.replica_valid)

    for b_ext in spec.remove_brokers:
        b = broker_index[b_ext]
        arrays["broker_alive"][b] = False
        on_broker = (replica_broker == b) & replica_valid
        # replicas on a broker still in JBOD-broken state stay offline
        # after a revive — removal only ever ADDS offline flags here
        replica_offline |= on_broker
        original_offline |= on_broker
    for b_ext in spec.demote_brokers:
        arrays["broker_demoted"][broker_index[b_ext]] = True
    for b_ext, caps in spec.capacity_overrides.items():
        from cruise_control_tpu.scenario.spec import RESOURCE_NAMES
        for name, v in caps.items():
            arrays["broker_capacity"][broker_index[b_ext],
                                      RESOURCE_NAMES.index(name)] = v

    scale = spec.load_scale_vector()
    base_load = np.asarray(base_state.replica_base_load)
    bonus = np.asarray(base_state.partition_leader_bonus)
    if spec.load_scale:
        base_load = base_load * scale[None, :]
        bonus = bonus * scale[None, :]

    state = ClusterState(
        replica_valid=jnp.asarray(replica_valid),
        replica_partition=base_state.replica_partition,
        replica_broker=base_state.replica_broker,
        replica_disk=base_state.replica_disk,
        replica_is_leader=base_state.replica_is_leader,
        replica_offline=jnp.asarray(replica_offline),
        replica_original_offline=jnp.asarray(original_offline),
        replica_base_load=jnp.asarray(base_load, dtype=jnp.float32),
        partition_topic=base_state.partition_topic,
        partition_leader_bonus=jnp.asarray(bonus, dtype=jnp.float32),
        broker_alive=jnp.asarray(arrays["broker_alive"]),
        broker_new=jnp.asarray(arrays["broker_new"]),
        broker_demoted=jnp.asarray(arrays["broker_demoted"]),
        broker_bad_disks=jnp.asarray(arrays["broker_bad_disks"]),
        broker_capacity=jnp.asarray(arrays["broker_capacity"],
                                    dtype=jnp.float32),
        broker_rack=jnp.asarray(arrays["broker_rack"], dtype=jnp.int32),
        broker_host=jnp.asarray(arrays["broker_host"], dtype=jnp.int32),
        disk_broker=base_state.disk_broker,
        disk_capacity=base_state.disk_capacity,
        disk_alive=base_state.disk_alive,
        num_racks=num_racks,
        num_hosts=num_hosts,
        num_topics=base_state.num_topics,
    )
    variant_topo = ClusterTopology(
        broker_ids=broker_ids,
        rack_ids=rack_ids,
        host_names=host_names,
        topics=list(topology.topics),
        partitions=list(topology.partitions),
        disk_names=list(topology.disk_names),
    )
    options = OptimizationOptions(
        requested_destination_broker_ids=(
            frozenset(added_ids) if spec.only_move_to_added
            else frozenset()))
    return state, variant_topo, options


def compile_batch(base_state: ClusterState, topology: ClusterTopology,
                  specs: Sequence[ScenarioSpec],
                  constraint: Optional[BalancingConstraint] = None,
                  options: Optional[OptimizationOptions] = None,
                  table_slots_override: Optional[int] = None
                  ) -> CompiledBatch:
    """Materialize + context-build every spec at one shared geometry.

    Per-scenario contexts CAN differ in their array planes (different
    dead-broker masks, destination restrictions) — they stack along the
    scenario axis like the states do — but their STATIC fields must
    agree for one program to serve the whole batch; `table_slots` is
    therefore unified to the batch maximum."""
    constraint = constraint or BalancingConstraint()
    base_options = options or OptimizationOptions()
    geometry = _batch_geometry(base_state, topology, specs)
    num_brokers, rack_index, num_racks, num_hosts = geometry

    states: List[ClusterState] = []
    contexts: List[OptimizationContext] = []
    topologies: List[ClusterTopology] = []
    for spec in specs:
        state, topo, spec_options = materialize(
            base_state, topology, spec, num_brokers, rack_index,
            num_racks, num_hosts)
        merged = base_options
        if spec_options.requested_destination_broker_ids:
            merged = dataclasses.replace(
                base_options,
                requested_destination_broker_ids=(
                    spec_options.requested_destination_broker_ids))
        contexts.append(make_context(state, constraint, merged, topo))
        states.append(state)
        topologies.append(topo)

    slots = (table_slots_override if table_slots_override is not None
             else max((c.table_slots for c in contexts), default=0))
    contexts = [c if c.table_slots == slots
                else dataclasses.replace(c, table_slots=slots)
                for c in contexts]
    from cruise_control_tpu.analyzer.context import partition_replica_index
    return CompiledBatch(specs=list(specs), states=states,
                         contexts=contexts, topologies=topologies,
                         num_brokers=num_brokers,
                         partition_rows=partition_replica_index(states[0]))
