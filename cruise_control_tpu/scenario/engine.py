"""Batched what-if engine: K scenario solves in one device program.

The PR-1 fused goal pipeline (analyzer/optimizer.py: pre program, fused
per-goal segments with on-device prev-stats threading, post sweep) is
`vmap`ped over a new leading SCENARIO axis: one compile serves every
scenario of a batch, the per-goal instruments accumulate into
[K, G]-shaped device tables, and the whole batch pays exactly ONE
end-of-batch instrument fetch plus one placement fetch for the host-side
proposal diff — the same 2-`device_get` transfer discipline the
single-solve path pins in tests/test_fused_pipeline.py, now per BATCH
instead of per solve (pinned in tests/test_scenario.py).

Failure discipline (the PR-2 ladder, applied to batches):

* RESOURCE_EXHAUSTED on the batched dispatch halves the batch and
  retries both halves (a K-scenario program can exceed HBM where K/2
  fits; see docs/SCENARIOS.md for sizing guidance), up to
  `max_oom_halvings` times;
* any other batched failure descends the engine's own degradation
  ladder (analyzer/degradation.py): EAGER = a per-scenario loop through
  `GoalOptimizer.optimizations(eager_driver=True)`, CPU =
  `model/cpu_model.host_fallback_solve` per scenario — scenario
  evaluation degrades but never goes dark;
* per-scenario solver VERDICTS (unsatisfiable hard goal, stats
  regression, invalid inputs, unhealed offline replicas) are NOT
  failures: the batched path reports them as infeasible outcomes from
  the instrument fetch, so one doomed scenario cannot poison its
  batchmates.

Fault-injection sites: ``scenario.compile`` (batched program build) and
``scenario.execute`` (batched dispatch) — the eager/CPU rungs run under
the optimizer's own ``optimizer.*`` sites.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time as _time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                 OptimizationOptions)
from cruise_control_tpu.analyzer.degradation import (CircuitBreaker,
                                                     DegradationLadder,
                                                     InvalidModelInputError,
                                                     SolverRung,
                                                     classify_failure)
from cruise_control_tpu.analyzer.goals.base import OptimizationFailure
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.state import ClusterState
from cruise_control_tpu.parallel import health
from cruise_control_tpu.scenario.compiler import (CompiledBatch,
                                                  _batch_geometry,
                                                  compile_batch, materialize)
from cruise_control_tpu.scenario.spec import ScenarioSpec
from cruise_control_tpu.sched.runtime import (SolvePreempted,
                                              current_mesh_token,
                                              segment_checkpoint)
from cruise_control_tpu.utils import faults

LOG = logging.getLogger(__name__)

#: base-solve scenario prepended by the facade (spec.is_noop() == True)
BASE_SCENARIO_NAME = "__base__"


def _is_resource_exhausted(exc: BaseException) -> bool:
    text = f"{type(exc).__name__}: {exc}"
    return ("RESOURCE_EXHAUSTED" in text
            or "resource exhausted" in text.lower()
            or "out of memory" in text.lower())


class _TableOverflow(Exception):
    """Post-heal replica concentration overflowed the broker-table width;
    the chunk re-runs with `slots` (mirrors the single-solve re-run in
    GoalOptimizer.optimizations)."""

    def __init__(self, slots: int) -> None:
        super().__init__(f"broker table overflow; need width {slots}")
        self.slots = slots


@dataclasses.dataclass
class ScenarioOutcome:
    """One scenario's verdict + instruments (host-side values only)."""

    spec: ScenarioSpec
    feasible: bool
    reason: str = ""                       #: why infeasible ("" when not)
    rung: str = "FUSED"                    #: rung that served this solve
    violated_goals_before: List[str] = dataclasses.field(
        default_factory=list)
    violated_goals_after: List[str] = dataclasses.field(
        default_factory=list)
    violated_broker_counts: Dict[str, Tuple[int, int, int]] = \
        dataclasses.field(default_factory=dict)
    #: per-goal violated count at the goal's own entry (see
    #: OptimizerResult.entry_broker_counts)
    entry_broker_counts: Dict[str, int] = dataclasses.field(
        default_factory=dict)
    rounds_by_goal: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: per-goal last-committing round (see
    #: OptimizerResult.converged_at_by_goal)
    converged_at_by_goal: Dict[str, int] = dataclasses.field(
        default_factory=dict)
    stats_before: Optional[object] = None  #: host ClusterModelStats
    stats_after: Optional[object] = None
    #: per-goal stats snapshots (the fused path computes these anyway;
    #: the fleet router needs them to rebuild a full OptimizerResult)
    stats_by_goal: Dict[str, object] = dataclasses.field(
        default_factory=dict)
    regressed_goals: List[str] = dataclasses.field(default_factory=list)
    #: the infeasibility is an input-validity verdict (NaN/Inf/negative
    #: model), not a solver verdict — the router re-raises it as
    #: InvalidModelInputError to match the single-solve path
    invalid_input: bool = False
    #: this lane's FINAL placement (host numpy: replica_broker,
    #: replica_is_leader, optional replica_disk) — populated only for
    #: per-lane-membership batches (fleet folds), where it is already
    #: fetched for the proposal diff; the fleet router rebuilds a full
    #: final ClusterState from it so folded solves seed warm starts
    #: exactly like inline solves do (PR-5 left folded results with
    #: final_state=None, starving warm starts)
    final_placement: Optional[dict] = None
    balancedness: float = 0.0
    num_replica_moves: int = 0
    num_leadership_moves: int = 0
    data_to_move: float = 0.0
    proposals: List = dataclasses.field(default_factory=list)

    @property
    def num_violated_goals_after(self) -> int:
        return len(self.violated_goals_after)


@dataclasses.dataclass
class ScenarioBatchResult:
    """The whole evaluation: outcomes in request order + batch telemetry."""

    outcomes: List[ScenarioOutcome]
    duration_s: float = 0.0
    compile_s: float = 0.0
    solve_s: float = 0.0
    oom_halvings: int = 0
    batch_sizes: List[int] = dataclasses.field(default_factory=list)
    rung: str = "FUSED"

    def outcome(self, name: str) -> Optional[ScenarioOutcome]:
        for o in self.outcomes:
            if o.spec.name == name:
                return o
        return None


class ScenarioEngine:
    """Evaluates batches of what-if scenarios against one base model.

    `optimizer_factory(goal_names_or_None)` returns the GoalOptimizer to
    run (the facade passes its own, so scenario programs share the
    process-wide trace cache with request-path solves).  The engine owns
    its OWN degradation ladder — a failing scenario batch must not pin
    the request-path solver, and vice versa."""

    def __init__(self, optimizer_factory: Callable,
                 constraint: Optional[BalancingConstraint] = None,
                 max_batch_size: int = 32,
                 max_oom_halvings: int = 4,
                 breaker_failure_threshold: int = 3,
                 breaker_cooldown_s: float = 300.0,
                 balancedness_weights: Tuple[float, float] = (1.1, 1.5),
                 metrics=None,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self._optimizer_factory = optimizer_factory
        self._constraint = constraint or BalancingConstraint()
        self.balancedness_weights = balancedness_weights
        self.max_batch_size = max(1, max_batch_size)
        self.max_oom_halvings = max(0, max_oom_halvings)
        self._metrics = metrics
        self._time = time_fn or _time.time
        self.breaker = CircuitBreaker(
            failure_threshold=breaker_failure_threshold,
            cooldown_s=breaker_cooldown_s, time_fn=self._time)
        self.ladder = DegradationLadder(self.breaker)
        self._lock = threading.Lock()
        #: serializes whole evaluations: concurrent SCENARIOS user tasks
        #: would otherwise interleave per-call telemetry and double-pay
        #: identical program compiles (device solves serialize on one
        #: chip anyway, so queueing here costs nothing extra)
        self._eval_lock = threading.Lock()
        #: AOT-compiled vmapped programs, LRU-bounded (each holds traced
        #: jaxprs + executables; unbounded growth mirrors the
        #: _SHARED_PROGRAMS leak fixed in PR 1)
        self._programs: "OrderedDict[tuple, object]" = OrderedDict()
        self._max_programs = 24
        # telemetry (STATE ScenarioEngineState + scenario-* sensors)
        self.last_batch_size = 0
        self.total_batches = 0
        self.total_scenarios = 0
        self.total_oom_halvings = 0
        self.last_compile_s = 0.0
        self.last_solve_s = 0.0

    # ------------------------------------------------------------------
    def attach_metrics(self, registry) -> None:
        """Late-bind the facade's MetricRegistry (the engine is built
        before the registry during facade construction)."""
        self._metrics = registry

    def reserve_program_capacity(self, n: int) -> None:
        """Grow (never shrink) the AOT program LRU to hold at least `n`
        entries.  A portfolio sweep streams `trace groups x per-segment
        programs` distinct keys per search; below that the LRU thrashes
        and every "warm" search re-hydrates its whole working set."""
        with self._lock:
            self._max_programs = max(self._max_programs, int(n))

    def to_json(self) -> dict:
        return {
            "rung": self.ladder.rung.name,
            "breaker": self.breaker.to_json(),
            "lastBatchSize": self.last_batch_size,
            "totalBatches": self.total_batches,
            "totalScenarios": self.total_scenarios,
            "totalOomHalvings": self.total_oom_halvings,
            "lastCompileS": round(self.last_compile_s, 3),
            "lastSolveS": round(self.last_solve_s, 3),
        }

    # ------------------------------------------------------------------
    def evaluate(self, base_state: ClusterState, topology,
                 specs: Sequence[ScenarioSpec],
                 goals: Optional[Sequence[str]] = None,
                 options: Optional[OptimizationOptions] = None,
                 include_proposals: bool = True) -> ScenarioBatchResult:
        """Solve every spec; outcomes return in request order.

        Scenarios sharing a goal list share one vmapped program (a
        per-spec `goals` override opens a separate sub-batch); each
        sub-batch is capped at `max_batch_size` scenarios per device
        program."""
        for spec in specs:
            spec.validate(topology)
        from cruise_control_tpu.obs import trace as obs_trace
        with self._eval_lock:
            with obs_trace.span("scenario.batch",
                                scenarios=len(specs)) as sp:
                result = self._evaluate_locked(base_state, topology,
                                               specs, goals, options,
                                               include_proposals)
                if sp is not None:
                    sp.set_tag("rung", getattr(result.rung, "name",
                                               str(result.rung)))
                    sp.set_tag("oomHalvings", result.oom_halvings)
                return result

    def _evaluate_locked(self, base_state, topology, specs, goals,
                         options, include_proposals) -> ScenarioBatchResult:
        t0 = self._time()
        result = ScenarioBatchResult(outcomes=[None] * len(specs))
        self.last_compile_s = 0.0
        self.last_solve_s = 0.0

        groups: "OrderedDict[Optional[Tuple[str, ...]], list]" = \
            OrderedDict()
        default_key = tuple(goals) if goals is not None else None
        for i, spec in enumerate(specs):
            key = spec.goals if spec.goals is not None else default_key
            groups.setdefault(key, []).append((i, spec))

        for goal_key, group in groups.items():
            optimizer = self._optimizer_factory(
                list(goal_key) if goal_key is not None else None)
            for start in range(0, len(group), self.max_batch_size):
                chunk = group[start:start + self.max_batch_size]
                outs = self._solve_chunk(
                    optimizer, base_state, topology,
                    [s for _, s in chunk], options, include_proposals,
                    result)
                for (idx, _), out in zip(chunk, outs):
                    result.outcomes[idx] = out

        result.duration_s = self._time() - t0
        result.compile_s = self.last_compile_s
        result.solve_s = self.last_solve_s
        result.rung = self.ladder.rung.name
        with self._lock:
            self.last_batch_size = len(specs)
            self.total_batches += 1
            self.total_scenarios += len(specs)
        if self._metrics is not None:
            # compile time is already sampled per program inside _run;
            # recording the batch sum here too would double-count it
            self._metrics.update_timer("scenario-execute-timer",
                                       result.duration_s)
        return result

    # ------------------------------------------------------------------
    # pre-compiled batches (fleet/router.py cross-tenant folds)
    # ------------------------------------------------------------------
    def solve_compiled(self, optimizer, batch: CompiledBatch,
                       include_proposals: bool = True
                       ) -> ScenarioBatchResult:
        """Run a caller-assembled CompiledBatch through the batched
        fused pipeline (OOM halving included) and return the outcomes +
        telemetry.  NO ladder here: the caller owns failure policy (the
        fleet router falls back to per-tenant inline solves so each
        tenant's own ladder classifies its own failure).  Broker-table
        overflow re-runs at the widened slot count, exactly like the
        compile_batch path."""
        t0 = self._time()
        result = ScenarioBatchResult(outcomes=[])
        with self._eval_lock:
            self.last_compile_s = 0.0
            self.last_solve_s = 0.0
            for _ in range(3):
                try:
                    result.outcomes = self._solve_fused(
                        optimizer, batch, self.max_oom_halvings,
                        include_proposals, result)
                    break
                except _TableOverflow as overflow:
                    batch = batch.with_table_slots(overflow.slots)
            else:
                raise RuntimeError(
                    "broker table kept overflowing after 3 re-widened "
                    "runs; the batch cannot be solved fused")
        result.duration_s = self._time() - t0
        result.compile_s = self.last_compile_s
        result.solve_s = self.last_solve_s
        result.rung = "FUSED"
        with self._lock:
            self.last_batch_size = len(batch.specs)
            self.total_batches += 1
            self.total_scenarios += len(batch.specs)
        return result

    # ------------------------------------------------------------------
    # rung dispatch
    # ------------------------------------------------------------------
    def _solve_chunk(self, optimizer, base_state, topology,
                     specs: List[ScenarioSpec], options, include_proposals,
                     result: ScenarioBatchResult,
                     table_override: Optional[int] = None
                     ) -> List[ScenarioOutcome]:
        import jax
        rung = self.ladder.entry_rung()
        if rung is SolverRung.FUSED:
            try:
                with jax.transfer_guard_device_to_host("allow"):
                    # host-side variant assembly reads the base model's
                    # device arrays (sanctioned pre-dispatch region)
                    batch = compile_batch(
                        base_state, topology, specs, self._constraint,
                        options, table_slots_override=table_override)
                outs = self._solve_fused(optimizer, batch,
                                         self.max_oom_halvings,
                                         include_proposals, result)
                self.ladder.on_success(SolverRung.FUSED)
                return outs
            except _TableOverflow as overflow:
                return self._solve_chunk(optimizer, base_state, topology,
                                         specs, options, include_proposals,
                                         result,
                                         table_override=overflow.slots)
            except SolvePreempted:
                # scheduler preemption is control flow, never ladder
                # material: the dispatch loop re-queues the whole sweep
                raise
            except Exception as exc:  # noqa: BLE001 - ladder classifies
                kind = classify_failure(exc)
                self.ladder.on_failure(SolverRung.FUSED)
                self._descend_metered(SolverRung.FUSED)
                LOG.warning("batched scenario solve failed (%s): %s; "
                            "descending to per-scenario EAGER loop",
                            kind.value, exc)
                rung = SolverRung.EAGER
        return self._solve_per_scenario(optimizer, base_state, topology,
                                        specs, options, include_proposals,
                                        rung, result)

    def _solve_per_scenario(self, optimizer, base_state, topology,
                            specs, options, include_proposals,
                            rung: SolverRung, result: ScenarioBatchResult
                            ) -> List[ScenarioOutcome]:
        """Degraded rungs: EAGER = one eager-driver solve per scenario
        (per-goal programs localize device faults); CPU = numpy
        host-fallback per scenario (no XLA dispatch at all)."""
        import jax
        outs: List[ScenarioOutcome] = []
        eager_failed = False
        served_any_at_rung = False
        for spec in specs:
            with jax.transfer_guard_device_to_host("allow"):
                geometry = _batch_geometry(base_state, topology, [spec])
                v_state, v_topo, spec_opts = materialize(
                    base_state, topology, spec, *geometry)
            merged = options or OptimizationOptions()
            if spec_opts.requested_destination_broker_ids:
                merged = dataclasses.replace(
                    merged, requested_destination_broker_ids=(
                        spec_opts.requested_destination_broker_ids))
            if rung is SolverRung.EAGER:
                try:
                    res = optimizer.optimizations(v_state, v_topo, merged,
                                                  check_sanity=False,
                                                  eager_driver=True)
                    outs.append(self._outcome_from_result(
                        spec, res, "EAGER", include_proposals))
                    served_any_at_rung = True
                    continue
                except (OptimizationFailure,
                        InvalidModelInputError) as exc:
                    outs.append(ScenarioOutcome(
                        spec=spec, feasible=False, reason=str(exc),
                        rung="EAGER"))
                    served_any_at_rung = True
                    continue
                except SolvePreempted:
                    raise
                except Exception as exc:  # noqa: BLE001
                    eager_failed = True
                    self.ladder.on_failure(SolverRung.EAGER)
                    LOG.warning("eager scenario solve %r failed (%s); "
                                "host fallback", spec.name,
                                classify_failure(exc).value)
            try:
                from cruise_control_tpu.model.cpu_model import \
                    host_fallback_solve
                res = host_fallback_solve(v_state, v_topo, options=merged,
                                          time_fn=self._time)
                outs.append(self._outcome_from_result(
                    spec, res, "CPU", include_proposals))
            except (OptimizationFailure, InvalidModelInputError) as exc:
                outs.append(ScenarioOutcome(
                    spec=spec, feasible=False, reason=str(exc),
                    rung="CPU"))
            except Exception as exc:  # noqa: BLE001 - bottom rung failed
                self.ladder.on_failure(SolverRung.CPU)
                outs.append(ScenarioOutcome(
                    spec=spec, feasible=False,
                    reason=f"solve failed at every rung: {exc}",
                    rung="CPU"))
        if eager_failed:
            self._descend_metered(SolverRung.EAGER)
        elif served_any_at_rung:
            self.ladder.on_success(rung)
        result.batch_sizes.extend([1] * len(specs))
        return outs

    def _descend_metered(self, from_rung: SolverRung) -> None:
        """Descend and meter `scenario-descents` only when the RESTING
        rung actually moved (a failed probe back onto an already-pinned
        rung is not a new descent)."""
        before = self.ladder.rung
        self.ladder.descend(from_rung)
        if self._metrics is not None and self.ladder.rung != before:
            self._metrics.meter("scenario-descents").mark()

    def _outcome_from_result(self, spec, res, rung: str,
                             include_proposals: bool) -> ScenarioOutcome:
        return ScenarioOutcome(
            spec=spec, feasible=True, rung=rung,
            violated_goals_before=list(res.violated_goals_before),
            violated_goals_after=list(res.violated_goals_after),
            violated_broker_counts=dict(res.violated_broker_counts),
            entry_broker_counts=dict(res.entry_broker_counts),
            rounds_by_goal=dict(res.rounds_by_goal),
            converged_at_by_goal=dict(res.converged_at_by_goal),
            stats_before=res.stats_before, stats_after=res.stats_after,
            balancedness=res.balancedness_score(),
            num_replica_moves=res.num_replica_movements,
            num_leadership_moves=res.num_leadership_movements,
            data_to_move=res.data_to_move,
            proposals=list(res.proposals) if include_proposals else [])

    # ------------------------------------------------------------------
    # FUSED rung: the vmapped batch
    # ------------------------------------------------------------------
    def _solve_fused(self, optimizer, batch: CompiledBatch,
                     halvings_left: int, include_proposals: bool,
                     result: ScenarioBatchResult) -> List[ScenarioOutcome]:
        try:
            return self._solve_batched(optimizer, batch,
                                       include_proposals, result)
        except _TableOverflow:
            raise
        except Exception as exc:  # noqa: BLE001 - OOM gets the halving path
            if (_is_resource_exhausted(exc) and len(batch.specs) > 1
                    and halvings_left > 0):
                with self._lock:
                    self.total_oom_halvings += 1
                result.oom_halvings += 1
                if self._metrics is not None:
                    self._metrics.meter("scenario-oom-halvings").mark()
                half = len(batch.specs) // 2
                LOG.warning("batched scenario solve of %d hit "
                            "RESOURCE_EXHAUSTED; retrying as %d + %d",
                            len(batch.specs), half,
                            len(batch.specs) - half)
                return (self._solve_fused(optimizer, batch.slice(0, half),
                                          halvings_left - 1,
                                          include_proposals, result)
                        + self._solve_fused(optimizer,
                                            batch.slice(half, None),
                                            halvings_left - 1,
                                            include_proposals, result))
            raise

    def _solve_batched(self, optimizer, batch: CompiledBatch,
                       include_proposals: bool,
                       result: ScenarioBatchResult
                       ) -> List[ScenarioOutcome]:
        """One vmapped run of the fused pipeline over the batch: pre →
        fused goal segments (prev-stats threaded on device along the goal
        axis, exactly as in the single-solve path) → post sweep →
        movement epilogue, then the single end-of-batch instrument fetch
        and one placement fetch for the host diff."""
        import jax

        if not optimizer.goals:
            raise ValueError("scenario solves need at least one goal")
        k = len(batch.specs)
        t_solve = self._time()
        with jax.transfer_guard_device_to_host("allow"):
            # sanctioned pre-dispatch host region (host-side variant
            # assembly reads the base model's device arrays)
            stacked_state, stacked_ctx = batch.stack()
        # spare mesh capacity as a SECOND batching axis: when the
        # dispatch thread holds a multi-chip mesh token (the scheduler
        # owns the mesh, sched/runtime), the leading scenario/lane axis
        # shards across the chips — K lanes x N devices, each lane's
        # solve running whole on its chip(s), zero cross-lane
        # collectives.  device_put needs the lane dim divisible by the
        # shard count, so K pads up with copies of lane 0 (ignored on
        # the way back out — every consumer below indexes i < K); the
        # padded duplicates cost less than leaving chips idle would.
        # Without a token (or K=1) nothing changes: the single-chip
        # vmapped path stays bit-identical.
        token = current_mesh_token()
        mesh_k = 0
        lane_pad = 0
        if (token is not None
                and getattr(token, "is_multichip", False) and k >= 2):
            mesh_k = min(k, token.size)
            lane_pad = -(-k // mesh_k) * mesh_k - k
            if lane_pad:
                stacked_state, stacked_ctx = _pad_lane_axis(
                    k, lane_pad, stacked_state, stacked_ctx)
            stacked_state, stacked_ctx = _shard_lane_axis(
                token.mesh, k + lane_pad, mesh_k,
                stacked_state, stacked_ctx)
        initial = stacked_state
        ctx0 = batch.contexts[0]
        shapes = (k, initial.replica_valid.shape[1], batch.num_brokers,
                  ctx0.table_slots, ctx0.rf_max, initial.num_racks,
                  initial.num_hosts, mesh_k, lane_pad)

        faults.inject("scenario.execute")
        (stats0_dev, vb_dev, state, cache, still_dev, maxc_dev,
         broken_dev, pre_rounds_dev, invalid_dev) = self._run(
            optimizer, "__pre__", optimizer._pre_fn(), shapes, (),
            initial, stacked_state, stacked_ctx)
        prev_stats = stats0_dev
        stacked_parts, own_parts, rounds_parts, regr_parts = [], [], [], []
        entry_parts = []
        conv_parts = []
        # segment boundaries follow the optimizer's plan — fusion-group
        # megaprograms when it opted in — so scenario lanes dispatch the
        # same `__seg_` keys (and per-solve dispatch count) as request
        # solves
        for start, stop in optimizer._plan_segments():
            # scheduler preemption checkpoint: a queued ANOMALY_HEAL /
            # USER_INTERACTIVE solve takes the device at the next
            # segment boundary; the whole sweep re-queues
            segment_checkpoint()
            (state, cache, prev_stats,
             (stacked_seg, own_seg, rounds_seg, regr_seg, _hard,
              entry_seg, conv_seg)) = \
                self._run(optimizer, f"__seg_{start}_{stop}__",
                          optimizer._segment_fn(start, stop), shapes,
                          (0, 1), state, cache, prev_stats, stacked_ctx)
            stacked_parts.append(stacked_seg)
            own_parts.append(own_seg)
            rounds_parts.append(rounds_seg)
            regr_parts.append(regr_seg)
            entry_parts.append(entry_seg)
            conv_parts.append(conv_seg)
        va_dev = self._run(optimizer, "__post__", optimizer._post_fn(),
                           shapes, (), state, cache, stacked_ctx)
        moves_dev = self._run(optimizer, "__moves__", _movement_metrics,
                              shapes, (), initial, state)

        goals = optimizer.goals
        traceable = optimizer._device_comparators()
        with jax.transfer_guard_device_to_host("allow"):
            # fetch 1/2: every instrument of the whole batch in ONE
            # device_get — [K]- and [K, G]-shaped tables
            (stats0_h, stacked_h, own_h, rounds_h, regr_h, entry_h,
             conv_h, vb_h, va_h, still_h, maxc_h, broken_h, pre_rounds_h,
             invalid_h, moves_h) = jax.device_get(
                (stats0_dev, stacked_parts, own_parts, rounds_parts,
                 regr_parts, entry_parts, conv_parts, vb_dev, va_dev,
                 still_dev, maxc_dev, broken_dev, pre_rounds_dev,
                 invalid_dev, moves_dev))
            slots = ctx0.table_slots
            max_count = int(np.max(maxc_h)) if k else 0
            if slots and max_count > slots:
                new_slots = min(int(initial.replica_valid.shape[1]),
                                -(-int(max_count * 1.5 + 64) // 128) * 128)
                LOG.warning("scenario batch overflowed broker table "
                            "width %d (max count %d); re-running with "
                            "width %d", slots, max_count, new_slots)
                raise _TableOverflow(new_slots)

            # fetch 2/2: final + initial placements for the host diff.
            # Scenario batches share one base membership/placement, so
            # lane 0's initial rows serve the whole batch; cross-tenant
            # fleet batches stack different base models and fetch the
            # full [K, R] initial planes instead
            has_disks = batch.states[0].num_disks > 0
            shared = batch.shared_membership

            def _init(x):
                return x[0] if shared else x
            fetch2: tuple = (state.replica_broker, state.replica_is_leader,
                             _init(initial.replica_broker),
                             _init(initial.replica_is_leader),
                             _init(initial.replica_valid),
                             initial.replica_base_load[:, :, Resource.DISK],
                             _init(initial.replica_partition))
            if has_disks:
                fetch2 = fetch2 + (state.replica_disk,
                                   _init(initial.replica_disk))
            fetched2 = jax.device_get(fetch2)
        self.last_solve_s += self._time() - t_solve
        result.batch_sizes.append(k)

        (fin_b, fin_l, init_b, init_l, valid, base_disk, part) = \
            fetched2[:7]
        init_d = fetched2[8] if has_disks else None
        fin_d = fetched2[7] if has_disks else None

        own_all = np.concatenate(own_h, axis=1) if own_h else \
            np.zeros((k, 0), np.int32)
        entry_all = np.concatenate(entry_h, axis=1) if entry_h else \
            np.zeros((k, 0), np.int32)
        rounds_all = np.concatenate(rounds_h, axis=1) if rounds_h else \
            np.zeros((k, 0), np.int32)
        conv_all = np.concatenate(conv_h, axis=1) if conv_h else \
            np.zeros((k, 0), np.int32)
        regr_all = np.concatenate(regr_h, axis=1) if regr_h else \
            np.zeros((k, 0), bool)
        stacked_all = jax.tree.map(
            lambda *xs: np.concatenate(xs, axis=1), *stacked_h)

        def _lane(x, i):
            return x if shared else x[i]
        outcomes: List[ScenarioOutcome] = []
        for i in range(k):
            outcomes.append(self._assemble_outcome(
                batch, i, goals, traceable,
                jax.tree.map(lambda x, i=i: x[i], stats0_h),
                jax.tree.map(lambda x, i=i: x[i], stacked_all),
                own_all[i], entry_all[i], rounds_all[i], conv_all[i],
                regr_all[i], vb_h[i], va_h[i],
                int(still_h[i]), bool(broken_h[i]), int(pre_rounds_h[i]),
                bool(invalid_h[i]), tuple(m[i] for m in moves_h),
                include_proposals,
                dict(fin_b=fin_b[i], fin_l=fin_l[i],
                     fin_d=None if fin_d is None else fin_d[i],
                     init_b=_lane(init_b, i), init_l=_lane(init_l, i),
                     init_d=None if init_d is None else _lane(init_d, i),
                     valid=_lane(valid, i), base_disk=base_disk[i],
                     part=_lane(part, i))))
        return outcomes

    def _assemble_outcome(self, batch, i, goals, traceable, stats_before,
                          stats_by_idx, own, entry, rounds, conv, regr,
                          vb, va, still_offline, broken, pre_rounds,
                          invalid, moves, include_proposals, placements
                          ) -> ScenarioOutcome:
        """Host tail for scenario i — the same evaluation order as the
        single-solve host tail in GoalOptimizer.optimizations, but
        verdicts become per-scenario feasibility instead of exceptions."""
        spec = batch.specs[i]
        violated_before = [g.name for g, v in zip(goals, vb) if v]
        violated_after = [g.name for g, v in zip(goals, va) if v]
        counts = {g.name: (int(b), int(o), int(a))
                  for g, b, o, a in zip(goals, vb, own, va)}
        entry_counts = {g.name: int(e) for g, e in zip(goals, entry)}
        rounds_by_goal = {g.name: int(r) for g, r in zip(goals, rounds)}
        converged_by_goal = {g.name: int(c) for g, c in zip(goals, conv)}
        if pre_rounds:
            rounds_by_goal["__prebalance__"] = pre_rounds

        import jax
        stats_by_goal = {}
        regressed: List[str] = []
        prev = stats_before
        for gi, goal in enumerate(goals):
            goal_stats = jax.tree.map(lambda x, gi=gi: x[gi], stats_by_idx)
            stats_by_goal[goal.name] = goal_stats
            flag = (bool(regr[gi]) if traceable[gi]
                    else not goal.stats_not_worse(prev, goal_stats))
            if flag:
                regressed.append(goal.name)
            prev = goal_stats
        stats_after = (stats_by_goal[goals[-1].name] if goals
                       else stats_before)

        num_moves, leader_moves, data = (int(moves[0]), int(moves[1]),
                                         float(moves[2]))
        feasible, reason = True, ""
        if invalid:
            feasible, reason = False, (
                "model carries NaN/Inf/negative loads or capacities")
        elif still_offline:
            feasible, reason = False, (
                f"{still_offline} offline replicas could not be "
                f"relocated (insufficient capacity or eligible brokers)")
        elif regressed and not broken:
            feasible, reason = False, (
                "optimization made goal statistics worse than before "
                "for: " + ", ".join(regressed))
        else:
            hard_violated = [g.name for g in goals
                             if g.is_hard and g.name in violated_after]
            if hard_violated:
                feasible, reason = False, (
                    "hard goals still violated after optimization: "
                    + ", ".join(hard_violated))

        from cruise_control_tpu.scenario.report import balancedness_score
        balancedness = balancedness_score(
            [g.name for g in goals],
            frozenset(g.name for g in goals if g.is_hard),
            violated_after, self.balancedness_weights)

        final_placement = None
        if not batch.shared_membership and feasible:
            # per-lane-membership batch (fleet fold): the final
            # placement planes are already fetched per lane — retain
            # them so the router can rebuild this lane's final state
            # (warm-start seeding).  Scenario batches share one base
            # model and never seed warm starts: skip the retention.
            final_placement = dict(
                replica_broker=placements["fin_b"],
                replica_is_leader=placements["fin_l"])
            if placements["fin_d"] is not None:
                final_placement["replica_disk"] = placements["fin_d"]

        proposals: List = []
        if include_proposals and feasible:
            from cruise_control_tpu.analyzer.proposals import \
                diff_proposals_host
            p = placements
            init = dict(replica_broker=p["init_b"],
                        replica_is_leader=p["init_l"])
            opt = dict(replica_broker=p["fin_b"],
                       replica_is_leader=p["fin_l"])
            if p["init_d"] is not None:
                init["replica_disk"] = p["init_d"]
                opt["replica_disk"] = p["fin_d"]
            proposals = diff_proposals_host(
                init, opt, p["valid"], p["base_disk"], p["part"],
                batch.topologies[i], batch.rows_of(i))

        return ScenarioOutcome(
            spec=spec, feasible=feasible, reason=reason, rung="FUSED",
            violated_goals_before=violated_before,
            violated_goals_after=violated_after,
            violated_broker_counts=counts,
            entry_broker_counts=entry_counts,
            rounds_by_goal=rounds_by_goal,
            converged_at_by_goal=converged_by_goal,
            stats_before=stats_before, stats_after=stats_after,
            stats_by_goal=stats_by_goal,
            regressed_goals=regressed,
            invalid_input=bool(invalid),
            final_placement=final_placement,
            balancedness=balancedness,
            num_replica_moves=num_moves,
            num_leadership_moves=leader_moves,
            data_to_move=data,
            proposals=proposals)

    # ------------------------------------------------------------------
    # program cache (AOT-compiled vmapped pipeline programs)
    # ------------------------------------------------------------------
    def _run(self, optimizer, key: str, fn, shapes: tuple,
             donate: tuple, *args):
        import jax
        gk = optimizer._goals_share_key()
        cache_key = ((gk if gk is not None else id(optimizer)),
                     key, shapes)
        with self._lock:
            entry = self._programs.get(cache_key)
            if entry is not None:
                self._programs.move_to_end(cache_key)
        if entry is None:
            faults.inject("scenario.compile")
            if jax.default_backend() == "cpu":
                donate = ()
            t0 = self._time()
            prog = self._compile_batched(gk, key, fn, donate, shapes,
                                         args)
            dt = self._time() - t0
            self.last_compile_s += dt
            if self._metrics is not None:
                self._metrics.update_timer("scenario-compile-timer", dt)
            # the entry PINS the optimizer: id()-keyed entries (goal
            # lists with non-primitive state) must never outlive their
            # optimizer, or a recycled id could serve a different goal
            # list's compiled program
            entry = (prog, optimizer)
            with self._lock:
                self._programs[cache_key] = entry
                self._programs.move_to_end(cache_key)
                while len(self._programs) > self._max_programs:
                    self._programs.popitem(last=False)
        # watched-dispatch gateway (parallel/health.py): a wedged lane
        # batch releases the dispatch thread within mesh.watchdog.ms
        # exactly like a wedged request solve (watchdog-gateway rule)
        prog = entry[0]
        return health.watched_call(lambda: prog(*args), program=key)

    def _compile_batched(self, gk, key: str, fn, donate: tuple,
                         shapes: tuple, args):
        """Compile gateway for the vmapped programs — the same
        persistent-cache protocol as GoalOptimizer's, under the SHARED
        key helpers (parallel/mesh.py) so the engine's keyspace cannot
        drift from the optimizer's: program key (the mesh-lane span
        rides the '@meshN' suffix exactly like the optimizer's mesh
        programs), goal-list signature, input-tree signature (which
        subsumes the in-memory `shapes` tuple: lane count, padding and
        table width are all argument avals), fingerprint.  Hit →
        deserialize + recompile (zero tracing, donation re-applied);
        miss → trace + export + store + compile the round-tripped
        module (one XLA-cache key for cold and warm)."""
        import jax
        from cruise_control_tpu.parallel import mesh as mesh_mod
        from cruise_control_tpu.parallel import progcache as progcache_mod
        cache = progcache_mod.get_cache()
        gsig = mesh_mod.goal_list_signature(gk)
        mesh_k = shapes[-2] if len(shapes) >= 2 else 0
        pkey = mesh_mod.program_key(f"__vmap{key}",
                                    mesh_k if mesh_k else 1)
        shape_sig = mesh_mod.tree_signature(args)
        exported = cache.load_exported(pkey, gsig, shape_sig)
        if exported is not None:
            try:
                return jax.jit(exported.call,
                               donate_argnums=donate).lower(
                    *args).compile()
            except Exception as exc:  # noqa: BLE001 - bad entry => miss
                LOG.warning("progcache: compiling cached %s failed "
                            "(%s); quarantining and recompiling from "
                            "source", pkey,
                            str(exc).splitlines()[0][:120])
                cache.quarantine(pkey, gsig, shape_sig)
        cache.count_fresh_compile()
        program = jax.jit(jax.vmap(fn), donate_argnums=donate)
        if cache.is_active(gsig):
            from jax import export as jexport
            try:
                progcache_mod.ensure_export_registrations()
                blob = bytes(jexport.export(program)(*args).serialize())
                cache.store(pkey, gsig, shape_sig, blob)
                return jax.jit(jexport.deserialize(bytearray(blob)).call,
                               donate_argnums=donate).lower(
                    *args).compile()
            except Exception as exc:  # noqa: BLE001 - the cache layer
                # must never fail the compile it fronts
                LOG.warning("progcache: export of %s failed (%s); "
                            "compiling without the persistent tier",
                            pkey, str(exc).splitlines()[0][:120])
                cache.count_export_error()
        return program.lower(*args).compile()


def _pad_lane_axis(k: int, pad: int, *trees):
    """Grow every [K, ...] array leaf of `trees` by `pad` duplicate
    lanes (copies of lane 0) so the lane axis divides the mesh shard
    count.  Duplicates solve real (lane-0) models, so no NaN/abort
    garbage can leak into the shared instrument tables; every consumer
    reads back only lanes < K."""
    import jax
    import jax.numpy as jnp

    def place(x):
        if (getattr(x, "ndim", 0) >= 1
                and getattr(x, "shape", ())[0] == k):
            x = jnp.asarray(x)
            fill = jnp.broadcast_to(x[:1], (pad,) + tuple(x.shape[1:]))
            return jnp.concatenate([x, fill], axis=0)
        return x
    out = tuple(jax.tree.map(place, t) for t in trees)
    return out if len(out) > 1 else out[0]


def _shard_lane_axis(mesh, k: int, n_devices: int, *trees):
    """device_put every [K, ...] array leaf of `trees` sharded on its
    leading lane axis over the first `n_devices` mesh devices (K is
    padded to a multiple of n_devices first — _pad_lane_axis).
    Non-array leaves and arrays whose leading dim is not the lane axis
    replicate untouched."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from cruise_control_tpu.parallel.mesh import REPLICA_AXIS, make_mesh
    sub = (mesh if n_devices == mesh.size
           else make_mesh(list(mesh.devices.flat)[:n_devices]))
    lanes = NamedSharding(sub, PartitionSpec(REPLICA_AXIS))

    def place(x):
        if (getattr(x, "ndim", 0) >= 1
                and getattr(x, "shape", ())[0] == k):
            return jax.device_put(x, lanes)
        return x
    out = tuple(jax.tree.map(place, t) for t in trees)
    return out if len(out) > 1 else out[0]


def _movement_metrics(initial: ClusterState, final: ClusterState):
    """(replica moves i32, leadership-only moves i32, data-to-move f32) —
    the on-device movement-cost estimate, riding the single instrument
    fetch so ranking never needs the per-scenario proposal diff."""
    import jax.numpy as jnp
    valid = initial.replica_valid
    moved = valid & (final.replica_broker != initial.replica_broker)
    promoted = (valid & final.replica_is_leader
                & ~initial.replica_is_leader & ~moved)
    data = jnp.sum(initial.replica_base_load[:, Resource.DISK] * moved)
    return (jnp.sum(moved.astype(jnp.int32)),
            jnp.sum(promoted.astype(jnp.int32)),
            data)
