"""Declarative what-if scenario specifications.

A `ScenarioSpec` describes ONE hypothetical cluster variant relative to
the live model: brokers added (hypothetical rows or freshly-joined
brokers marked as immigration targets), removed (modeled dead so the
solve drains them), or demoted; per-resource load scaling for
topic-growth projections; capacity overrides; and an optional goal-list
override.  Specs are pure data — the compiler (scenario/compiler.py)
materializes them into padded `ClusterState` variants and the engine
(scenario/engine.py) evaluates K of them in one batched device program.

The JSON form (see `SCENARIO_SPEC_SCHEMA`) is the SCENARIOS REST
endpoint's request-body contract; `parse_scenarios_payload` is the one
parser used by the server, the client, and the operator CLI so the
three can never drift.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Tuple

from cruise_control_tpu.common.resources import NUM_RESOURCES

#: resource name <-> index (Resource enum order: CPU, NW_IN, NW_OUT, DISK)
RESOURCE_NAMES = ("cpu", "nw_in", "nw_out", "disk")


class ScenarioSpecError(ValueError):
    """400-level: malformed or inconsistent scenario specification."""


@dataclasses.dataclass(frozen=True)
class BrokerAdd:
    """One broker addition.  An id already present in the topology marks
    the EXISTING broker as new (the ADD_BROKER 'freshly joined, empty'
    semantics); an unknown id materializes a hypothetical broker row.
    `capacity` maps resource name -> value (hypothetical rows default to
    the mean capacity of alive brokers); `rack` names the rack
    (hypothetical rows default to round-robin over existing racks)."""

    broker_id: int
    rack: Optional[str] = None
    capacity: Optional[Dict[str, float]] = None

    def to_json(self) -> dict:
        out: dict = {"brokerId": self.broker_id}
        if self.rack is not None:
            out["rack"] = self.rack
        if self.capacity is not None:
            out["capacity"] = dict(self.capacity)
        return out

    @classmethod
    def from_json(cls, obj) -> "BrokerAdd":
        if isinstance(obj, int):
            return cls(broker_id=obj)
        if not isinstance(obj, dict) or "brokerId" not in obj:
            raise ScenarioSpecError(
                f"broker addition must be an int or an object with "
                f"brokerId, got {obj!r}")
        cap = obj.get("capacity")
        if cap is not None:
            _check_resource_map("capacity", cap, allow_zero=False)
        return cls(broker_id=int(obj["brokerId"]),
                   rack=obj.get("rack"),
                   capacity=None if cap is None
                   else {k: float(v) for k, v in cap.items()})


def _check_resource_map(what: str, m, allow_zero: bool = True) -> None:
    if not isinstance(m, dict):
        raise ScenarioSpecError(f"{what} must map resource name -> number")
    for k, v in m.items():
        if k not in RESOURCE_NAMES:
            raise ScenarioSpecError(
                f"{what} names unknown resource {k!r}; "
                f"legal: {list(RESOURCE_NAMES)}")
        try:
            v = float(v)
        except (TypeError, ValueError):
            raise ScenarioSpecError(f"{what}[{k}] must be a number")
        if v < 0 or (not allow_zero and v == 0):
            raise ScenarioSpecError(f"{what}[{k}] must be positive")


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """One hypothetical cluster variant (pure data; see module doc)."""

    name: str
    add_brokers: Tuple[BrokerAdd, ...] = ()
    remove_brokers: Tuple[int, ...] = ()
    demote_brokers: Tuple[int, ...] = ()
    #: per-resource load multipliers (topic-growth projection): applied to
    #: every replica's base load and every partition's leadership bonus
    load_scale: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: broker id -> {resource: absolute capacity} overrides
    capacity_overrides: Dict[int, Dict[str, float]] = dataclasses.field(
        default_factory=dict)
    #: goal-list override for this scenario (None = the engine default);
    #: scenarios sharing a goal list share one batched program
    goals: Optional[Tuple[str, ...]] = None
    #: restrict replica-move destinations to the added brokers (the
    #: ADD_BROKER no-old->old-movement rule; facade candidate routing)
    only_move_to_added: bool = False

    def is_noop(self) -> bool:
        """True for the identity scenario (the base solve)."""
        return not (self.add_brokers or self.remove_brokers
                    or self.demote_brokers or self.load_scale
                    or self.capacity_overrides)

    # ------------------------------------------------------------------
    def validate(self, topology=None) -> None:
        """Raise ScenarioSpecError on an inconsistent spec; with a
        `topology` (ClusterTopology) also check broker ids exist where
        they must."""
        if not self.name or not isinstance(self.name, str):
            raise ScenarioSpecError("scenario needs a non-empty name")
        _check_resource_map("loadScale", self.load_scale, allow_zero=False)
        for b, caps in self.capacity_overrides.items():
            _check_resource_map(f"capacityOverrides[{b}]", caps,
                                allow_zero=False)
        added = {a.broker_id for a in self.add_brokers}
        if len(added) != len(self.add_brokers):
            raise ScenarioSpecError(
                f"{self.name}: duplicate broker ids in add_brokers")
        overlap = added & set(self.remove_brokers)
        if overlap:
            raise ScenarioSpecError(
                f"{self.name}: brokers {sorted(overlap)} both added and "
                f"removed")
        if self.only_move_to_added and not self.add_brokers:
            raise ScenarioSpecError(
                f"{self.name}: only_move_to_added without add_brokers")
        if topology is not None:
            known = set(topology.broker_ids)
            for what, ids in (("remove_brokers", self.remove_brokers),
                              ("demote_brokers", self.demote_brokers),
                              ("capacity_overrides",
                               self.capacity_overrides)):
                unknown = [b for b in ids if b not in known
                           and b not in added]
                if unknown:
                    raise ScenarioSpecError(
                        f"{self.name}: {what} names unknown brokers "
                        f"{sorted(unknown)}")

    # ------------------------------------------------------------------
    def to_json(self) -> dict:
        out: dict = {"name": self.name}
        if self.add_brokers:
            out["addBrokers"] = [a.to_json() for a in self.add_brokers]
        if self.remove_brokers:
            out["removeBrokers"] = list(self.remove_brokers)
        if self.demote_brokers:
            out["demoteBrokers"] = list(self.demote_brokers)
        if self.load_scale:
            out["loadScale"] = dict(self.load_scale)
        if self.capacity_overrides:
            out["capacityOverrides"] = {
                str(b): dict(c) for b, c in self.capacity_overrides.items()}
        if self.goals is not None:
            out["goals"] = list(self.goals)
        if self.only_move_to_added:
            out["onlyMoveToAdded"] = True
        return out

    @classmethod
    def from_json(cls, obj) -> "ScenarioSpec":
        if not isinstance(obj, dict):
            raise ScenarioSpecError(f"scenario must be an object, "
                                    f"got {type(obj).__name__}")
        unknown = set(obj) - {"name", "addBrokers", "removeBrokers",
                              "demoteBrokers", "loadScale",
                              "capacityOverrides", "goals",
                              "onlyMoveToAdded"}
        if unknown:
            raise ScenarioSpecError(
                f"unknown scenario fields {sorted(unknown)}")
        try:
            cap_over = {int(b): {k: float(v) for k, v in caps.items()}
                        for b, caps
                        in (obj.get("capacityOverrides") or {}).items()}
        except (TypeError, ValueError, AttributeError):
            raise ScenarioSpecError(
                "capacityOverrides must map broker id -> "
                "{resource: number}")
        spec = cls(
            name=str(obj.get("name", "")),
            add_brokers=tuple(BrokerAdd.from_json(a)
                              for a in obj.get("addBrokers") or ()),
            remove_brokers=tuple(int(b)
                                 for b in obj.get("removeBrokers") or ()),
            demote_brokers=tuple(int(b)
                                 for b in obj.get("demoteBrokers") or ()),
            load_scale={k: float(v)
                        for k, v in (obj.get("loadScale") or {}).items()},
            capacity_overrides=cap_over,
            goals=(tuple(str(g) for g in obj["goals"])
                   if obj.get("goals") is not None else None),
            only_move_to_added=bool(obj.get("onlyMoveToAdded", False)),
        )
        spec.validate()
        return spec

    def load_scale_vector(self):
        """f32[RES] multiplier vector (1.0 where unnamed)."""
        import numpy as np
        vec = np.ones(NUM_RESOURCES, dtype=np.float32)
        for k, v in self.load_scale.items():
            vec[RESOURCE_NAMES.index(k)] = v
        return vec


#: JSON Schema (draft 2020-12) of ONE scenario object — embedded in the
#: SCENARIOS request-body schema and published via api/schema.py
_RES_MAP = {"type": "object",
            "properties": {r: {"type": "number", "exclusiveMinimum": 0}
                           for r in RESOURCE_NAMES},
            "additionalProperties": False}
SCENARIO_SPEC_SCHEMA = {
    "type": "object",
    "properties": {
        "name": {"type": "string", "minLength": 1},
        "addBrokers": {"type": "array", "items": {"oneOf": [
            {"type": "integer"},
            {"type": "object",
             "properties": {"brokerId": {"type": "integer"},
                            "rack": {"type": "string"},
                            "capacity": _RES_MAP},
             "required": ["brokerId"], "additionalProperties": False},
        ]}},
        "removeBrokers": {"type": "array", "items": {"type": "integer"}},
        "demoteBrokers": {"type": "array", "items": {"type": "integer"}},
        "loadScale": _RES_MAP,
        "capacityOverrides": {"type": "object",
                              "additionalProperties": _RES_MAP},
        "goals": {"type": "array", "items": {"type": "string"}},
        "onlyMoveToAdded": {"type": "boolean"},
    },
    "required": ["name"],
    "additionalProperties": False,
}

#: request body of the SCENARIOS endpoint
SCENARIOS_REQUEST_SCHEMA = {
    "type": "object",
    "properties": {
        "scenarios": {"type": "array", "items": SCENARIO_SPEC_SCHEMA,
                      "minItems": 1},
        "goals": {"type": "array", "items": {"type": "string"}},
        "includeBase": {"type": "boolean"},
    },
    "required": ["scenarios"],
    "additionalProperties": False,
}


def parse_scenarios_payload(body) -> Tuple[List[ScenarioSpec],
                                           Optional[List[str]],
                                           Optional[bool]]:
    """(specs, goal override, include_base) from a SCENARIOS request body
    (str/bytes JSON or an already-parsed dict).  `include_base` is None
    when the body does not say — the facade then applies the
    scenario.include.base.solve config default.  Raises
    ScenarioSpecError (a ValueError -> HTTP 400) on anything
    malformed."""
    if body is None or body == "" or body == b"":
        raise ScenarioSpecError(
            "SCENARIOS requires a JSON body: "
            '{"scenarios": [{"name": ..., ...}]}')
    if isinstance(body, (bytes, bytearray)):
        body = body.decode("utf-8", errors="replace")
    if isinstance(body, str):
        try:
            body = json.loads(body)
        except json.JSONDecodeError as exc:
            raise ScenarioSpecError(f"request body is not JSON: {exc}")
    if not isinstance(body, dict) or not isinstance(
            body.get("scenarios"), list) or not body["scenarios"]:
        raise ScenarioSpecError(
            'request body must be {"scenarios": [...]} with at least one '
            'scenario')
    unknown = set(body) - {"scenarios", "goals", "includeBase"}
    if unknown:
        raise ScenarioSpecError(f"unknown body fields {sorted(unknown)}")
    specs = [ScenarioSpec.from_json(s) for s in body["scenarios"]]
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        raise ScenarioSpecError("scenario names must be unique")
    goals = body.get("goals")
    if goals is not None and (not isinstance(goals, list)
                              or not all(isinstance(g, str)
                                         for g in goals)):
        raise ScenarioSpecError("goals must be a list of goal names")
    include_base = body.get("includeBase")
    if include_base is not None:
        include_base = bool(include_base)
    return specs, goals, include_base


def candidate_broker_sets(broker_ids: Sequence) -> Optional[List[List[int]]]:
    """None when `broker_ids` is a flat id list (the single-solve path);
    the K candidate sets when it is a sequence of sequences (the facade's
    batched what-if routing for ADD/REMOVE/DEMOTE_BROKER)."""
    ids = list(broker_ids)
    if not ids or not any(isinstance(b, (list, tuple, set, frozenset))
                          for b in ids):
        return None
    if not all(isinstance(b, (list, tuple, set, frozenset)) for b in ids):
        raise ScenarioSpecError(
            "broker ids must be all ints (one candidate set) or all "
            "lists (multiple candidate sets), not a mix")
    return [sorted(int(x) for x in s) for s in ids]
