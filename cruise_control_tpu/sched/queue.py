"""Bounded admission queue with backpressure and single-flight
coalescing.

Admission: each class owns a queue cap (policy.py); an offer beyond the
cap raises `QueueFullError` carrying a `retry_after_s` derived from the
observed solve-latency EWMA times the queue depth — the REST layer turns
it into HTTP 429 + `Retry-After`, and the client backs off accordingly.

Single-flight coalescing: a job may carry a `coalesce_key` (the facade
keys request-path solves on goal list x model generation x options hash).
An offer whose key matches a QUEUED OR IN-FLIGHT ticket attaches to it
instead of admitting a second identical solve — N identical concurrent
rebalances pay ONE compile+solve and share the result.  Attaching a
more urgent class upgrades the pending entry's dispatch priority (the
solve is the same; its urgency is the max of its waiters').
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from cruise_control_tpu.sched.policy import SchedulerClass, SchedulerPolicy


class QueueFullError(RuntimeError):
    """Admission rejected: the class queue is at its cap.  `retry_after_s`
    is the backpressure hint (latency EWMA x queue depth) the REST layer
    forwards as the `Retry-After` header."""

    #: obs/trace.py classification: backpressure, not failure — the
    #: trace records outcome "rejected" (visible in the flight-recorder
    #: ring, never pinned), matching the 429-not-500 REST semantics
    trace_outcome = "rejected"

    def __init__(self, klass: SchedulerClass, depth: int, cap: int,
                 retry_after_s: float) -> None:
        super().__init__(
            f"solve queue full for class {klass.name}: {depth} queued "
            f">= cap {cap}; retry in ~{retry_after_s:.0f}s")
        self.klass = klass
        self.depth = depth
        self.cap = cap
        self.retry_after_s = retry_after_s


class SolveTicket:
    """One admitted solve, shared by every coalesced waiter."""

    def __init__(self, klass: SchedulerClass, enqueued_at: float,
                 queue: "AdmissionQueue") -> None:
        self.klass = klass
        self.enqueued_at = enqueued_at
        #: wall-clock when the dispatch loop picked the job up (None
        #: while still queued)
        self.started_at: Optional[float] = None
        #: requests that attached to this solve beyond the first
        self.attach_count = 0
        #: trace id of the job that created this ticket (obs/trace.py):
        #: coalesced waiters link their own trace to the leader's solve
        #: through it
        self.trace_id: Optional[str] = None
        self._queue = queue
        self._done = threading.Event()
        self._result = None
        self._exc: Optional[BaseException] = None

    # -- resolution ----------------------------------------------------
    def resolve(self, result) -> None:
        self._result = result
        self._done.set()

    def fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("solve did not finish within the timeout")
        if self._exc is not None:
            raise self._exc
        return self._result

    # -- operator visibility (USER_TASKS QueuePosition / ETA) ----------
    def queue_position(self) -> Optional[int]:
        """0-based number of entries that would dispatch before this one;
        None once dispatched (running or finished)."""
        return self._queue.position_of(self)

    def estimated_start_ms(self) -> float:
        """Epoch-ms start estimate: actual start once dispatched,
        otherwise now + (position + 1) x the solve-latency EWMA (the +1
        accounts for the solve occupying the device right now)."""
        return self._queue.estimated_start_ms(self)


class _Entry:
    __slots__ = ("job", "ticket", "klass", "best_klass", "enqueued_at",
                 "last_queued_at", "seq")

    def __init__(self, job, ticket: SolveTicket, seq: int) -> None:
        self.job = job
        self.ticket = ticket
        self.klass = job.klass          #: admission class (cap accounting)
        self.best_klass = job.klass     #: dispatch class (upgraded by
        self.enqueued_at = ticket.enqueued_at  # coalesced waiters)
        #: last time the entry (re)entered the queue: aging uses
        #: enqueued_at (credit survives preemption), but the per-class
        #: wait metrics sample now - last_queued_at so a redispatch
        #: after preemption does not re-log the full original wait
        self.last_queued_at = ticket.enqueued_at
        self.seq = seq


class AdmissionQueue:
    """Thread-safe priority admission queue (see module docstring)."""

    #: EWMA smoothing for observed solve latency
    _ALPHA = 0.3

    def __init__(self, policy: SchedulerPolicy,
                 time_fn: Callable[[], float]) -> None:
        self._policy = policy
        self._time = time_fn
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._entries: List[_Entry] = []
        #: coalesce key -> ticket, held from admission until the solve
        #: RESOLVES (so in-flight solves keep attracting identical
        #: requests)
        self._by_key: Dict[tuple, Tuple[SolveTicket, Optional[_Entry]]] = {}
        self._depth: Dict[SchedulerClass, int] = {c: 0
                                                  for c in SchedulerClass}
        #: entries popped for service (take/take_fold_peers) but not
        #: yet settled (done_serving/requeue): counted under the same
        #: lock as the pop so idle() is race-free
        self._in_service = 0
        self._seq = 0
        self._latency_ewma_s = 0.0
        self._latency_samples = 0

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------
    def offer(self, job) -> Tuple[SolveTicket, bool]:
        """Admit `job` (or attach to an identical queued/in-flight one).
        Returns (ticket, created); raises QueueFullError at the cap."""
        with self._cond:
            key = job.coalesce_key
            if key is not None:
                hit = self._by_key.get(key)
                if hit is not None and not hit[0].done():
                    ticket, entry = hit
                    ticket.attach_count += 1
                    if job.klass.value < ticket.klass.value:
                        # a more urgent waiter attached: the shared solve
                        # dispatches (and reports in USER_TASKS) at the
                        # best attached class, not the creator's
                        ticket.klass = job.klass
                    if entry is not None \
                            and job.klass.value < entry.best_klass.value:
                        entry.best_klass = job.klass
                    return ticket, False
            depth = self._depth[job.klass]
            cap = self._policy.queue_cap(job.klass)
            if depth >= cap:
                raise QueueFullError(job.klass, depth, cap,
                                     self._retry_after_locked(job.klass))
            ticket = SolveTicket(job.klass, self._time(), self)
            trace_ctx = getattr(job, "trace", None)
            if trace_ctx is not None:
                # duck-typed (obs.trace.TraceContext): this module keeps
                # zero obs dependencies, the id alone is what waiters
                # link against
                ticket.trace_id = getattr(trace_ctx, "trace_id", None)
            self._seq += 1
            entry = _Entry(job, ticket, self._seq)
            self._entries.append(entry)
            self._depth[job.klass] += 1
            if key is not None:
                self._by_key[key] = (ticket, entry)
            self._cond.notify()
            return ticket, True

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------
    def take(self, stop: threading.Event,
             poll_s: float = 0.5) -> Optional[_Entry]:
        """Pop the best-effective-priority entry; blocks until one is
        available or `stop` is set (then returns None)."""
        with self._cond:
            while not self._entries:
                if stop.is_set():
                    return None
                self._cond.wait(poll_s)
            entry = min(self._entries, key=self._dispatch_key)
            self._pop_locked(entry)
            entry.ticket.started_at = self._time()
            return entry

    def _dispatch_key(self, e: _Entry):
        now = self._time()
        return (self._policy.effective_priority(e.best_klass,
                                                now - e.enqueued_at),
                e.seq)

    def _pop_locked(self, entry: _Entry) -> None:
        self._entries.remove(entry)
        self._depth[entry.klass] -= 1
        # popped-for-service under the SAME lock as the removal, so
        # depth()==0 can never race a just-taken entry past idle() (the
        # graceful-drain quiesce reads it); the scheduler settles the
        # count via done_serving()/requeue()
        self._in_service += 1
        # the _by_key mapping STAYS: identical requests attach to the
        # in-flight solve until finish() severs it

    def take_fold_peers(self, fold_key: tuple, limit: int) -> List[_Entry]:
        """Pop up to `limit` queued entries sharing `fold_key` (scenario
        folding: compatible sweeps merge into one vmapped batch)."""
        if limit <= 0:
            return []
        with self._cond:
            peers = [e for e in self._entries
                     if getattr(e.job, "fold_key", None) == fold_key]
            peers.sort(key=lambda e: e.seq)
            peers = peers[:limit]
            for e in peers:
                self._pop_locked(e)
                e.ticket.started_at = self._time()
            return peers

    def requeue(self, entry: _Entry) -> None:
        """Put a preempted entry back, keeping its original enqueue time
        (its aging credit keeps accruing across preemptions)."""
        with self._cond:
            entry.ticket.started_at = None
            entry.last_queued_at = self._time()
            self._entries.append(entry)
            self._depth[entry.klass] += 1
            self._in_service -= 1     # back to queued, atomically
            self._cond.notify()

    def done_serving(self, n: int = 1) -> None:
        """The scheduler finished (resolved or failed) `n` entries it
        had taken — the other half of _pop_locked's in-service count."""
        with self._cond:
            self._in_service -= n

    def idle(self) -> bool:
        """Nothing queued AND nothing taken-but-unfinished, read under
        one lock — the race-free predicate the drain path polls."""
        with self._cond:
            return not self._entries and self._in_service == 0

    def finish(self, entry: _Entry) -> None:
        """Sever the coalesce binding once the solve resolved (call
        BEFORE resolving the ticket so late arrivals start a fresh
        solve rather than attaching to a completed one)."""
        key = getattr(entry.job, "coalesce_key", None)
        if key is None:
            return
        with self._cond:
            hit = self._by_key.get(key)
            if hit is not None and hit[0] is entry.ticket:
                self._by_key.pop(key, None)

    def drain(self) -> List[_Entry]:
        """Remove and return everything queued (shutdown: fail their
        tickets)."""
        with self._cond:
            entries, self._entries = self._entries, []
            for c in SchedulerClass:
                self._depth[c] = 0
            self._by_key.clear()
            return entries

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    def has_effective_better_than(self, effective: float) -> bool:
        """A queued entry whose LIVE effective priority (aging included)
        strictly beats `effective` — the preemption predicate consulted
        at segment checkpoints.  Comparing effective priorities on BOTH
        sides bounds preemption thrash: a running job's aging credit
        keeps accruing (requeue preserves enqueued_at), so sustained
        higher-class traffic delays it a bounded number of segments
        instead of livelocking it."""
        with self._lock:
            now = self._time()
            return any(
                self._policy.effective_priority(e.best_klass,
                                                now - e.enqueued_at)
                < effective
                for e in self._entries)

    def depth(self, klass: Optional[SchedulerClass] = None) -> int:
        with self._lock:
            if klass is not None:
                return self._depth[klass]
            return len(self._entries)

    def depths(self) -> Dict[SchedulerClass, int]:
        with self._lock:
            return dict(self._depth)

    def oldest_wait_s(self) -> float:
        with self._lock:
            if not self._entries:
                return 0.0
            now = self._time()
            return max(now - e.enqueued_at for e in self._entries)

    def position_of(self, ticket: SolveTicket) -> Optional[int]:
        with self._lock:
            ordered = sorted(self._entries, key=self._dispatch_key)
            for i, e in enumerate(ordered):
                if e.ticket is ticket:
                    return i
            return None

    def estimated_start_ms(self, ticket: SolveTicket) -> float:
        started = ticket.started_at
        if started is not None:
            return started * 1000.0
        pos = self.position_of(ticket)
        now = self._time()
        if pos is None:       # resolved before it ever dispatched
            return now * 1000.0
        with self._lock:
            per_solve = max(self._latency_ewma_s, 0.1)
        return (now + (pos + 1) * per_solve) * 1000.0

    # ------------------------------------------------------------------
    # latency EWMA -> Retry-After
    # ------------------------------------------------------------------
    def observe_latency(self, duration_s: float) -> None:
        with self._lock:
            if self._latency_samples == 0:
                self._latency_ewma_s = duration_s
            else:
                self._latency_ewma_s = (self._ALPHA * duration_s
                                        + (1 - self._ALPHA)
                                        * self._latency_ewma_s)
            self._latency_samples += 1

    def latency_ewma_s(self) -> float:
        with self._lock:
            return self._latency_ewma_s

    def _retry_after_locked(self, klass: SchedulerClass) -> float:
        """Caller holds the lock.  Depth x latency EWMA, clamped to
        [1s, 600s]: roughly when the rejected class's backlog will have
        drained."""
        per_solve = max(self._latency_ewma_s, 0.1)
        depth = self._depth[klass] + 1
        return min(600.0, max(1.0, depth * per_solve))
