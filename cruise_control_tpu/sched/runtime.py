"""Thread-local runtime hooks tying the device-time scheduler to the
solver pipeline without an import cycle.

The scheduler (sched/scheduler.py) sits ABOVE the facade's solve paths;
the solver pipeline (analyzer/optimizer.py, scenario/engine.py) sits
BELOW them.  Both ends need a tiny shared surface:

* the *gateway* flag — set for the duration of a scheduled job so tests
  (and the chaos stress suite) can assert every device solve entered
  through the scheduler ("single-gateway" invariant; the static half is
  tools/lint.py's gateway rule);
* the *segment checkpoint* — the dispatch loop installs a preemption
  check around a preemptible job; the optimizer and the scenario engine
  call `segment_checkpoint()` between goal segments, and when the check
  fires the in-flight solve unwinds with `SolvePreempted` at that
  boundary (device buffers are simply dropped; the scheduler re-queues
  the job and serves the higher-priority request first);
* the *submission listener* — the USER_TASKS pool registers a callback
  per operation run so the user-task registry can attach the scheduler
  ticket (queue position / class / ETA) to the task it is serving.

This module has NO dependencies inside the package, so the optimizer can
import it without pulling the scheduler (and vice versa).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Callable, Optional

_TLS = threading.local()


class SolvePreempted(Exception):
    """Control-flow signal, not an error: the dispatch loop asked the
    in-flight solve to yield the device at the next segment boundary
    because a higher-priority request is queued.  The scheduler catches
    it, re-queues the preempted job (original enqueue time kept, so its
    anti-starvation aging continues), and dispatches the higher-priority
    work.  Never ladder material — a preempted solve did not FAIL."""


def under_gateway() -> bool:
    """True while the current thread is executing a scheduled solve job
    (or an inline job of a disabled scheduler) — the runtime half of the
    single-gateway invariant."""
    return getattr(_TLS, "gateway_depth", 0) > 0


@contextlib.contextmanager
def gateway(preempt_check: Optional[Callable[[], bool]] = None,
            async_dispatch: bool = False):
    """Mark the current thread as inside the solve gateway; when
    `preempt_check` is given, `segment_checkpoint()` consults it between
    goal segments and raises SolvePreempted when it returns True.

    `async_dispatch=True` marks a job running on the scheduler's OWN
    dispatch thread (a separate thread from its submitter): re-queueing
    machinery — preemption, mesh-recovery requeue — is only meaningful
    there, because an inline job has no queue entry to re-queue and
    must instead retry in place (`dispatch_is_async()` is how the solve
    paths below the facade pick between the two)."""
    depth = getattr(_TLS, "gateway_depth", 0)
    prev_check = getattr(_TLS, "preempt_check", None)
    prev_async = getattr(_TLS, "async_dispatch", False)
    _TLS.gateway_depth = depth + 1
    _TLS.preempt_check = preempt_check
    _TLS.async_dispatch = async_dispatch
    try:
        yield
    finally:
        _TLS.gateway_depth = depth
        _TLS.preempt_check = prev_check
        _TLS.async_dispatch = prev_async


def dispatch_is_async() -> bool:
    """True while the current thread runs a job the scheduler's
    dispatch loop (not the submitter) is executing — i.e. raising a
    SolvePreempted-family exception will RE-QUEUE the job instead of
    surfacing to a caller."""
    return getattr(_TLS, "async_dispatch", False)


@contextlib.contextmanager
def shielded():
    """Suppress the preemption checkpoint for the duration.  Used by the
    fleet router once part of a multi-group fold has committed results:
    a SolvePreempted past that point would make the dispatch loop
    re-queue (and re-run) work that is already done, so the remainder of
    the fold runs to completion and the higher-priority job takes the
    device right after instead."""
    prev = getattr(_TLS, "preempt_check", None)
    _TLS.preempt_check = None
    try:
        yield
    finally:
        _TLS.preempt_check = prev


@contextlib.contextmanager
def mesh_token_scope(token):
    """Put the scheduler's mesh token in scope for the duration of a
    dispatched (or inline) solve job: the dispatch thread OWNS the mesh
    the way it owns the device, and the solve paths below the facade
    (degradation ladder rung selection, scenario lane batching) read it
    back via `current_mesh_token()` instead of acquiring devices
    themselves.  The token is opaque to this module (no package
    dependencies here); `None` is a valid scope meaning single-chip."""
    prev = getattr(_TLS, "mesh_token", None)
    _TLS.mesh_token = token
    try:
        yield
    finally:
        _TLS.mesh_token = prev


def current_mesh_token():
    """The mesh token of the solve job executing on this thread (None
    outside the gateway or under a scheduler with no mesh)."""
    return getattr(_TLS, "mesh_token", None)


def segment_checkpoint() -> None:
    """Called by the solver between goal segments (and by the scenario
    engine between batched segments): a no-op unless the scheduler
    installed a preemption check for the running job.  One host-side
    predicate read per segment — no device sync."""
    check = getattr(_TLS, "preempt_check", None)
    if check is not None and check():
        raise SolvePreempted(
            "higher-priority solve queued; yielding the device at a "
            "segment boundary")


# ---------------------------------------------------------------------------
# submission listener (user-task <-> scheduler-ticket linkage)
# ---------------------------------------------------------------------------
def set_submission_listener(cb: Callable[[object], None]) -> None:
    """Install a per-thread callback invoked with every SolveTicket the
    current thread's work submits to the scheduler."""
    _TLS.submission_listener = cb


def clear_submission_listener() -> None:
    _TLS.submission_listener = None


def notify_submission(ticket: object) -> None:
    """Report a scheduler submission to the current thread's listener
    (no-op without one)."""
    cb = getattr(_TLS, "submission_listener", None)
    if cb is not None:
        cb(ticket)
