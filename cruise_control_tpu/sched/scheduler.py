"""The device-time scheduler: one dispatch loop owning the device token.

Every solve in the process — REST rebalances, the proposal precompute,
anomaly-remediation solves, scenario sweeps — is wrapped in a `SolveJob`
and submitted here; submitters block on a `SolveTicket` while the single
dispatch thread runs jobs one at a time in effective-priority order
(policy.py).  That buys, over the unscheduled free-for-all of 8
USER_TASKS pool threads + the precompute loop + detector self-healing
racing one accelerator:

* **priority admission** — an anomaly heal never sits behind a queued
  32-scenario sweep; aging keeps the background classes from starving;
* **single-flight coalescing** — N identical queued/in-flight requests
  attach to ONE compile+solve (queue.py);
* **scenario folding** — compatible queued SCENARIO_SWEEP jobs merge
  into one vmapped engine batch (one compile amortized over all of
  them) and their outcomes are split back per caller;
* **preemption** — preemptible jobs (PRECOMPUTE / SCENARIO_SWEEP) are
  asked to yield at the next goal-segment boundary when a
  higher-priority class queues up (runtime.segment_checkpoint); the
  abandoned job is re-queued with its aging intact, compiled programs
  and the proposal cache untouched;
* **backpressure** — admission beyond a class's queue cap raises
  QueueFullError, surfaced as HTTP 429 + Retry-After.

The solve itself is whatever the facade wrapped — the PR-2 degradation
ladder, the PR-1 fused pipeline, the PR-3 scenario engine all run
unchanged inside the job.

Fault site: ``sched.dispatch`` fires before every job execution so chaos
tests can fail dispatches deterministically.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Callable, List, Optional

from cruise_control_tpu.obs import trace as obs_trace
from cruise_control_tpu.sched import runtime
from cruise_control_tpu.sched.policy import SchedulerClass, SchedulerPolicy
from cruise_control_tpu.sched.queue import (AdmissionQueue, QueueFullError,
                                            SolveTicket)
from cruise_control_tpu.sched.stats import SchedulerStats, attach_metrics
from cruise_control_tpu.utils import faults

LOG = logging.getLogger(__name__)

__all__ = ["SolveJob", "DeviceTimeScheduler", "FoldedFailure",
           "QueueFullError", "SchedulerClass", "SolveTicket"]


class FoldedFailure:
    """Per-entry failure marker a `fold_run` may return IN PLACE of a
    result: that entry's ticket fails with `exc` while its fold peers
    still resolve normally.  Raising inside fold_run fails the WHOLE
    fold — one tenant's solver verdict inside a cross-tenant fleet batch
    must instead fail only that tenant's waiter (fleet/router.py)."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException) -> None:
        self.exc = exc


@dataclasses.dataclass
class SolveJob:
    """One unit of device work.

    `run` executes the solve and returns its result.  `coalesce_key`
    (optional) enables single-flight: identical keys share one
    execution.  Fold support (SCENARIO_SWEEP): jobs sharing a non-None
    `fold_key` may be merged — the scheduler calls `fold_run` with the
    list of every folded job's `fold_payload` and expects one result per
    payload, in order."""

    klass: SchedulerClass
    run: Callable[[], Any]
    label: str = ""
    coalesce_key: Optional[tuple] = None
    preemptible: bool = False
    fold_key: Optional[tuple] = None
    fold_payload: Any = None
    fold_run: Optional[Callable[[List[Any]], List[Any]]] = None
    #: obs.trace.TraceContext of the submitting request: the dispatch
    #: thread activates it around the solve so queue-wait, dispatch,
    #: fold and preemption land in the request's span tree.  Every
    #: facade submission carries one (tools/lint.py trace rule); None =
    #: untraced (tests, embedding code)
    trace: Optional[object] = None


class SchedulerStoppedError(RuntimeError):
    """The scheduler shut down while this request was queued."""


class DeviceTimeScheduler:
    """See module docstring.  `enabled=False` degenerates to running
    every job inline on the submitting thread (still inside the gateway,
    so the single-gateway invariant holds either way) — the K=1
    single-client path is byte-identical in both modes because the job
    body is the same code."""

    def __init__(self, policy: Optional[SchedulerPolicy] = None,
                 enabled: bool = True,
                 max_fold: int = 8,
                 mesh_token=None,
                 mesh_supervisor=None,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        import time as _time
        self.policy = policy or SchedulerPolicy.default()
        self.enabled = enabled
        #: the scheduler's device topology (parallel/mesh.MeshToken or
        #: None = single chip): the dispatch thread owns the WHOLE mesh
        #: and puts the token in scope around every job it runs, so
        #: high-priority solves get all chips while batch-shaped work
        #: (scenario sweeps, fleet folds) uses the same mesh as a second
        #: batching axis.  Under fleet serving the shared scheduler's
        #: token governs every tenant.
        self.mesh_token = mesh_token
        #: mesh health authority (parallel/health.MeshSupervisor or
        #: None): when present, every dispatch resolves its token
        #: through the supervisor instead of the static `mesh_token`,
        #: so a span shrink between dispatches re-shards the very next
        #: job — request solves, scenario lanes and fleet folds alike —
        #: without the scheduler restarting anything
        self.mesh_supervisor = mesh_supervisor
        self._max_fold = max(1, max_fold)
        #: INLINE jobs currently executing (disabled scheduler /
        #: nested dispatcher submits — they never touch the queue, so
        #: the queue's in-service count cannot see them): the drain
        #: path's quiesce() reads it alongside queue.idle()
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._time = time_fn or _time.time
        self.queue = AdmissionQueue(self.policy, self._time)
        self.stats = SchedulerStats(self._time)
        self._metrics = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._thread_lock = threading.Lock()

    # ------------------------------------------------------------------
    def attach_metrics(self, registry) -> None:
        self._metrics = registry
        attach_metrics(registry, self)

    def _mark(self, sensor: str, n: int = 1) -> None:
        if self._metrics is not None:
            self._metrics.meter(sensor).mark(n)

    # ------------------------------------------------------------------
    # submission (blocking: the caller's thread waits on the ticket)
    # ------------------------------------------------------------------
    def submit(self, job: SolveJob,
               timeout: Optional[float] = None) -> Any:
        """Run `job` under the scheduler and return its result (or raise
        what it raised).  Raises QueueFullError at the class queue cap.

        Inline execution (no queue) happens when the scheduler is
        disabled or when the DISPATCH THREAD itself submits (a scheduled
        job that submits nested device work must not deadlock waiting
        for the busy dispatcher).  A submission after stop() is rejected
        with SchedulerStoppedError — running it inline would race the
        rest of teardown with a full device solve (facade.shutdown
        relies on nothing new being admitted)."""
        if (self._stop.is_set() and self.enabled
                and threading.current_thread() is not self._thread):
            raise SchedulerStoppedError(
                "scheduler is stopped; not accepting new solves")
        self.stats.record_submitted()
        if (not self.enabled
                or threading.current_thread() is self._thread):
            t0 = self._time()
            failed = True
            with self._inflight_lock:
                self._inflight += 1
            try:
                with runtime.mesh_token_scope(self._current_mesh_token()), \
                        runtime.gateway():
                    result = job.run()
                failed = False
                return result
            finally:
                with self._inflight_lock:
                    self._inflight -= 1
                self.stats.record_done(self._time() - t0, failed)
        try:
            ticket, created = self.queue.offer(job)
        except QueueFullError:
            self.stats.record_rejected()
            self._mark("sched-rejected-requests")
            obs_trace.event("sched.rejected", klass=job.klass.name,
                            ctx=job.trace)
            raise
        if created:
            self._ensure_dispatcher()
        else:
            self.stats.record_coalesced()
            self._mark("sched-coalesced-requests")
            # the waiter's own trace links the leader's solve: a
            # coalesced request never runs its job, so this span is its
            # whole device story
            now = self._time()
            obs_trace.record_span("sched.coalesced", now, now,
                                  ctx=job.trace,
                                  leaderTraceId=ticket.trace_id,
                                  klass=job.klass.name)
        runtime.notify_submission(ticket)
        return ticket.wait(timeout)

    def _current_mesh_token(self):
        """The LIVE mesh token for the next job: the supervisor's
        (survivor span after any shrink/recovery) when one is attached,
        else the static construction-time token."""
        if self.mesh_supervisor is not None:
            return self.mesh_supervisor.current_token()
        return self.mesh_token

    def _ensure_dispatcher(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        with self._thread_lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="solve-scheduler", daemon=True)
                self._thread.start()

    # ------------------------------------------------------------------
    # dispatch loop
    # ------------------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            entry = self.queue.take(self._stop)
            if entry is None:
                continue
            entries = [entry]
            job = entry.job
            if job.fold_key is not None and job.fold_run is not None:
                entries += self.queue.take_fold_peers(job.fold_key,
                                                      self._max_fold - 1)
            self._execute(entries)
        for entry in self.queue.drain():
            self.queue.finish(entry)
            entry.ticket.fail(SchedulerStoppedError(
                "scheduler stopped while the request was queued"))

    def _execute(self, entries: List) -> None:
        job = entries[0].job
        now = self._time()
        best = min(e.best_klass for e in entries)
        lead_trace = getattr(job, "trace", None)
        lead_trace_id = (getattr(lead_trace, "trace_id", None)
                         if lead_trace is not None else None)
        for i, e in enumerate(entries):
            # wait sampled since the LAST (re)queue: a redispatch after
            # preemption logs only the incremental wait, not the full
            # original wait again
            self.stats.record_dispatch(e.best_klass,
                                       now - e.last_queued_at)
            if self._metrics is not None:
                name = e.best_klass.name.lower().replace("_", "-")
                self._metrics.update_timer(f"sched-wait-timer-{name}",
                                           now - e.last_queued_at)
                self._metrics.update_histogram(
                    f"sched-wait-hist-{name}", now - e.last_queued_at)
            tc = getattr(e.job, "trace", None)
            obs_trace.record_span("sched.queue-wait", e.last_queued_at,
                                  now, ctx=tc,
                                  klass=e.best_klass.name)
            if i > 0:
                # fold members: each folded tenant's trace records its
                # LANE in the shared dispatch plus the leader it rode
                obs_trace.record_span("sched.fold-member", now, now,
                                      ctx=tc, lane=i,
                                      leaderTraceId=lead_trace_id)
        if len(entries) > 1:
            obs_trace.event("sched.fold", ctx=lead_trace,
                            members=len(entries))
        check = None
        if (job.preemptible and self.policy.preemption_enabled):
            # evaluate BOTH sides LIVE at each checkpoint: a more urgent
            # request coalescing onto this in-flight solve upgrades
            # best_klass, and the running job's own aging credit keeps
            # accruing (requeue preserves enqueued_at) — so each
            # preemption raises the bar the queued traffic must clear,
            # and a repeatedly-preempted job eventually completes
            # instead of livelocking under sustained interactive load
            def check():
                now = self._time()
                running = min(self.policy.effective_priority(
                    e.best_klass, now - e.enqueued_at) for e in entries)
                return self.queue.has_effective_better_than(running)
        t0 = self._time()
        # every taken entry must be settled exactly once: requeued
        # entries settle inside queue.requeue (atomically with the
        # re-add), everything else through done_serving in the finally
        served = len(entries)
        try:
            faults.inject("sched.dispatch")
            with runtime.mesh_token_scope(self._current_mesh_token()), \
                    runtime.gateway(check, async_dispatch=True), \
                    obs_trace.activate(lead_trace):
                with obs_trace.span("sched.dispatch", klass=best.name,
                                    label=job.label,
                                    folded=len(entries)):
                    if len(entries) > 1:
                        results = job.fold_run(
                            [e.job.fold_payload for e in entries])
                        if len(results) != len(entries):
                            raise RuntimeError(
                                f"fold_run returned {len(results)} "
                                f"results for {len(entries)} folded "
                                f"jobs")
                    else:
                        results = [job.run()]
        except runtime.SolvePreempted as preempted:
            # the yielded segments really ran on the device: count them
            # busy (occupancy must not read idle under preemption
            # thrash), but not as a latency sample
            from cruise_control_tpu.parallel.health import \
                MeshRecoveryRequeue
            mesh_requeue = isinstance(preempted, MeshRecoveryRequeue)
            self.stats.record_preempted(len(entries),
                                        busy_s=self._time() - t0)
            if mesh_requeue:
                # not a preemption: the mesh supervisor shrank the span
                # under this solve (watchdog fire / collective failure)
                # and released the dispatch thread — the SAME requeue
                # machinery redispatches the job on the survivor span
                self._mark("sched-mesh-requeues", len(entries))
                LOG.warning("mesh recovery released %s job %r; "
                            "re-queued onto the shrunk span",
                            best.name, job.label)
            else:
                self._mark("sched-preemptions", len(entries))
                LOG.info("preempted %s job %r at a segment boundary "
                         "(%d queued above it); re-queued",
                         best.name, job.label, self.queue.depth())
            for e in entries:
                tc = getattr(e.job, "trace", None)
                if tc is not None:
                    tc.trace.mark("degraded" if mesh_requeue
                                  else "preempted")
                obs_trace.record_span("sched.preempted", t0,
                                      self._time(), ctx=tc,
                                      klass=e.best_klass.name,
                                      meshRequeue=mesh_requeue)
            for e in entries:
                self.queue.requeue(e)
            served = 0
            return
        except BaseException as exc:  # noqa: BLE001 - resolve the waiters
            duration = self._time() - t0
            self.stats.record_done(duration, failed=True)
            # NOT a latency sample (same rule as preemption): a solve
            # failing fast — e.g. invalid model input raised in 0.1s —
            # would collapse the EWMA and have Retry-After tell rejected
            # clients to hammer the server every ~1s for the duration of
            # an incident, instead of backing off on the scale of a real
            # solve
            LOG.warning("scheduled %s job %r failed: %s: %s", best.name,
                        job.label, type(exc).__name__, exc)
            for e in entries:
                self.queue.finish(e)
                e.ticket.fail(exc)
            return
        finally:
            self.queue.done_serving(served)
        duration = self._time() - t0
        self.stats.record_done(duration, failed=False)
        self.queue.observe_latency(duration)
        self._mark("sched-dispatches")
        if self._metrics is not None:
            self._metrics.update_timer("sched-solve-timer", duration)
            self._metrics.update_histogram("sched-solve-hist", duration)
            busy = best.name.lower().replace("_", "-")
            self._metrics.update_histogram(
                f"sched-device-busy-hist-{busy}", duration)
        if len(entries) > 1:
            self.stats.record_folded(len(entries) - 1)
            self._mark("sched-folded-sweeps", len(entries) - 1)
        for e, result in zip(entries, results):
            self.queue.finish(e)
            if isinstance(result, FoldedFailure):
                e.ticket.fail(result.exc)
            else:
                e.ticket.resolve(result)

    # ------------------------------------------------------------------
    def quiesce(self, timeout_s: float, poll_s: float = 0.05) -> bool:
        """Bounded wait for the scheduler to go idle: no queued jobs
        and nothing in flight (dispatch thread or inline).  The
        graceful-drain path calls this AFTER admission has stopped
        (REST 503-draining), so idleness is terminal.  Wall-clock
        bounded with real time — a wedged in-flight solve must not
        hold shutdown hostage (the same rule as the precompute
        watchdog); returns False when the timeout elapsed first."""
        import time as _real_time
        deadline = _real_time.monotonic() + max(0.0, timeout_s)
        while True:
            with self._inflight_lock:
                inline_busy = self._inflight
            # queue.idle() counts taken-but-unfinished entries under
            # the queue's own lock, so a job the dispatch loop has
            # popped but not yet started can never slip past the drain
            if self.queue.idle() and inline_busy == 0:
                return True
            if _real_time.monotonic() >= deadline:
                return False
            _real_time.sleep(poll_s)

    # ------------------------------------------------------------------
    def stop(self, join_timeout_s: float = 5.0) -> None:
        """Stop dispatching; pending tickets fail with
        SchedulerStoppedError.  A wedged in-flight solve cannot be
        aborted from Python — the daemon dispatch thread dies with the
        process, mirroring the precompute watchdog's shutdown rule."""
        self._stop.set()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=join_timeout_s)
            if thread.is_alive():
                LOG.warning("solve-scheduler still running after %.0fs "
                            "join timeout; shutting down around it",
                            join_timeout_s)
        # the loop drains on exit; drain here too for the never-started
        # or wedged-thread cases
        for entry in self.queue.drain():
            self.queue.finish(entry)
            entry.ticket.fail(SchedulerStoppedError(
                "scheduler stopped while the request was queued"))

    # ------------------------------------------------------------------
    def to_json(self) -> dict:
        depths = self.queue.depths()
        live_token = self._current_mesh_token()
        return {
            "enabled": self.enabled,
            "mesh": (live_token.to_json()
                     if live_token is not None
                     else {"devices": 1, "axis": None, "platform": None}),
            **({"meshSupervisor": self.mesh_supervisor.to_json()}
               if self.mesh_supervisor is not None else {}),
            "policy": self.policy.to_json(),
            "queueDepthByClass": {c.name: d for c, d in depths.items()},
            "queueDepth": sum(depths.values()),
            "oldestWaitS": round(self.queue.oldest_wait_s(), 3),
            "latencyEwmaS": round(self.queue.latency_ewma_s(), 3),
            "occupancy": round(self.stats.occupancy(), 4),
            **self.stats.to_json(),
        }
