"""Scheduling policy: priority classes, per-class admission caps,
deadline budgets, and weighted anti-starvation aging.

Four request classes arbitrate the one device, in strict base-priority
order with aging on top (the serving-scheduler shape of arXiv:2603.10545
cluster schedulers and online-reconfiguration engines, applied to the
solve traffic this service actually carries):

* ``ANOMALY_HEAL`` — self-healing remediation solves (a broker just
  died); the cluster is degraded until this runs.
* ``USER_INTERACTIVE`` — REST/CLI operations a human (or their
  automation) is blocked on.
* ``PRECOMPUTE`` — the background proposal-cache warmer; pure
  opportunistic work, preemptible at segment boundaries.
* ``SCENARIO_SWEEP`` — batched what-if analysis; throughput-oriented,
  preemptible, and foldable (compatible queued sweeps merge into one
  vmapped batch).

Effective priority = base class value minus aging credit: a request of
class *c* that has waited ``w`` seconds scores
``c - weight_c * (w / deadline_budget_c)`` (lower dispatches first), so
a class earns one full priority class of credit per deadline budget
elapsed, scaled by its weight — sustained high-priority traffic can
delay background classes but never starve them.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Dict, Optional, Sequence


class SchedulerClass(enum.IntEnum):
    """Base dispatch priority (lower value = more urgent)."""

    ANOMALY_HEAL = 0
    USER_INTERACTIVE = 1
    PRECOMPUTE = 2
    SCENARIO_SWEEP = 3


#: classes the dispatch loop may preempt at segment boundaries; the
#: interactive classes always run to completion once dispatched
PREEMPTIBLE_CLASSES = frozenset({SchedulerClass.PRECOMPUTE,
                                 SchedulerClass.SCENARIO_SWEEP})

#: defaults, in SchedulerClass order (heal, user, precompute, sweep).
#: The USER_INTERACTIVE cap deliberately sits BELOW the USER_TASKS pool
#: width (api/user_tasks.py max_workers=8): each pool worker holds at
#: most one queued solve, so a cap >= the pool width could never fill
#: from REST traffic and the documented 429 backpressure would be
#: replaced by invisible pool queueing
DEFAULT_WEIGHTS = (8.0, 4.0, 2.0, 1.0)
DEFAULT_QUEUE_CAPS = (8, 6, 2, 8)
DEFAULT_DEADLINE_BUDGETS_S = (5.0, 30.0, 120.0, 300.0)


@dataclasses.dataclass(frozen=True)
class ClassPolicy:
    """One class's knobs."""

    weight: float            #: aging-credit multiplier (anti-starvation)
    queue_cap: int           #: queued requests admitted before 429
    deadline_budget_s: float  #: wait that earns one class of credit


@dataclasses.dataclass(frozen=True)
class SchedulerPolicy:
    """The whole policy: per-class knobs + preemption switch."""

    classes: Dict[SchedulerClass, ClassPolicy]
    preemption_enabled: bool = True

    @staticmethod
    def default(preemption_enabled: bool = True) -> "SchedulerPolicy":
        return SchedulerPolicy.from_lists(preemption_enabled=
                                          preemption_enabled)

    @staticmethod
    def from_lists(weights: Optional[Sequence[float]] = None,
                   queue_caps: Optional[Sequence[int]] = None,
                   deadline_budgets_s: Optional[Sequence[float]] = None,
                   preemption_enabled: bool = True) -> "SchedulerPolicy":
        """Build from the config-file form: one value per class in
        SchedulerClass order (scheduler.class.weights /
        scheduler.class.queue.caps / scheduler.class.deadline.budget.ms).
        """
        weights = list(weights or DEFAULT_WEIGHTS)
        caps = list(queue_caps or DEFAULT_QUEUE_CAPS)
        budgets = list(deadline_budgets_s or DEFAULT_DEADLINE_BUDGETS_S)
        n = len(SchedulerClass)
        for name, lst in (("weights", weights), ("queue caps", caps),
                          ("deadline budgets", budgets)):
            if len(lst) != n:
                raise ValueError(
                    f"scheduler {name} need exactly {n} values "
                    f"(one per class {[c.name for c in SchedulerClass]}), "
                    f"got {len(lst)}")
        classes = {}
        for c in SchedulerClass:
            w = float(weights[c.value])
            cap = int(caps[c.value])
            budget = float(budgets[c.value])
            if w <= 0 or cap < 1 or budget <= 0:
                raise ValueError(
                    f"scheduler policy for {c.name}: weight and deadline "
                    f"budget must be > 0 and the queue cap >= 1")
            classes[c] = ClassPolicy(weight=w, queue_cap=cap,
                                     deadline_budget_s=budget)
        return SchedulerPolicy(classes=classes,
                               preemption_enabled=preemption_enabled)

    # ------------------------------------------------------------------
    def effective_priority(self, klass: SchedulerClass,
                           waited_s: float) -> float:
        """Dispatch score (lower runs first): base class value minus the
        aging credit earned while waiting."""
        cp = self.classes[klass]
        return klass.value - cp.weight * (max(0.0, waited_s)
                                          / cp.deadline_budget_s)

    def queue_cap(self, klass: SchedulerClass) -> int:
        return self.classes[klass].queue_cap

    def is_preemptible(self, klass: SchedulerClass) -> bool:
        return klass in PREEMPTIBLE_CLASSES

    def to_json(self) -> dict:
        return {
            "preemptionEnabled": self.preemption_enabled,
            "classes": {c.name: {
                "weight": cp.weight,
                "queueCap": cp.queue_cap,
                "deadlineBudgetS": cp.deadline_budget_s,
                "preemptible": self.is_preemptible(c),
            } for c, cp in self.classes.items()},
        }
