"""Device-time solve scheduler: the single gateway for every solve.

Pieces: policy.py (priority classes, caps, deadline budgets, aging),
queue.py (bounded admission + single-flight coalescing + backpressure),
scheduler.py (the dispatch loop: priority order, scenario folding,
segment-boundary preemption), stats.py (SchedulerState + sched-*
sensors), runtime.py (the thread-local hooks the solver pipeline and the
USER_TASKS layer share with the scheduler).
"""
from cruise_control_tpu.sched.policy import (PREEMPTIBLE_CLASSES,
                                             ClassPolicy, SchedulerClass,
                                             SchedulerPolicy)
from cruise_control_tpu.sched.queue import (AdmissionQueue, QueueFullError,
                                            SolveTicket)
from cruise_control_tpu.sched.runtime import SolvePreempted
from cruise_control_tpu.sched.scheduler import (DeviceTimeScheduler,
                                                FoldedFailure,
                                                SchedulerStoppedError,
                                                SolveJob)

__all__ = [
    "AdmissionQueue", "ClassPolicy", "DeviceTimeScheduler",
    "FoldedFailure", "PREEMPTIBLE_CLASSES", "QueueFullError",
    "SchedulerClass", "SchedulerPolicy", "SchedulerStoppedError",
    "SolveJob", "SolvePreempted", "SolveTicket",
]
