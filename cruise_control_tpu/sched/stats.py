"""Scheduler telemetry: SchedulerState for the STATE endpoint plus the
sched-* sensors.

Everything the operator needs to answer "why is my request waiting":
per-class queue depth / wait, device-busy seconds and occupancy, and
meters for coalesced / folded / preempted / rejected requests.  The
numbers live here (one lock, plain counters); scheduler.py records into
them and `attach_metrics` exports gauges/meters through the facade's
MetricRegistry exactly like the solver and scenario sensors.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from cruise_control_tpu.sched.policy import SchedulerClass

#: EWMA smoothing for per-class queue-wait seconds
_WAIT_ALPHA = 0.3


class SchedulerStats:
    """Counters + per-class wait EWMAs; thread-safe."""

    def __init__(self, time_fn: Callable[[], float]) -> None:
        self._time = time_fn
        self._lock = threading.Lock()
        self._started_at = time_fn()
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.coalesced = 0
        self.folded = 0
        self.preemptions = 0
        self.rejections = 0
        self.busy_s = 0.0
        self._wait_ewma_s: Dict[SchedulerClass, float] = {}
        self._dispatched: Dict[SchedulerClass, int] = {
            c: 0 for c in SchedulerClass}

    # ------------------------------------------------------------------
    def record_submitted(self) -> None:
        with self._lock:
            self.submitted += 1

    def record_coalesced(self) -> None:
        with self._lock:
            self.coalesced += 1

    def record_rejected(self) -> None:
        with self._lock:
            self.rejections += 1

    def record_preempted(self, n: int = 1,
                         busy_s: float = 0.0) -> None:
        """`busy_s` is the device time the job consumed BEFORE yielding:
        preempted segments really ran on the device, so they count
        toward busy/occupancy (else preemption thrash reads as an idle
        device) — but not toward the solve-latency EWMA (a partial
        solve is not a latency sample)."""
        with self._lock:
            self.preemptions += n
            self.busy_s += max(0.0, busy_s)

    def record_folded(self, n: int) -> None:
        with self._lock:
            self.folded += n

    def record_dispatch(self, klass: SchedulerClass,
                        wait_s: float) -> None:
        with self._lock:
            self._dispatched[klass] += 1
            prev = self._wait_ewma_s.get(klass)
            self._wait_ewma_s[klass] = (wait_s if prev is None
                                        else _WAIT_ALPHA * wait_s
                                        + (1 - _WAIT_ALPHA) * prev)

    def record_done(self, duration_s: float, failed: bool) -> None:
        with self._lock:
            self.busy_s += max(0.0, duration_s)
            if failed:
                self.failed += 1
            else:
                self.completed += 1

    # ------------------------------------------------------------------
    def occupancy(self) -> float:
        """Fraction of wall-clock the device spent solving since the
        scheduler started (device-busy-seconds / elapsed)."""
        with self._lock:
            elapsed = self._time() - self._started_at
            return self.busy_s / elapsed if elapsed > 0 else 0.0

    def busy_seconds(self) -> float:
        with self._lock:
            return self.busy_s

    def wait_ewma_s(self, klass: SchedulerClass) -> float:
        with self._lock:
            return self._wait_ewma_s.get(klass, 0.0)

    def to_json(self) -> dict:
        with self._lock:
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "failed": self.failed,
                "coalesced": self.coalesced,
                "folded": self.folded,
                "preemptions": self.preemptions,
                "rejections": self.rejections,
                "deviceBusySeconds": round(self.busy_s, 3),
                "dispatchedByClass": {c.name: n for c, n
                                      in self._dispatched.items()},
                "waitEwmaSByClass": {
                    c.name: round(self._wait_ewma_s.get(c, 0.0), 3)
                    for c in SchedulerClass},
            }


def attach_metrics(registry, scheduler) -> Optional[object]:
    """Register the sched-* gauges on the facade's MetricRegistry (the
    event meters are marked by the scheduler as events happen)."""
    if registry is None:
        return None
    stats = scheduler.stats
    queue = scheduler.queue
    for c in SchedulerClass:
        name = c.name.lower().replace("_", "-")
        registry.gauge(f"sched-queue-depth-{name}",
                       lambda c=c: queue.depth(c))
        registry.gauge(f"sched-wait-ewma-s-{name}",
                       lambda c=c: stats.wait_ewma_s(c))
    registry.gauge("sched-queue-depth", lambda: queue.depth())
    registry.gauge("sched-device-busy-seconds",
                   lambda: stats.busy_seconds())
    registry.gauge("sched-occupancy", lambda: stats.occupancy())
    registry.gauge("sched-latency-ewma-s",
                   lambda: queue.latency_ewma_s())
    registry.gauge("sched-oldest-wait-s", lambda: queue.oldest_wait_s())
    return registry
