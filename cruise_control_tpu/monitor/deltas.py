"""Structured workload-model deltas.

Production traffic is not cold full-model re-solves — it is thousands
of small changes (one broker added, one topic hot, one rack drained).
The reference's Load Monitor maintains ONE continuously-updated
in-memory workload model (CC/monitor/LoadMonitor.java); the tensor
equivalent here is a `ModelDelta` stream: each delta describes one
small, structured change to the monitor's model, the LoadMonitor logs
it against the model-generation chain (load_monitor.apply_model_delta),
and the device-resident model store (model/store.py) replays it as a
jitted in-place tensor update instead of paying the full host-side
model re-materialization.

The mutation vocabulary deliberately REUSES the PR-3 `ScenarioSpec`
shapes (scenario/spec.py): broker add (`BrokerAdd` — an id already in
the topology marks the existing broker as freshly-joined/new), broker
remove (modeled dead so the solve drains it), broker demote, absolute
per-broker capacity overrides, plus the one kind scenarios do not need:
per-partition expected-load updates (the "topic went hot" delta).  A
delta a scenario could express hypothetically is exactly a delta the
monitor can ingest for real.

Generation chaining: every applied delta advances the model generation
by one `delta_generation` step and records (from_generation,
to_generation) — the store may only fast-forward through a CONTIGUOUS
chain.  Any unlogged change (metadata refresh found a new broker, fresh
samples moved the load generation) breaks the chain and the store falls
back to a full rebuild; a delta can make the resident model wrong only
if its host-overlay application and its device application disagree,
which the byte-equality pin (tests/test_incremental.py) forbids.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

from cruise_control_tpu.common.resources import NUM_RESOURCES
from cruise_control_tpu.scenario.spec import (RESOURCE_NAMES, BrokerAdd,
                                              ScenarioSpecError,
                                              _check_resource_map)


class ModelDeltaError(ValueError):
    """Malformed or inapplicable model delta."""


@dataclasses.dataclass(frozen=True)
class PartitionLoadUpdate:
    """New EXPECTED leader utilization for one partition (the value the
    monitor's window collapse would produce — avg CPU/NW, latest DISK).
    Follower loads and the leadership bonus re-derive from it exactly
    like a full rebuild derives them (builder leader-load split)."""

    topic: str
    partition: int
    #: leader expected utilization in Resource order (cpu, nw_in,
    #: nw_out, disk)
    load: Tuple[float, ...]

    def __post_init__(self) -> None:
        if len(self.load) != NUM_RESOURCES:
            raise ModelDeltaError(
                f"partition load needs {NUM_RESOURCES} entries "
                f"({', '.join(RESOURCE_NAMES)}), got {len(self.load)}")
        for v in self.load:
            if not (float(v) >= 0.0):
                raise ModelDeltaError(
                    f"partition load must be finite and >= 0, got {v!r}")


@dataclasses.dataclass(frozen=True)
class ModelDelta:
    """One structured change to the monitor's workload model (pure
    data; the ScenarioSpec mutation vocabulary plus load updates)."""

    #: mark existing brokers as freshly joined (`broker_new`, the
    #: ADD_BROKER immigration-target semantics).  Hypothetical rows are
    #: NOT materialized by a delta — a broker unknown to the metadata
    #: is a shape change and forces a full rebuild.
    add_brokers: Tuple[BrokerAdd, ...] = ()
    #: model these brokers dead (replicas drain via self-healing)
    remove_brokers: Tuple[int, ...] = ()
    demote_brokers: Tuple[int, ...] = ()
    #: broker id -> {resource name: absolute capacity}
    capacity_overrides: Dict[int, Dict[str, float]] = dataclasses.field(
        default_factory=dict)
    load_updates: Tuple[PartitionLoadUpdate, ...] = ()
    reason: str = ""

    def is_noop(self) -> bool:
        return not (self.add_brokers or self.remove_brokers
                    or self.demote_brokers or self.capacity_overrides
                    or self.load_updates)

    def validate(self) -> None:
        if self.is_noop():
            raise ModelDeltaError("empty model delta")
        for a in self.add_brokers:
            if a.rack is not None or a.capacity is not None:
                raise ModelDeltaError(
                    f"add_brokers[{a.broker_id}] carries rack/capacity: "
                    f"a delta only marks an EXISTING broker as freshly "
                    f"joined — materializing a hypothetical row is a "
                    f"shape change (rebuild), and capacity belongs in "
                    f"capacity_overrides")
        try:
            for b, caps in self.capacity_overrides.items():
                _check_resource_map(f"capacityOverrides[{int(b)}]", caps,
                                    allow_zero=False)
        except ScenarioSpecError as exc:
            raise ModelDeltaError(str(exc))
        added = {a.broker_id for a in self.add_brokers}
        overlap = added & set(self.remove_brokers)
        if overlap:
            raise ModelDeltaError(
                f"brokers {sorted(overlap)} both added and removed in "
                f"one delta")

    def broker_ids_touched(self) -> Tuple[int, ...]:
        """Broker ids DIRECTLY named by this delta (load updates dirty
        the hosting brokers too — resolved against the resident model
        by the store, which knows the placement)."""
        ids = ({a.broker_id for a in self.add_brokers}
               | set(self.remove_brokers) | set(self.demote_brokers)
               | set(self.capacity_overrides))
        return tuple(sorted(ids))

    def describe(self) -> str:
        parts = []
        if self.add_brokers:
            added = sorted(a.broker_id for a in self.add_brokers)
            parts.append(f"add={added}")
        if self.remove_brokers:
            parts.append(f"remove={sorted(self.remove_brokers)}")
        if self.demote_brokers:
            parts.append(f"demote={sorted(self.demote_brokers)}")
        if self.capacity_overrides:
            parts.append(f"capacity={sorted(self.capacity_overrides)}")
        if self.load_updates:
            parts.append(f"loads={len(self.load_updates)}p")
        return " ".join(parts) or "noop"


@dataclasses.dataclass(frozen=True)
class DeltaRecord:
    """One applied delta on the model-generation chain: the monitor's
    generation moved `from_generation` -> `to_generation` by applying
    exactly `delta`.  `seq` is a monotonically increasing ordinal (log
    trimming bookkeeping)."""

    seq: int
    from_generation: object          #: monitor.ModelGeneration
    to_generation: object
    delta: ModelDelta


def capacity_rows(capacity_overrides: Dict[int, Dict[str, float]],
                  broker_index: Dict[int, int]):
    """(rows i32[N], mask bool[N, RES], values f32[N, RES]) — the
    numeric form of per-broker capacity overrides, shared by the
    monitor's rebuild overlay and the device store's delta application
    so the two can never round differently.  Brokers absent from
    `broker_index` are skipped (they left the metadata)."""
    import numpy as np
    rows, mask, values = [], [], []
    for b in sorted(capacity_overrides):
        if b not in broker_index:
            continue
        caps = capacity_overrides[b]
        m = np.zeros(NUM_RESOURCES, dtype=bool)
        v = np.zeros(NUM_RESOURCES, dtype=np.float32)
        for name, value in caps.items():
            r = RESOURCE_NAMES.index(name)
            m[r] = True
            v[r] = np.float32(value)
        rows.append(broker_index[b])
        mask.append(m)
        values.append(v)
    if not rows:
        return (np.zeros(0, np.int32), np.zeros((0, NUM_RESOURCES), bool),
                np.zeros((0, NUM_RESOURCES), np.float32))
    return (np.asarray(rows, np.int32), np.stack(mask), np.stack(values))


def leader_load_split(load, follower_cpu):
    """(leader_base f32[RES], follower_base f32[RES], bonus f32[RES]) —
    the builder's leader-load split (model/builder.py build(): follower
    base + leadership bonus) applied to one partition's new expected
    leader utilization, in the SAME float64-then-f32 arithmetic.

    The leader's base CPU is the CLAMPED estimate (the builder wraps
    the estimator in np.clip) while follower rows carry the monitor
    loop's RAW estimate (LoadMonitor.cluster_model follower
    attribution) — normally equal, but a custom estimator can make them
    differ, so the two are kept separate exactly like a rebuild keeps
    them."""
    import numpy as np
    from cruise_control_tpu.common.resources import Resource
    vec = np.asarray(load, dtype=np.float64)
    raw_f = float(follower_cpu(vec[Resource.CPU], vec[Resource.NW_IN],
                               vec[Resource.NW_OUT]))
    clipped_f = float(np.clip(raw_f, 0.0, vec[Resource.CPU]))
    leader_base = vec.copy()
    leader_base[Resource.CPU] = clipped_f
    leader_base[Resource.NW_OUT] = 0.0
    follower_base = vec.copy()
    follower_base[Resource.CPU] = raw_f
    follower_base[Resource.NW_OUT] = 0.0
    bonus = np.zeros(NUM_RESOURCES, dtype=np.float64)
    bonus[Resource.CPU] = vec[Resource.CPU] - clipped_f
    bonus[Resource.NW_OUT] = vec[Resource.NW_OUT]
    return (leader_base.astype(np.float32),
            follower_base.astype(np.float32),
            bonus.astype(np.float32))


def chain_between(records, from_generation, to_generation
                  ) -> Optional[list]:
    """The CONTIGUOUS DeltaRecord chain taking `from_generation` to
    `to_generation`, or None when no such chain exists (an unlogged
    change interleaved, the log was trimmed past `from_generation`, or
    the generations are unrelated).  `from == to` is the empty chain."""
    if from_generation == to_generation:
        return []
    chain: list = []
    cur = from_generation
    for rec in records:
        if rec.from_generation == cur:
            chain.append(rec)
            cur = rec.to_generation
            if cur == to_generation:
                return chain
        elif chain:
            # continuity broken mid-walk: something moved the
            # generation without a record
            return None
    return None
