"""Load-monitor task runner: the sampling state machine.

Reference: CC/monitor/task/LoadMonitorTaskRunner.java:1-338 — drives the
periodic sampling task and one-shot bootstrap/load tasks through states
{NOT_STARTED, RUNNING, SAMPLING, PAUSED, BOOTSTRAPPING, TRAINING, LOADING};
sampling can be paused/resumed (the executor pauses it during moves,
reference Executor.java:796).
"""
from __future__ import annotations

import enum
import logging
import threading
import time
from typing import Callable, Optional

from cruise_control_tpu.cluster.metadata import MetadataClient
from cruise_control_tpu.monitor.sampling.fetcher import MetricFetcherManager
from cruise_control_tpu.monitor.sampling.sampler import SamplingMode

LOG = logging.getLogger(__name__)


class LoadMonitorTaskRunnerState(enum.Enum):
    """reference LoadMonitorTaskRunner.LoadMonitorTaskRunnerState"""

    NOT_STARTED = "NOT_STARTED"
    RUNNING = "RUNNING"
    SAMPLING = "SAMPLING"
    PAUSED = "PAUSED"
    BOOTSTRAPPING = "BOOTSTRAPPING"
    TRAINING = "TRAINING"
    LOADING = "LOADING"


class LoadMonitorTaskRunner:
    """Background sampling loop with pause/resume and bootstrap."""

    def __init__(self, metadata: MetadataClient,
                 fetcher: MetricFetcherManager,
                 sampling_interval_ms: float,
                 time_fn: Callable[[], float] = time.time):
        self._metadata = metadata
        self._fetcher = fetcher
        self._interval_s = sampling_interval_ms / 1000.0
        self._time_fn = time_fn
        self._state = LoadMonitorTaskRunnerState.NOT_STARTED
        self._lock = threading.RLock()
        self._wake = threading.Event()
        self._shutdown = False
        self._paused_reason: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        self._last_sample_end_ms = 0.0

    # ------------------------------------------------------------------
    @property
    def state(self) -> LoadMonitorTaskRunnerState:
        with self._lock:
            return self._state

    @property
    def reason_of_pause(self) -> Optional[str]:
        with self._lock:
            return self._paused_reason

    def start(self, do_sampling: bool = True) -> None:
        with self._lock:
            if self._state != LoadMonitorTaskRunnerState.NOT_STARTED:
                raise RuntimeError("task runner already started")
            self._state = LoadMonitorTaskRunnerState.RUNNING
        if do_sampling:
            self._thread = threading.Thread(
                target=self._run, name="load-monitor-task-runner",
                daemon=True)
            self._thread.start()

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def pause_sampling(self, reason: str) -> None:
        """reference LoadMonitorTaskRunner.pauseSampling"""
        with self._lock:
            if self._state in (LoadMonitorTaskRunnerState.RUNNING,
                               LoadMonitorTaskRunnerState.SAMPLING):
                self._state = LoadMonitorTaskRunnerState.PAUSED
                self._paused_reason = reason
                LOG.info("metric sampling paused: %s", reason)

    def resume_sampling(self, reason: str) -> None:
        """reference LoadMonitorTaskRunner.resumeSampling"""
        with self._lock:
            if self._state == LoadMonitorTaskRunnerState.PAUSED:
                self._state = LoadMonitorTaskRunnerState.RUNNING
                self._paused_reason = None
                LOG.info("metric sampling resumed: %s", reason)
        self._wake.set()

    # ------------------------------------------------------------------
    def sample_once(self, mode: SamplingMode = SamplingMode.ALL) -> None:
        """One synchronous sampling round (also used by tests and by
        bootstrap)."""
        with self._lock:
            now_ms = self._time_fn() * 1000.0
            start_ms = (self._last_sample_end_ms
                        or now_ms - self._interval_s * 1e3)
        cluster = self._metadata.refresh_metadata()
        self._fetcher.fetch_metrics_for_model(cluster, start_ms, now_ms, mode)
        # window handoff under the lock (the loop thread and bootstrap/
        # test callers share it); only a SUCCESSFUL fetch consumes the
        # window, so the two blocks stay separate
        with self._lock:
            self._last_sample_end_ms = now_ms

    def bootstrap(self, num_rounds: int, advance_fn: Optional[
            Callable[[float], None]] = None) -> None:
        """Synchronously run `num_rounds` sampling rounds to fill windows
        (reference BootstrapTask.java; range-bootstrap via a sampler that
        serves history).  `advance_fn(seconds)` lets simulated time move
        between rounds."""
        with self._lock:
            prev = self._state
            self._state = LoadMonitorTaskRunnerState.BOOTSTRAPPING
        try:
            for _ in range(num_rounds):
                self.sample_once()
                if advance_fn is not None:
                    advance_fn(self._interval_s)
        finally:
            with self._lock:
                self._state = prev

    def set_loading(self, loading: bool) -> None:
        with self._lock:
            if loading:
                self._state_before_loading = self._state
                self._state = LoadMonitorTaskRunnerState.LOADING
            elif self._state == LoadMonitorTaskRunnerState.LOADING:
                self._state = getattr(self, "_state_before_loading",
                                      LoadMonitorTaskRunnerState.RUNNING)

    # ------------------------------------------------------------------
    def _run(self) -> None:
        while True:
            self._wake.wait(timeout=self._interval_s)
            self._wake.clear()
            with self._lock:
                if self._shutdown:
                    return
                if self._state != LoadMonitorTaskRunnerState.RUNNING:
                    continue
                self._state = LoadMonitorTaskRunnerState.SAMPLING
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 - keep the loop alive
                LOG.exception("sampling round failed")
            finally:
                with self._lock:
                    if self._state == LoadMonitorTaskRunnerState.SAMPLING:
                        self._state = LoadMonitorTaskRunnerState.RUNNING
