"""Monitor plane: sampling, windowed aggregation, cluster-model building.

Reference: CC/monitor/ (LoadMonitor, task runner, fetchers, samplers,
aggregators, completeness) — see SURVEY.md §2.4.
"""
from cruise_control_tpu.monitor.completeness import (
    ModelCompletenessRequirements)
from cruise_control_tpu.monitor.load_monitor import (LoadMonitor,
                                                     LoadMonitorState,
                                                     ModelGeneration)
from cruise_control_tpu.monitor.task_runner import (
    LoadMonitorTaskRunner, LoadMonitorTaskRunnerState)

__all__ = [
    "ModelCompletenessRequirements", "LoadMonitor", "LoadMonitorState",
    "ModelGeneration", "LoadMonitorTaskRunner", "LoadMonitorTaskRunnerState",
]
