"""Framework metric definitions (raw metric types → aggregation metrics).

The reference maps 77 `RawMetricType`s emitted by its in-broker reporter to
~25 aggregation metric definitions split into a "common" set (valid for both
partition and broker entities) and a broker-only set
(reference CC/monitor/metricdefinition/KafkaMetricDef.java:42-298 and
cruise-control-metrics-reporter/.../metric/RawMetricType.java:27-183).

The same split is kept here: `RawMetricType` is the wire enum the node agent
emits; `MetricScope` says which entity a raw type describes; the two
`MetricDef` registries below are what the windowed aggregators are built on.
"""
from __future__ import annotations

import enum
from typing import Dict

from cruise_control_tpu.core.metricdef import AggregationFunction, MetricDef


class MetricScope(enum.Enum):
    """Which entity a raw metric describes (reference RawMetricType.Scope)."""

    BROKER = "broker"
    TOPIC = "topic"
    PARTITION = "partition"


class RawMetricType(enum.Enum):
    """Wire-level metric types produced by the node agent (subset of the
    reference's 77 covering every metric its model actually consumes;
    reference RawMetricType.java:27-183)."""

    # broker scope
    ALL_TOPIC_BYTES_IN = ("broker",)
    ALL_TOPIC_BYTES_OUT = ("broker",)
    ALL_TOPIC_REPLICATION_BYTES_IN = ("broker",)
    ALL_TOPIC_REPLICATION_BYTES_OUT = ("broker",)
    ALL_TOPIC_FETCH_REQUEST_RATE = ("broker",)
    ALL_TOPIC_PRODUCE_REQUEST_RATE = ("broker",)
    ALL_TOPIC_MESSAGES_IN_PER_SEC = ("broker",)
    BROKER_CPU_UTIL = ("broker",)
    BROKER_PRODUCE_REQUEST_RATE = ("broker",)
    BROKER_CONSUMER_FETCH_REQUEST_RATE = ("broker",)
    BROKER_FOLLOWER_FETCH_REQUEST_RATE = ("broker",)
    BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT = ("broker",)
    BROKER_REQUEST_QUEUE_SIZE = ("broker",)
    BROKER_RESPONSE_QUEUE_SIZE = ("broker",)
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX = ("broker",)
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN = ("broker",)
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MAX = ("broker",)
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN = ("broker",)
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MAX = ("broker",)
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN = ("broker",)
    BROKER_LOG_FLUSH_RATE = ("broker",)
    BROKER_LOG_FLUSH_TIME_MS_MEAN = ("broker",)
    BROKER_LOG_FLUSH_TIME_MS_999TH = ("broker",)
    # topic scope
    TOPIC_BYTES_IN = ("topic",)
    TOPIC_BYTES_OUT = ("topic",)
    TOPIC_REPLICATION_BYTES_IN = ("topic",)
    TOPIC_REPLICATION_BYTES_OUT = ("topic",)
    TOPIC_PRODUCE_REQUEST_RATE = ("topic",)
    TOPIC_FETCH_REQUEST_RATE = ("topic",)
    TOPIC_MESSAGES_IN_PER_SEC = ("topic",)
    # partition scope
    PARTITION_SIZE = ("partition",)

    def __init__(self, scope: str):
        self.scope = MetricScope(scope)


# ---------------------------------------------------------------------------
# Aggregation metric names (reference KafkaMetricDef.CommonMetricDef /
# BrokerMetricDef enum constants)
# ---------------------------------------------------------------------------

CPU_USAGE = "CPU_USAGE"
DISK_USAGE = "DISK_USAGE"
LEADER_BYTES_IN = "LEADER_BYTES_IN"
LEADER_BYTES_OUT = "LEADER_BYTES_OUT"
REPLICATION_BYTES_IN_RATE = "REPLICATION_BYTES_IN_RATE"
REPLICATION_BYTES_OUT_RATE = "REPLICATION_BYTES_OUT_RATE"
PRODUCE_RATE = "PRODUCE_RATE"
FETCH_RATE = "FETCH_RATE"
MESSAGE_IN_RATE = "MESSAGE_IN_RATE"

BROKER_PRODUCE_REQUEST_RATE = "BROKER_PRODUCE_REQUEST_RATE"
BROKER_CONSUMER_FETCH_REQUEST_RATE = "BROKER_CONSUMER_FETCH_REQUEST_RATE"
BROKER_FOLLOWER_FETCH_REQUEST_RATE = "BROKER_FOLLOWER_FETCH_REQUEST_RATE"
BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT = (
    "BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT")
BROKER_REQUEST_QUEUE_SIZE = "BROKER_REQUEST_QUEUE_SIZE"
BROKER_RESPONSE_QUEUE_SIZE = "BROKER_RESPONSE_QUEUE_SIZE"
BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX = (
    "BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX")
BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN = (
    "BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN")
BROKER_LOG_FLUSH_RATE = "BROKER_LOG_FLUSH_RATE"
BROKER_LOG_FLUSH_TIME_MS_MEAN = "BROKER_LOG_FLUSH_TIME_MS_MEAN"
BROKER_LOG_FLUSH_TIME_MS_999TH = "BROKER_LOG_FLUSH_TIME_MS_999TH"

#: common metrics (partition & broker entities), with the aggregation
#: strategy the reference assigns (CPU/NW/rates = AVG, DISK = LATEST;
#: KafkaMetricDef.java:48-90) and whether the metric participates in the
#: `toFollower` load transfer on leadership change.
_COMMON = [
    (CPU_USAGE, AggregationFunction.AVG, True),
    (LEADER_BYTES_IN, AggregationFunction.AVG, True),
    (LEADER_BYTES_OUT, AggregationFunction.AVG, True),
    (DISK_USAGE, AggregationFunction.LATEST, False),
    (PRODUCE_RATE, AggregationFunction.AVG, False),
    (FETCH_RATE, AggregationFunction.AVG, False),
    (MESSAGE_IN_RATE, AggregationFunction.AVG, False),
    (REPLICATION_BYTES_IN_RATE, AggregationFunction.AVG, False),
    (REPLICATION_BYTES_OUT_RATE, AggregationFunction.AVG, False),
]

_BROKER_ONLY = [
    (BROKER_PRODUCE_REQUEST_RATE, AggregationFunction.AVG),
    (BROKER_CONSUMER_FETCH_REQUEST_RATE, AggregationFunction.AVG),
    (BROKER_FOLLOWER_FETCH_REQUEST_RATE, AggregationFunction.AVG),
    (BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT, AggregationFunction.AVG),
    (BROKER_REQUEST_QUEUE_SIZE, AggregationFunction.AVG),
    (BROKER_RESPONSE_QUEUE_SIZE, AggregationFunction.AVG),
    (BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX, AggregationFunction.MAX),
    (BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN, AggregationFunction.AVG),
    (BROKER_LOG_FLUSH_RATE, AggregationFunction.AVG),
    (BROKER_LOG_FLUSH_TIME_MS_MEAN, AggregationFunction.AVG),
    (BROKER_LOG_FLUSH_TIME_MS_999TH, AggregationFunction.MAX),
]


#: group name marking metrics whose load follows leadership transfers
#: (reference KafkaMetricDef constructor's `toFollower` flag)
TO_FOLLOWER_GROUP = "toFollower"


def _build_common() -> MetricDef:
    md = MetricDef()
    for name, func, to_follower in _COMMON:
        md.define(name, func,
                  group=TO_FOLLOWER_GROUP if to_follower else None)
    return md


def _build_broker() -> MetricDef:
    md = _build_common()
    for name, func in _BROKER_ONLY:
        md.define(name, func)
    return md


_COMMON_METRIC_DEF = _build_common()
_BROKER_METRIC_DEF = _build_broker()


def common_metric_def() -> MetricDef:
    """MetricDef for partition entities (reference
    KafkaMetricDef.commonMetricDef)."""
    return _COMMON_METRIC_DEF


def broker_metric_def() -> MetricDef:
    """MetricDef for broker entities (common + broker-only metrics;
    reference KafkaMetricDef.brokerMetricDef)."""
    return _BROKER_METRIC_DEF


#: raw broker metric type → broker MetricDef name
RAW_TO_BROKER_METRIC: Dict[RawMetricType, str] = {
    RawMetricType.BROKER_CPU_UTIL: CPU_USAGE,
    RawMetricType.ALL_TOPIC_BYTES_IN: LEADER_BYTES_IN,
    RawMetricType.ALL_TOPIC_BYTES_OUT: LEADER_BYTES_OUT,
    RawMetricType.ALL_TOPIC_REPLICATION_BYTES_IN: REPLICATION_BYTES_IN_RATE,
    RawMetricType.ALL_TOPIC_REPLICATION_BYTES_OUT: REPLICATION_BYTES_OUT_RATE,
    RawMetricType.ALL_TOPIC_PRODUCE_REQUEST_RATE: PRODUCE_RATE,
    RawMetricType.ALL_TOPIC_FETCH_REQUEST_RATE: FETCH_RATE,
    RawMetricType.ALL_TOPIC_MESSAGES_IN_PER_SEC: MESSAGE_IN_RATE,
    RawMetricType.BROKER_PRODUCE_REQUEST_RATE: BROKER_PRODUCE_REQUEST_RATE,
    RawMetricType.BROKER_CONSUMER_FETCH_REQUEST_RATE:
        BROKER_CONSUMER_FETCH_REQUEST_RATE,
    RawMetricType.BROKER_FOLLOWER_FETCH_REQUEST_RATE:
        BROKER_FOLLOWER_FETCH_REQUEST_RATE,
    RawMetricType.BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT:
        BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT,
    RawMetricType.BROKER_REQUEST_QUEUE_SIZE: BROKER_REQUEST_QUEUE_SIZE,
    RawMetricType.BROKER_RESPONSE_QUEUE_SIZE: BROKER_RESPONSE_QUEUE_SIZE,
    RawMetricType.BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX:
        BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX,
    RawMetricType.BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN:
        BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN,
    RawMetricType.BROKER_LOG_FLUSH_RATE: BROKER_LOG_FLUSH_RATE,
    RawMetricType.BROKER_LOG_FLUSH_TIME_MS_MEAN: BROKER_LOG_FLUSH_TIME_MS_MEAN,
    RawMetricType.BROKER_LOG_FLUSH_TIME_MS_999TH:
        BROKER_LOG_FLUSH_TIME_MS_999TH,
}
