"""LoadMonitor: metrics in, tensorized ClusterModel out.

Reference: CC/monitor/LoadMonitor.java:78-780 — owns the metadata client,
capacity resolver, both windowed aggregators and the sampling task runner;
`clusterModel(...)` (:518-570) refreshes metadata, aggregates partition
samples, creates racks/brokers with resolved capacities
(populateClusterCapacity :465-502), populates per-replica loads
(MonitorUtils.populatePartitionLoad) and marks dead/bad brokers
(setBadBrokerState).  A bounded semaphore throttles concurrent model
builds (:366-377).

The output here is the solver-ready tensor state (`ClusterState` +
`ClusterTopology`) rather than a mutable object graph — the expensive
Java-side object walk becomes a columnar build feeding device arrays.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from cruise_control_tpu.cluster.admin import ClusterAdminClient
from cruise_control_tpu.cluster.metadata import MetadataClient
from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
from cruise_control_tpu.config.capacity import (
    BrokerCapacityConfigResolver, StaticCapacityResolver)
from cruise_control_tpu.core.aggregator import (NotEnoughValidWindowsError,
                                                ValuesAndExtrapolations)
from cruise_control_tpu.model.cpu_model import LinearRegressionCpuModel
from cruise_control_tpu.model.builder import (ClusterModelBuilder,
                                              ClusterTopology,
                                              estimate_follower_cpu)
from cruise_control_tpu.model.state import ClusterState
from cruise_control_tpu.monitor import metricdef as MD
from cruise_control_tpu.monitor.aggregators import (
    BrokerMetricSampleAggregator, PartitionMetricSampleAggregator)
from cruise_control_tpu.monitor.completeness import (
    ModelCompletenessRequirements)
from cruise_control_tpu.monitor.entities import PartitionEntity
from cruise_control_tpu.monitor.sampling.fetcher import MetricFetcherManager
from cruise_control_tpu.monitor.sampling.sample_store import (SampleLoader,
                                                              SampleStore)
from cruise_control_tpu.monitor.sampling.sampler import MetricSampler, Samples
from cruise_control_tpu.monitor.task_runner import LoadMonitorTaskRunner

LOG = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True, order=True)
class ModelGeneration:
    """(cluster metadata generation, load/aggregator generation, applied
    model-delta count) — staleness key for model/proposal caches
    (reference CC/monitor/ModelGeneration.java).  `delta_generation`
    counts structured model deltas applied to the monitor's overlay
    (apply_model_delta): a delta changes what cluster_model() builds, so
    it must move the generation exactly like a metadata or sample change
    does — otherwise the proposal cache and the device model store would
    serve pre-delta results as current."""

    cluster_generation: int
    load_generation: int
    delta_generation: int = 0

    def is_stale(self, other: "ModelGeneration") -> bool:
        return (self.cluster_generation < other.cluster_generation
                or self.load_generation < other.load_generation
                or self.delta_generation < other.delta_generation)


@dataclasses.dataclass
class LoadMonitorState:
    """REST-visible snapshot (reference CC/monitor/LoadMonitorState.java)."""

    state: str
    num_valid_windows: int
    total_num_windows: int
    monitored_partitions_percentage: float
    num_monitored_partitions: int
    num_total_partitions: int
    reason_of_pause: Optional[str] = None
    last_sampling_ms: float = 0.0


class _LoaderShim(SampleLoader):
    def __init__(self, monitor: "LoadMonitor"):
        self._monitor = monitor

    def load_samples(self, samples: Samples) -> None:
        self._monitor._partition_aggregator.add_partition_samples(
            samples.partition_samples)
        self._monitor._broker_aggregator.add_broker_samples(
            samples.broker_samples)


class LoadMonitor:
    """The monitor-plane facade."""

    def __init__(self, admin: ClusterAdminClient,
                 sampler: MetricSampler,
                 capacity_resolver: Optional[
                     BrokerCapacityConfigResolver] = None,
                 sample_store: Optional[SampleStore] = None,
                 num_windows: int = 5,
                 window_ms: float = 3_600_000,
                 min_samples_per_window: int = 3,
                 broker_num_windows: int = 20,
                 broker_window_ms: Optional[float] = None,
                 broker_min_samples_per_window: int = 1,
                 sampling_interval_ms: float = 120_000,
                 num_fetchers: int = 1,
                 metadata_ttl_ms: float = 5_000,
                 max_concurrent_model_builds: int = 2,
                 max_allowed_extrapolations_per_partition: int = 5,
                 max_allowed_extrapolations_per_broker: int = 5,
                 allow_cpu_capacity_estimation: bool = True,
                 state_update_interval_ms: float = 0.0,
                 completeness_cache_size: int = 5,
                 broker_completeness_cache_size: int = 5,
                 min_valid_partition_ratio: float = 0.0,
                 partition_assignor=None,
                 use_linear_regression_model: bool = True,
                 linear_regression_kwargs: Optional[dict] = None,
                 cpu_util_weights: Optional[tuple] = None,
                 delta_log_size: int = 256,
                 time_fn: Callable[[], float] = time.time):
        self._admin = admin
        self._metadata = MetadataClient(admin, metadata_ttl_ms, time_fn)
        self._capacity_resolver = (capacity_resolver
                                   or StaticCapacityResolver())
        self._sample_store = sample_store
        self._time_fn = time_fn
        self._partition_aggregator = PartitionMetricSampleAggregator(
            num_windows, int(window_ms), min_samples_per_window,
            completeness_cache_size=completeness_cache_size)
        self._broker_aggregator = BrokerMetricSampleAggregator(
            broker_num_windows, int(broker_window_ms or window_ms),
            broker_min_samples_per_window,
            completeness_cache_size=broker_completeness_cache_size)
        #: default monitored-partition completeness when a request names
        #: none (reference min.valid.partition.ratio)
        self._min_valid_partition_ratio = min_valid_partition_ratio
        #: aggregation extrapolation caps (reference
        #: max.allowed.extrapolations.per.{partition,broker})
        self._max_extrapolations_partition = \
            max_allowed_extrapolations_per_partition
        self._max_extrapolations_broker = \
            max_allowed_extrapolations_per_broker
        #: whether CPU capacity may be estimated during sampling-side
        #: capacity resolution (reference
        #: sampling.allow.cpu.capacity.estimation)
        self._allow_cpu_capacity_estimation = allow_cpu_capacity_estimation
        #: get_state() result cache TTL (reference
        #: monitor.state.update.interval.ms sensor-update period)
        self._state_ttl_s = state_update_interval_ms / 1e3
        self._state_cache = None
        self._state_cache_at = -1e18
        self._fetcher = MetricFetcherManager(
            sampler, self._partition_aggregator, self._broker_aggregator,
            sample_store, num_fetchers,
            partition_assignor=partition_assignor)
        self.task_runner = LoadMonitorTaskRunner(
            self._metadata, self._fetcher, sampling_interval_ms, time_fn)
        # reference: cluster-model-creation semaphore
        # (LoadMonitor.java:92,366-377)
        self._model_semaphore = threading.BoundedSemaphore(
            max_concurrent_model_builds)
        cdef = MD.common_metric_def()
        self._cpu_id = cdef.metric_id(MD.CPU_USAGE)
        self._nw_in_id = cdef.metric_id(MD.LEADER_BYTES_IN)
        self._nw_out_id = cdef.metric_id(MD.LEADER_BYTES_OUT)
        self._disk_id = cdef.metric_id(MD.DISK_USAGE)
        #: trainable CPU attribution model (reference TRAIN endpoint +
        #: LinearRegressionModelParameters)
        self.cpu_model = LinearRegressionCpuModel(
            **(linear_regression_kwargs or {}))
        #: reference use.linear.regression.model (config default False,
        #: per the reference): when False the trained model is kept (TRAIN
        #: still works) but model building sticks to the static
        #: coefficients.  The CONSTRUCTOR default stays True so direct
        #: embedders keep the train-then-use behavior
        self._use_linear_regression = use_linear_regression_model
        #: static CPU attribution weights (reference
        #: {leader,follower}.network.{in,out}bound.weight.for.cpu.util,
        #: ModelParameters.java:22-30); None = module defaults
        self._cpu_util_weights = cpu_util_weights

        # -- incremental workload model (monitor/deltas.py) --
        # The monitor's host-side model OVERLAY: structured deltas
        # (apply_model_delta) land here so a full rebuild reflects them
        # exactly like the device store's in-place tensor application —
        # the two paths must stay byte-identical (the incremental pin).
        self._delta_lock = threading.Lock()
        self._delta_generation = 0
        self._delta_seq = 0
        self._delta_log: list = []          #: deltas.DeltaRecord, oldest
        self._delta_log_size = max(1, delta_log_size)   # first
        self._overlay_new: set = set()      #: broker ids marked new
        self._overlay_removed: set = set()  #: broker ids modeled dead
        self._overlay_demoted: set = set()
        #: broker id -> {resource name: absolute capacity}
        self._overlay_capacity: Dict[int, Dict[str, float]] = {}
        #: (topic, partition) -> (expected leader load f64[RES],
        #: load-generation stamp) — superseded (and dropped) as soon as
        #: fresh samples move the aggregator generation past the stamp
        self._overlay_loads: Dict[Tuple[str, int], tuple] = {}

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start_up(self, do_sampling: bool = True,
                 skip_loading_samples: bool = False) -> None:
        """reference LoadMonitor.startUp: reload stored samples, then start
        the sampling loop."""
        if self._sample_store is not None and not skip_loading_samples:
            self.task_runner.set_loading(True)
            try:
                self._sample_store.load_samples(_LoaderShim(self))
            finally:
                self.task_runner.set_loading(False)
        self.task_runner.start(do_sampling)

    def shutdown(self) -> None:
        self.task_runner.shutdown()
        self._fetcher.shutdown()
        if self._sample_store is not None:
            self._sample_store.close()

    # ------------------------------------------------------------------
    # accessors
    # ------------------------------------------------------------------
    @property
    def num_quarantined_samples(self) -> int:
        """Samples dropped by the ingest quarantine (NaN/Inf/negative
        values) — exported by the facade as the
        `sampler-quarantined-samples` sensor."""
        return self._fetcher.num_quarantined_samples

    @property
    def metadata(self) -> MetadataClient:
        return self._metadata

    @property
    def partition_aggregator(self) -> PartitionMetricSampleAggregator:
        return self._partition_aggregator

    @property
    def broker_aggregator(self) -> BrokerMetricSampleAggregator:
        return self._broker_aggregator

    def model_generation(self) -> ModelGeneration:
        return ModelGeneration(self._metadata.cluster_generation,
                               self._partition_aggregator.generation,
                               self._delta_generation)

    # ------------------------------------------------------------------
    # incremental workload model: structured deltas (monitor/deltas.py)
    # ------------------------------------------------------------------
    def apply_model_delta(self, delta) -> ModelGeneration:
        """Ingest one structured model delta: validate it against the
        current metadata, apply it to the monitor's host-side overlay
        (so every later cluster_model() rebuild reflects it) and log it
        on the model-generation chain for the device model store's
        fast-forward path.  Returns the new model generation.

        Metadata is force-refreshed FIRST so a pending unlogged change
        (a broker the admin already sees) moves the generation before
        `from_generation` is captured — the chain then breaks at the
        unlogged change, never across it, and the store can never
        fast-forward past something it has no delta for."""
        from cruise_control_tpu.monitor.deltas import (DeltaRecord,
                                                       ModelDelta,
                                                       ModelDeltaError)
        if not isinstance(delta, ModelDelta):
            raise ModelDeltaError(f"expected a ModelDelta, got "
                                  f"{type(delta).__name__}")
        delta.validate()
        snapshot = self._metadata.refresh_metadata()
        known = set(snapshot.all_broker_ids)
        topics = {p.tp.topic for p in snapshot.partitions}
        unknown = [b for b in delta.broker_ids_touched() if b not in known]
        if unknown:
            raise ModelDeltaError(
                f"delta names brokers {sorted(unknown)} unknown to the "
                f"cluster metadata (a genuinely new broker is a shape "
                f"change: refresh metadata and rebuild instead)")
        bad_topics = sorted({u.topic for u in delta.load_updates}
                            - topics)
        if bad_topics:
            raise ModelDeltaError(
                f"delta updates loads of unknown topics {bad_topics}")
        with self._delta_lock:
            frm = self.model_generation()
            self._overlay_new.update(a.broker_id
                                     for a in delta.add_brokers)
            self._overlay_removed.update(delta.remove_brokers)
            self._overlay_demoted.update(delta.demote_brokers)
            for b, caps in delta.capacity_overrides.items():
                merged = dict(self._overlay_capacity.get(int(b), {}))
                merged.update({k: float(v) for k, v in caps.items()})
                self._overlay_capacity[int(b)] = merged
            load_gen = self._partition_aggregator.generation
            for u in delta.load_updates:
                self._overlay_loads[(u.topic, int(u.partition))] = (
                    np.asarray(u.load, dtype=np.float64), load_gen)
            self._delta_generation += 1
            self._delta_seq += 1
            # `to` derives from `frm` with ONLY the delta step applied —
            # never re-read the live generation here: a concurrent
            # sample/metadata bump between the two reads would fold an
            # UNLOGGED change into this record and let the store
            # fast-forward across it.  If something did move
            # concurrently, the current generation simply won't match
            # any record's to_generation and the store rebuilds — the
            # chain breaks AT the unlogged change, never across it.
            to = ModelGeneration(frm.cluster_generation,
                                 frm.load_generation,
                                 self._delta_generation)
            self._delta_log.append(DeltaRecord(
                seq=self._delta_seq, from_generation=frm,
                to_generation=to, delta=delta))
            del self._delta_log[:-self._delta_log_size]
        LOG.info("model delta applied (%s): generation %s -> %s",
                 delta.describe(), frm, to)
        return to

    def deltas_between(self, from_generation, to_generation):
        """The contiguous DeltaRecord chain from_generation ->
        to_generation, or None when no chain exists (unlogged change,
        trimmed log) — the device store's fast-forward eligibility
        check (model/store.py)."""
        from cruise_control_tpu.monitor.deltas import chain_between
        with self._delta_lock:
            records = list(self._delta_log)
        return chain_between(records, from_generation, to_generation)

    def clear_model_overlay(self) -> ModelGeneration:
        """Drop every overlay entry (operator reset: the next rebuild
        reflects raw metadata + samples only).  Moves the generation —
        clearing changes the model."""
        with self._delta_lock:
            self._overlay_new.clear()
            self._overlay_removed.clear()
            self._overlay_demoted.clear()
            self._overlay_capacity.clear()
            self._overlay_loads.clear()
            self._delta_generation += 1
            # an overlay clear is deliberately NOT a logged delta: the
            # store must full-rebuild, never fast-forward over it
            return self.model_generation()

    def follower_cpu_estimator(self):
        """The follower-CPU attribution function the next
        cluster_model() build will use (trained regression, configured
        static weights, or the module defaults) — the device model
        store derives per-partition load splits with the SAME function
        so delta application stays byte-identical to a rebuild."""
        return self._follower_cpu_fn()

    def pause_metric_sampling(self, reason: str) -> None:
        self.task_runner.pause_sampling(reason)

    def resume_metric_sampling(self, reason: str) -> None:
        self.task_runner.resume_sampling(reason)

    def acquire_for_model_generation(self) -> "ModelBuildPermit":
        """reference KafkaCruiseControl.acquireForModelGeneration — bounded
        concurrency on expensive model builds."""
        return ModelBuildPermit(self._model_semaphore)

    # ------------------------------------------------------------------
    # completeness
    # ------------------------------------------------------------------
    def meet_completeness_requirements(
            self, req: ModelCompletenessRequirements) -> bool:
        """reference LoadMonitor.meetCompletenessRequirements :618-631."""
        try:
            result = self._partition_aggregator.aggregate_with_requirements(
                self._time_fn() * 1000.0, req)
        except NotEnoughValidWindowsError:
            return False
        comp = result.completeness
        return (len(comp.valid_window_indices) >= req.min_required_num_windows
                and comp.valid_entity_ratio
                >= req.min_monitored_partitions_percentage)

    def get_state(self) -> LoadMonitorState:
        with self._delta_lock:
            cached, cached_at = self._state_cache, self._state_cache_at
        if (cached is not None
                and self._time_fn() - cached_at < self._state_ttl_s):
            return cached
        snapshot = self._metadata.cluster()
        total = len(snapshot.partitions)
        try:
            result = self._partition_aggregator.aggregate_with_requirements(
                self._time_fn() * 1000.0, ModelCompletenessRequirements())
            valid_windows = len(result.completeness.valid_window_indices)
            ratio = result.completeness.valid_entity_ratio
            monitored = len(result.entity_values)
        except NotEnoughValidWindowsError:
            valid_windows, ratio, monitored = 0, 0.0, 0
        state_out = LoadMonitorState(
            state=self.task_runner.state.value,
            num_valid_windows=valid_windows,
            total_num_windows=self._partition_aggregator.num_windows,
            monitored_partitions_percentage=ratio,
            num_monitored_partitions=monitored,
            num_total_partitions=total,
            reason_of_pause=self.task_runner.reason_of_pause,
            last_sampling_ms=self._fetcher.last_sampling_ms)
        # publish cache + timestamp atomically: the detector thread and
        # request threads both land here (C203)
        with self._delta_lock:
            self._state_cache = state_out
            self._state_cache_at = self._time_fn()
        return state_out

    # ------------------------------------------------------------------
    # CPU model training (reference TrainingTask.java + TRAIN endpoint)
    # ------------------------------------------------------------------
    def train(self) -> None:
        """Fit the linear CPU model from the broker metric history: every
        (broker, window) cell contributes one training row of
        (cpu%, leader-bytes-in, leader-bytes-out, replication-bytes-in)."""
        bdef = MD.broker_metric_def()
        cpu = bdef.metric_id(MD.CPU_USAGE)
        lin = bdef.metric_id(MD.LEADER_BYTES_IN)
        lout = bdef.metric_id(MD.LEADER_BYTES_OUT)
        rin = bdef.metric_id(MD.REPLICATION_BYTES_IN_RATE)
        result = self._broker_aggregator.aggregate(-np.inf, np.inf)
        # each training round feeds the FULL current history
        self.cpu_model.clear_samples()
        for vae in result.entity_values.values():
            vals = vae.values
            for w in range(vals.shape[0]):
                self.cpu_model.add_sample(
                    float(vals[w, cpu]), float(vals[w, lin]),
                    float(vals[w, lout]), float(vals[w, rin]))
        self.cpu_model.train()
        if self._use_linear_regression:
            # training changes follower-CPU attribution, i.e. what the
            # next build produces: move the model generation (UNLOGGED —
            # the device store must full-rebuild with the new estimator,
            # never fast-forward a load delta split with the stale one,
            # and the proposal cache must not serve pre-TRAIN results
            # as current).  With use.linear.regression.model=false the
            # trained model is kept but unused: nothing changed.
            with self._delta_lock:
                self._delta_generation += 1

    # ------------------------------------------------------------------
    # model building
    # ------------------------------------------------------------------
    def _follower_cpu_fn(self):
        """Follower-CPU attribution for the next build: the trained
        regression once TRAIN ran (clamped to [0, leader CPU] so a noisy
        fit cannot attribute a follower more CPU than its leader uses),
        else the configured static weights, else the module defaults."""
        coefs = (self.cpu_model.coefficients
                 if self._use_linear_regression else None)
        if coefs is not None:
            return (lambda cpu, nw_in, nw_out:
                    min(max(coefs.estimate_follower_cpu(nw_in), 0.0),
                        float(cpu)))
        if self._cpu_util_weights is not None:
            lw_in, lw_out, fw_in = self._cpu_util_weights
            return (lambda cpu, nw_in, nw_out:
                    estimate_follower_cpu(
                        cpu, nw_in, nw_out,
                        leader_in_weight=lw_in,
                        leader_out_weight=lw_out,
                        follower_in_weight=fw_in))
        return estimate_follower_cpu

    def _expected_utilization(self, vae: ValuesAndExtrapolations
                              ) -> np.ndarray:
        """Collapse windows → one load vector: avg for CPU/NW, latest for
        DISK (reference model/Load.java:25-120).  Aggregator rows are
        ordered oldest→newest, so the latest window is the last row."""
        values = vae.values
        out = np.zeros(NUM_RESOURCES, dtype=np.float64)
        out[Resource.CPU] = values[:, self._cpu_id].mean()
        out[Resource.NW_IN] = values[:, self._nw_in_id].mean()
        out[Resource.NW_OUT] = values[:, self._nw_out_id].mean()
        out[Resource.DISK] = values[-1, self._disk_id]
        return out

    def cluster_model(self,
                      requirements: Optional[
                          ModelCompletenessRequirements] = None,
                      allow_capacity_estimation: bool = True,
                      now_ms: Optional[float] = None
                      ) -> Tuple[ClusterState, ClusterTopology]:
        """Build the tensor cluster model
        (reference LoadMonitor.clusterModel :518-570)."""
        req = requirements or ModelCompletenessRequirements(
            min_monitored_partitions_percentage=(
                self._min_valid_partition_ratio))
        now_ms = now_ms if now_ms is not None else self._time_fn() * 1000.0
        t0 = time.time()
        snapshot = self._metadata.refresh_metadata()
        result = self._partition_aggregator.aggregate_with_requirements(
            now_ms, req,
            max_allowed_extrapolations=self._max_extrapolations_partition)
        comp = result.completeness
        if (len(comp.valid_window_indices) < req.min_required_num_windows
                or comp.valid_entity_ratio
                < req.min_monitored_partitions_percentage):
            raise NotEnoughValidWindowsError(
                f"completeness not met: {len(comp.valid_window_indices)} "
                f"valid windows, {comp.valid_entity_ratio:.1%} monitored "
                f"partitions (need {req.min_required_num_windows} / "
                f"{req.min_monitored_partitions_percentage:.1%})")

        # one read: per-partition consistency + no per-partition locking;
        # the builder's leader-load split must use the same follower-CPU
        # attribution as the follower loads assigned below
        follower_cpu = self._follower_cpu_fn()
        builder = ClusterModelBuilder(follower_cpu_estimator=follower_cpu)
        # consistent overlay snapshot for this build: structured deltas
        # applied so far (monitor/deltas.py) — a rebuild must reflect
        # them byte-for-byte like the device store's in-place delta
        # application does (the incremental pin).  Load overrides whose
        # aggregator-generation stamp aged out (fresh samples arrived)
        # are superseded and pruned here.
        with self._delta_lock:
            load_gen_now = self._partition_aggregator.generation
            self._overlay_loads = {
                k: vs for k, vs in self._overlay_loads.items()
                if vs[1] == load_gen_now}
            ov_new = set(self._overlay_new)
            ov_removed = set(self._overlay_removed)
            ov_demoted = set(self._overlay_demoted)
            ov_capacity = {b: dict(c)
                           for b, c in self._overlay_capacity.items()}
            ov_loads = {k: vs[0] for k, vs in self._overlay_loads.items()}
        # --- brokers with resolved capacity (populateClusterCapacity) ---
        logdirs_by_broker = self._admin.describe_log_dirs(
            sorted(snapshot.all_broker_ids))
        jbod_dirs: Dict[int, frozenset] = {}
        for binfo in snapshot.brokers:
            cap = self._capacity_resolver.capacity_for_broker(
                binfo.rack, binfo.host, binfo.broker_id,
                allow_capacity_estimation
                and self._allow_cpu_capacity_estimation)
            disks = None
            if cap.disk_capacity_by_logdir:
                disks = dict(cap.disk_capacity_by_logdir)
                for ld in logdirs_by_broker.get(binfo.broker_id, []):
                    if ld.offline and ld.path in disks:
                        disks[ld.path] = 0.0   # dead logdir
                jbod_dirs[binfo.broker_id] = frozenset(disks)
            builder.add_broker(
                binfo.broker_id, rack_id=binfo.rack or binfo.host,
                capacity=cap.capacity, host=binfo.host,
                alive=binfo.alive
                and binfo.broker_id not in ov_removed,
                new=binfo.broker_id in ov_new,
                demoted=binfo.broker_id in ov_demoted,
                disks=disks)

        # --- per-partition replica loads (populatePartitionLoad) ---
        n_skipped = 0
        for pinfo in snapshot.partitions:
            entity = PartitionEntity(pinfo.tp.topic, pinfo.tp.partition)
            vae = result.entity_values.get(entity)
            if vae is None:
                n_skipped += 1
                continue
            override = ov_loads.get((pinfo.tp.topic, pinfo.tp.partition))
            leader_load = (override if override is not None
                           else self._expected_utilization(vae))
            offline = set(pinfo.offline_replicas)
            leader = pinfo.leader
            for broker_id in pinfo.replicas:
                is_leader = broker_id == leader
                if is_leader:
                    load = leader_load
                else:
                    load = leader_load.copy()
                    load[Resource.NW_OUT] = 0.0
                    # trained linear model takes over follower CPU
                    # attribution once TRAIN has run (reference
                    # ModelUtils.getFollowerCpuUtilFromLeaderLoad switches
                    # from static coefficients to the trained regression)
                    load[Resource.CPU] = follower_cpu(
                        leader_load[Resource.CPU],
                        leader_load[Resource.NW_IN],
                        leader_load[Resource.NW_OUT])
                logdir = pinfo.logdir_by_broker.get(broker_id)
                has_jbod = (logdir is not None
                            and logdir in jbod_dirs.get(broker_id, ()))
                builder.add_replica(
                    pinfo.tp.topic, pinfo.tp.partition, broker_id,
                    is_leader, load,
                    offline=broker_id in offline,
                    logdir=logdir if has_jbod else None)
        state, topology = builder.build()
        if ov_capacity:
            state = _apply_capacity_overlay(state, topology, ov_capacity)
        LOG.debug("generated cluster model in %.0f ms (B=%d P=%d R=%d, "
                  "%d partitions without samples)",
                  (time.time() - t0) * 1e3, state.num_brokers,
                  state.num_partitions,
                  int(np.asarray(state.replica_valid).sum()), n_skipped)
        return state, topology


def _apply_capacity_overlay(state: ClusterState, topology,
                            capacity_overrides) -> ClusterState:
    """Apply absolute capacity overrides to a freshly built state with
    EXACTLY the ops the device store's delta application uses
    (deltas-to-rows in monitor/deltas.capacity_rows, scatter in
    model/state.set_broker_capacities) — the shared helpers are what
    makes rebuild-vs-delta byte equality hold by construction."""
    from cruise_control_tpu.model.state import set_broker_capacities
    from cruise_control_tpu.monitor.deltas import capacity_rows
    rows, mask, values = capacity_rows(capacity_overrides,
                                       topology.broker_index)
    if rows.size == 0:
        return state
    return set_broker_capacities(state, rows, mask, values)


class ModelBuildPermit:
    """Context manager wrapping the model-generation semaphore."""

    def __init__(self, semaphore: threading.BoundedSemaphore):
        self._semaphore = semaphore

    def __enter__(self) -> "ModelBuildPermit":
        self._semaphore.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self._semaphore.release()
