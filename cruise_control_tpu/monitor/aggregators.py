"""Framework-specific metric-sample aggregators.

Reference: CC/monitor/sampling/aggregator/
KafkaPartitionMetricSampleAggregator.java:1-301 (entity = partition, group =
topic) and KafkaBrokerMetricSampleAggregator.java (entity = broker) — thin
specializations of the core windowed aggregator that add the monitoring
config wiring and completeness-requirement translation.
"""
from __future__ import annotations

from typing import Iterable

from cruise_control_tpu.core.aggregator import (
    AggregationOptions, Granularity, MetricSampleAggregationResult,
    MetricSampleAggregator)
from cruise_control_tpu.monitor.completeness import (
    ModelCompletenessRequirements)
from cruise_control_tpu.monitor.metricdef import (broker_metric_def,
                                                  common_metric_def)
from cruise_control_tpu.monitor.sampling.holder import (BrokerMetricSample,
                                                        PartitionMetricSample)


class PartitionMetricSampleAggregator(MetricSampleAggregator):
    """Windowed aggregation over partition entities
    (reference KafkaPartitionMetricSampleAggregator.java:1-301)."""

    def __init__(self, num_windows: int, window_ms: int,
                 min_samples_per_window: int,
                 completeness_cache_size: int = 5):
        super().__init__(num_windows, window_ms, min_samples_per_window,
                         common_metric_def(), completeness_cache_size)

    def add_partition_sample(self, sample: PartitionMetricSample) -> bool:
        return self.add_sample(sample.to_metric_sample())

    def add_partition_samples(self,
                              samples: Iterable[PartitionMetricSample]) -> int:
        return sum(1 for s in samples if self.add_partition_sample(s))

    def aggregate_with_requirements(
            self, now_ms: float, req: ModelCompletenessRequirements,
            interested_entities=None,
            max_allowed_extrapolations: int = 5
            ) -> MetricSampleAggregationResult:
        """Aggregate [oldest, now] under a completeness requirement
        (reference KafkaPartitionMetricSampleAggregator.aggregate)."""
        options = AggregationOptions(
            min_valid_entity_ratio=req.min_monitored_partitions_percentage,
            min_valid_entity_group_ratio=0.0,
            min_valid_windows=req.min_required_num_windows,
            max_allowed_extrapolations_per_entity=max_allowed_extrapolations,
            granularity=(Granularity.ENTITY_GROUP
                         if req.include_all_topics else Granularity.ENTITY),
            include_invalid_entities=req.include_all_topics,
            interested_entities=interested_entities)
        return self.aggregate(-1.0, now_ms, options)


class BrokerMetricSampleAggregator(MetricSampleAggregator):
    """Windowed aggregation over broker entities
    (reference KafkaBrokerMetricSampleAggregator.java)."""

    def __init__(self, num_windows: int, window_ms: int,
                 min_samples_per_window: int,
                 completeness_cache_size: int = 5):
        super().__init__(num_windows, window_ms, min_samples_per_window,
                         broker_metric_def(), completeness_cache_size)

    def add_broker_sample(self, sample: BrokerMetricSample) -> bool:
        return self.add_sample(sample.to_metric_sample())

    def add_broker_samples(self,
                           samples: Iterable[BrokerMetricSample]) -> int:
        return sum(1 for s in samples if self.add_broker_sample(s))
