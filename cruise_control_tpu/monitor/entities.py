"""Aggregation entities: partition (grouped by topic) and broker.

Reference: CC/monitor/sampling/PartitionEntity.java and BrokerEntity.java —
the keys the two metric-sample aggregators aggregate by; the partition
entity's group is its topic, which powers ENTITY_GROUP completeness
(a topic is only valid if all its partitions are; reference
AggregationOptions.Granularity).
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class PartitionEntity:
    topic: str
    partition: int

    @property
    def group(self) -> str:
        return self.topic

    def __str__(self) -> str:
        return f"{self.topic}-{self.partition}"


@dataclasses.dataclass(frozen=True)
class BrokerEntity:
    broker_id: int

    @property
    def group(self) -> None:
        return None

    def __str__(self) -> str:
        return f"broker-{self.broker_id}"
