"""Sampling: sampler SPI, fetchers, sample holders/serde, sample stores.

Submodules are imported lazily at use sites to avoid circular imports with
the aggregators module (fetcher ↔ aggregators).
"""
from cruise_control_tpu.monitor.sampling.holder import (BrokerMetricSample,
                                                        PartitionMetricSample)
from cruise_control_tpu.monitor.sampling.sampler import (MetricSampler,
                                                         NoopSampler,
                                                         Samples,
                                                         SamplingMode,
                                                         SimulatedClusterSampler)
from cruise_control_tpu.monitor.sampling.sample_store import (FileSampleStore,
                                                              NoopSampleStore,
                                                              SampleLoader,
                                                              SampleStore)

__all__ = [
    "BrokerMetricSample", "PartitionMetricSample", "MetricSampler",
    "NoopSampler", "Samples", "SamplingMode", "SimulatedClusterSampler",
    "FileSampleStore", "NoopSampleStore", "SampleLoader", "SampleStore",
]
