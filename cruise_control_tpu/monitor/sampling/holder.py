"""Metric sample holders + binary serde + ingest quarantine.

Reference: CC/monitor/sampling/holder/PartitionMetricSample.java and
BrokerMetricSample.java:1-359 — the typed sample objects built by the
metrics processor, persisted by the sample store (binary serde with a
version byte), and fed to the windowed aggregators.

The quarantine (new in PR 2) is the INGEST half of the solver's
invalid-input defense: a NaN/Inf/negative metric value admitted into a
window poisons every model built from it, and the device-resident solve
only detects the damage at its end-of-solve fetch
(analyzer/optimizer.inputs_invalid).  Dropping the offending sample here
— behind a counter so data loss is visible — keeps the model clean at
the source; the device-side sweep remains as the last line for values
corrupted past ingest."""
from __future__ import annotations

import dataclasses
import math
import struct
from typing import Dict, Iterable, List, Mapping, Tuple

from cruise_control_tpu.cluster.types import TopicPartition
from cruise_control_tpu.core.aggregator import MetricSample
from cruise_control_tpu.monitor.entities import BrokerEntity, PartitionEntity
from cruise_control_tpu.monitor.metricdef import (broker_metric_def,
                                                  common_metric_def)

_HEADER = struct.Struct("<BqiH")  # version, time_ms, broker_id, n_metrics
_METRIC = struct.Struct("<Hf")    # metric id, value


@dataclasses.dataclass(frozen=True)
class PartitionMetricSample:
    """All common metrics of one partition (on its leader broker) at one
    instant (reference holder/PartitionMetricSample.java)."""

    broker_id: int
    tp: TopicPartition
    sample_time_ms: float
    values: Mapping[int, float]  # metric id (common def) -> value

    CURRENT_VERSION = 1

    def to_metric_sample(self) -> MetricSample:
        return MetricSample(PartitionEntity(self.tp.topic, self.tp.partition),
                            self.sample_time_ms, dict(self.values))

    def to_bytes(self) -> bytes:
        topic = self.tp.topic.encode()
        out = [_HEADER.pack(self.CURRENT_VERSION, int(self.sample_time_ms),
                            self.broker_id, len(self.values)),
               struct.pack("<Hi", len(topic), self.tp.partition), topic]
        for mid, val in sorted(self.values.items()):
            out.append(_METRIC.pack(mid, float(val)))
        return b"".join(out)

    @classmethod
    def from_bytes(cls, data: bytes) -> "PartitionMetricSample":
        ver, time_ms, broker_id, n = _HEADER.unpack_from(data, 0)
        if ver > cls.CURRENT_VERSION:
            raise ValueError(f"unsupported partition-sample version {ver}")
        off = _HEADER.size
        tlen, partition = struct.unpack_from("<Hi", data, off)
        off += 6
        topic = data[off:off + tlen].decode()
        off += tlen
        values: Dict[int, float] = {}
        for _ in range(n):
            mid, val = _METRIC.unpack_from(data, off)
            off += _METRIC.size
            values[mid] = val
        return cls(broker_id, TopicPartition(topic, partition),
                   float(time_ms), values)


@dataclasses.dataclass(frozen=True)
class BrokerMetricSample:
    """All broker metrics of one broker at one instant
    (reference holder/BrokerMetricSample.java:1-359)."""

    broker_id: int
    sample_time_ms: float
    values: Mapping[int, float]  # metric id (broker def) -> value

    CURRENT_VERSION = 1

    def to_metric_sample(self) -> MetricSample:
        return MetricSample(BrokerEntity(self.broker_id),
                            self.sample_time_ms, dict(self.values))

    def metric_value(self, name: str) -> float:
        return self.values.get(broker_metric_def().metric_id(name), 0.0)

    def to_bytes(self) -> bytes:
        out = [_HEADER.pack(self.CURRENT_VERSION, int(self.sample_time_ms),
                            self.broker_id, len(self.values))]
        for mid, val in sorted(self.values.items()):
            out.append(_METRIC.pack(mid, float(val)))
        return b"".join(out)

    @classmethod
    def from_bytes(cls, data: bytes) -> "BrokerMetricSample":
        ver, time_ms, broker_id, n = _HEADER.unpack_from(data, 0)
        if ver > cls.CURRENT_VERSION:
            raise ValueError(f"unsupported broker-sample version {ver}")
        off = _HEADER.size
        values: Dict[int, float] = {}
        for _ in range(n):
            mid, val = _METRIC.unpack_from(data, off)
            off += _METRIC.size
            values[mid] = val
        return cls(broker_id, float(time_ms), values)


def sample_values_valid(values: Mapping[int, float]) -> bool:
    """True when every metric value is finite and non-negative (all the
    framework's metrics are rates/sizes/percentages — a negative value is
    as corrupt as a NaN)."""
    for v in values.values():
        if not math.isfinite(v) or v < 0.0:
            return False
    return True


def quarantine_invalid(samples: Iterable) -> Tuple[List, int]:
    """Split a batch of Partition/BrokerMetricSamples into (valid,
    dropped-count); the caller owns the counting (the fetcher keeps the
    per-process counter the facade exports as
    `sampler-quarantined-samples`)."""
    valid = []
    dropped = 0
    for s in samples:
        if sample_values_valid(s.values):
            valid.append(s)
        else:
            dropped += 1
    return valid, dropped


def complete_partition_values(partial: Mapping[int, float]) -> Dict[int, float]:
    """Fill unset common-metric ids with 0.0 (the aggregator requires a value
    for every defined metric; reference MetricSample.close())."""
    values = {i: 0.0 for i in range(common_metric_def().size())}
    values.update(partial)
    return values


def complete_broker_values(partial: Mapping[int, float]) -> Dict[int, float]:
    values = {i: 0.0 for i in range(broker_metric_def().size())}
    values.update(partial)
    return values
