"""SampleStore SPI: durable metric samples reloaded at startup.

Reference: CC/monitor/sampling/SampleStore.java:1-91 — persists partition
and broker samples so a restarted instance recovers its load history
without waiting num_windows × window_ms; the default stores to two Kafka
topics (KafkaSampleStore.java:85-552).  Here the default is a pair of
append-only local files using the binary sample serde (holder.py); the
loading path streams records back through the same SampleLoader interface.
"""
from __future__ import annotations

import abc
import logging
import os
import struct
import threading
import time as _time
from typing import Iterable, Optional

from cruise_control_tpu.monitor.sampling.holder import (BrokerMetricSample,
                                                        PartitionMetricSample)
from cruise_control_tpu.monitor.sampling.sampler import Samples

LOG = logging.getLogger(__name__)

_LEN = struct.Struct("<I")


class SampleLoader(abc.ABC):
    """Callback receiving reloaded samples (reference SampleStore.SampleLoader)."""

    @abc.abstractmethod
    def load_samples(self, samples: Samples) -> None:
        ...


class SampleStore(abc.ABC):
    """reference SampleStore.java:1-91"""

    def configure(self, configs) -> None:  # pragma: no cover - plugin hook
        pass

    @abc.abstractmethod
    def store_samples(self, samples: Samples) -> None:
        ...

    @abc.abstractmethod
    def load_samples(self, loader: SampleLoader) -> None:
        ...

    def evict_samples_before(self, timestamp_ms: float) -> None:
        """Optional retention hook."""

    def close(self) -> None:  # pragma: no cover
        pass


class NoopSampleStore(SampleStore):
    """reference NoopSampleStore"""

    def store_samples(self, samples: Samples) -> None:
        pass

    def load_samples(self, loader: SampleLoader) -> None:
        pass


class FileSampleStore(SampleStore):
    """Length-prefixed binary record log per sample kind.

    Two files mirror the reference's two store topics
    (partition.metric.sample.store.topic / broker.metric.sample.store.topic,
    KafkaSampleStore.java:117-118).
    """

    PARTITION_FILE = "partition-samples.bin"
    BROKER_FILE = "broker-samples.bin"

    def __init__(self, directory: Optional[str] = None,
                 partition_retention_ms: Optional[float] = None,
                 broker_retention_ms: Optional[float] = None,
                 time_fn=None):
        #: directory may instead come from config via configure()
        #: (reference sample.store.* keys); files open lazily
        self._dir = directory
        self._partition_retention_ms = partition_retention_ms
        self._broker_retention_ms = broker_retention_ms
        self._time = time_fn or _time.time
        self._lock = threading.Lock()
        self._pf = self._bf = None
        if directory:
            self._open()

    def configure(self, configs) -> None:
        """Plugin-style config hook (reference KafkaSampleStore.configure):
        reads sample.store.directory and the two *.sample.retention.ms
        keys when the store was instantiated via config."""
        if self._dir is None:
            self._dir = configs.get("sample.store.directory") or "cc-samples"
        for attr, key in (("_partition_retention_ms",
                           "partition.sample.retention.ms"),
                          ("_broker_retention_ms",
                           "broker.sample.retention.ms")):
            if getattr(self, attr) is None and configs.get(key):
                setattr(self, attr, float(configs[key]))
        if self._pf is None:
            self._open()

    def _open(self) -> None:
        os.makedirs(self._dir, exist_ok=True)
        self._pf = open(os.path.join(self._dir, self.PARTITION_FILE), "ab")
        self._bf = open(os.path.join(self._dir, self.BROKER_FILE), "ab")

    def store_samples(self, samples: Samples) -> None:
        with self._lock:
            for s in samples.partition_samples:
                rec = s.to_bytes()
                self._pf.write(_LEN.pack(len(rec)) + rec)
            for s in samples.broker_samples:
                rec = s.to_bytes()
                self._bf.write(_LEN.pack(len(rec)) + rec)
            self._pf.flush()
            self._bf.flush()

    @staticmethod
    def _read_records(path: str) -> Iterable[bytes]:
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            while True:
                head = f.read(_LEN.size)
                if len(head) < _LEN.size:
                    return
                (n,) = _LEN.unpack(head)
                rec = f.read(n)
                if len(rec) < n:
                    LOG.warning("truncated sample record in %s; stopping "
                                "load", path)
                    return
                yield rec

    def load_samples(self, loader: SampleLoader) -> None:
        batch = Samples()
        n_bad = 0
        n_expired = 0
        now_ms = self._time() * 1000.0
        p_cut = (now_ms - self._partition_retention_ms
                 if self._partition_retention_ms else None)
        b_cut = (now_ms - self._broker_retention_ms
                 if self._broker_retention_ms else None)
        for rec in self._read_records(
                os.path.join(self._dir, self.PARTITION_FILE)):
            try:
                sample = PartitionMetricSample.from_bytes(rec)
            except (ValueError, struct.error):
                n_bad += 1
                continue
            if p_cut is not None and sample.sample_time_ms < p_cut:
                n_expired += 1
                continue
            batch.partition_samples.append(sample)
        for rec in self._read_records(
                os.path.join(self._dir, self.BROKER_FILE)):
            try:
                sample = BrokerMetricSample.from_bytes(rec)
            except (ValueError, struct.error):
                n_bad += 1
                continue
            if b_cut is not None and sample.sample_time_ms < b_cut:
                n_expired += 1
                continue
            batch.broker_samples.append(sample)
        if n_bad:
            LOG.warning("skipped %d unreadable stored samples", n_bad)
        if n_expired:
            LOG.info("dropped %d stored samples past retention", n_expired)
        loader.load_samples(batch)
        LOG.info("loaded %d partition + %d broker samples from %s",
                 len(batch.partition_samples), len(batch.broker_samples),
                 self._dir)

    def close(self) -> None:
        with self._lock:
            if self._pf is not None:
                self._pf.close()
                self._bf.close()
