"""SampleStore SPI: durable metric samples reloaded at startup.

Reference: CC/monitor/sampling/SampleStore.java:1-91 — persists partition
and broker samples so a restarted instance recovers its load history
without waiting num_windows × window_ms; the default stores to two Kafka
topics (KafkaSampleStore.java:85-552).  Here the default is a pair of
append-only local files using the binary sample serde (holder.py); the
loading path streams records back through the same SampleLoader interface.
"""
from __future__ import annotations

import abc
import logging
import os
import struct
import threading
from typing import Iterable, Optional

from cruise_control_tpu.monitor.sampling.holder import (BrokerMetricSample,
                                                        PartitionMetricSample)
from cruise_control_tpu.monitor.sampling.sampler import Samples

LOG = logging.getLogger(__name__)

_LEN = struct.Struct("<I")


class SampleLoader(abc.ABC):
    """Callback receiving reloaded samples (reference SampleStore.SampleLoader)."""

    @abc.abstractmethod
    def load_samples(self, samples: Samples) -> None:
        ...


class SampleStore(abc.ABC):
    """reference SampleStore.java:1-91"""

    def configure(self, configs) -> None:  # pragma: no cover - plugin hook
        pass

    @abc.abstractmethod
    def store_samples(self, samples: Samples) -> None:
        ...

    @abc.abstractmethod
    def load_samples(self, loader: SampleLoader) -> None:
        ...

    def evict_samples_before(self, timestamp_ms: float) -> None:
        """Optional retention hook."""

    def close(self) -> None:  # pragma: no cover
        pass


class NoopSampleStore(SampleStore):
    """reference NoopSampleStore"""

    def store_samples(self, samples: Samples) -> None:
        pass

    def load_samples(self, loader: SampleLoader) -> None:
        pass


class FileSampleStore(SampleStore):
    """Length-prefixed binary record log per sample kind.

    Two files mirror the reference's two store topics
    (partition.metric.sample.store.topic / broker.metric.sample.store.topic,
    KafkaSampleStore.java:117-118).
    """

    PARTITION_FILE = "partition-samples.bin"
    BROKER_FILE = "broker-samples.bin"

    def __init__(self, directory: str):
        self._dir = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._pf = open(os.path.join(directory, self.PARTITION_FILE), "ab")
        self._bf = open(os.path.join(directory, self.BROKER_FILE), "ab")

    def store_samples(self, samples: Samples) -> None:
        with self._lock:
            for s in samples.partition_samples:
                rec = s.to_bytes()
                self._pf.write(_LEN.pack(len(rec)) + rec)
            for s in samples.broker_samples:
                rec = s.to_bytes()
                self._bf.write(_LEN.pack(len(rec)) + rec)
            self._pf.flush()
            self._bf.flush()

    @staticmethod
    def _read_records(path: str) -> Iterable[bytes]:
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            while True:
                head = f.read(_LEN.size)
                if len(head) < _LEN.size:
                    return
                (n,) = _LEN.unpack(head)
                rec = f.read(n)
                if len(rec) < n:
                    LOG.warning("truncated sample record in %s; stopping "
                                "load", path)
                    return
                yield rec

    def load_samples(self, loader: SampleLoader) -> None:
        batch = Samples()
        n_bad = 0
        for rec in self._read_records(
                os.path.join(self._dir, self.PARTITION_FILE)):
            try:
                batch.partition_samples.append(
                    PartitionMetricSample.from_bytes(rec))
            except (ValueError, struct.error):
                n_bad += 1
        for rec in self._read_records(
                os.path.join(self._dir, self.BROKER_FILE)):
            try:
                batch.broker_samples.append(BrokerMetricSample.from_bytes(rec))
            except (ValueError, struct.error):
                n_bad += 1
        if n_bad:
            LOG.warning("skipped %d unreadable stored samples", n_bad)
        loader.load_samples(batch)
        LOG.info("loaded %d partition + %d broker samples from %s",
                 len(batch.partition_samples), len(batch.broker_samples),
                 self._dir)

    def close(self) -> None:
        with self._lock:
            self._pf.close()
            self._bf.close()
