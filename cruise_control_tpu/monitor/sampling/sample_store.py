"""SampleStore SPI: durable metric samples reloaded at startup.

Reference: CC/monitor/sampling/SampleStore.java:1-91 — persists partition
and broker samples so a restarted instance recovers its load history
without waiting num_windows × window_ms; the default stores to two Kafka
topics (KafkaSampleStore.java:85-552).  Here the default is a pair of
append-only local files using the binary sample serde (holder.py); the
loading path streams records back through the same SampleLoader interface.
"""
from __future__ import annotations

import abc
import logging
import os
import struct
import threading
import time as _time
from typing import Iterable, Optional

from cruise_control_tpu.monitor.sampling.holder import (BrokerMetricSample,
                                                        PartitionMetricSample)
from cruise_control_tpu.monitor.sampling.sampler import Samples
from cruise_control_tpu.utils import persist

LOG = logging.getLogger(__name__)

_LEN = struct.Struct("<I")


class SampleLoader(abc.ABC):
    """Callback receiving reloaded samples (reference SampleStore.SampleLoader)."""

    @abc.abstractmethod
    def load_samples(self, samples: Samples) -> None:
        ...


class SampleStore(abc.ABC):
    """reference SampleStore.java:1-91"""

    def configure(self, configs) -> None:  # pragma: no cover - plugin hook
        pass

    @abc.abstractmethod
    def store_samples(self, samples: Samples) -> None:
        ...

    @abc.abstractmethod
    def load_samples(self, loader: SampleLoader) -> None:
        ...

    def evict_samples_before(self, timestamp_ms: float) -> None:
        """Optional retention hook."""

    def close(self) -> None:  # pragma: no cover
        pass


class NoopSampleStore(SampleStore):
    """reference NoopSampleStore"""

    def store_samples(self, samples: Samples) -> None:
        pass

    def load_samples(self, loader: SampleLoader) -> None:
        pass


class FileSampleStore(SampleStore):
    """Length-prefixed binary record log per sample kind.

    Two files mirror the reference's two store topics
    (partition.metric.sample.store.topic / broker.metric.sample.store.topic,
    KafkaSampleStore.java:117-118).
    """

    PARTITION_FILE = "partition-samples.bin"
    BROKER_FILE = "broker-samples.bin"

    def __init__(self, directory: Optional[str] = None,
                 partition_retention_ms: Optional[float] = None,
                 broker_retention_ms: Optional[float] = None,
                 fsync: bool = False,
                 compaction_interval_ms: Optional[float] = None,
                 time_fn=None):
        #: directory may instead come from config via configure()
        #: (reference sample.store.* keys); files open lazily
        self._dir = directory
        self._partition_retention_ms = partition_retention_ms
        self._broker_retention_ms = broker_retention_ms
        #: fsync-on-store for journal-grade deployments (config key
        #: sample.store.fsync): samples survive a host crash, at the
        #: cost of one fsync per store call
        self._fsync = fsync
        #: how often store_samples applies retention ON DISK.  Without
        #: compaction the two files grow unbounded (retention used to
        #: be applied only at load); default: a quarter of the shortest
        #: configured retention
        self._compaction_interval_ms = compaction_interval_ms
        self._last_compaction_ms: Optional[float] = None
        self.compactions = 0
        self.evicted_samples = 0
        self._time = time_fn or _time.time
        self._lock = threading.Lock()
        self._pf = self._bf = None
        if directory:
            self._open()

    def configure(self, configs) -> None:
        """Plugin-style config hook (reference KafkaSampleStore.configure):
        reads sample.store.directory, the two *.sample.retention.ms
        keys, and sample.store.fsync when the store was instantiated
        via config."""
        if self._dir is None:
            self._dir = configs.get("sample.store.directory") or "cc-samples"
        for attr, key in (("_partition_retention_ms",
                           "partition.sample.retention.ms"),
                          ("_broker_retention_ms",
                           "broker.sample.retention.ms")):
            if getattr(self, attr) is None and configs.get(key):
                setattr(self, attr, float(configs[key]))
        if str(configs.get("sample.store.fsync", "")).lower() == "true":
            self._fsync = True
        if configs.get("sample.store.compaction.interval.ms"):
            self._compaction_interval_ms = float(
                configs["sample.store.compaction.interval.ms"])
        if self._pf is None:
            self._open()

    def _open(self) -> None:
        os.makedirs(self._dir, exist_ok=True)
        self._pf = open(os.path.join(self._dir, self.PARTITION_FILE), "ab")
        self._bf = open(os.path.join(self._dir, self.BROKER_FILE), "ab")

    def store_samples(self, samples: Samples) -> None:
        with self._lock:
            for s in samples.partition_samples:
                rec = s.to_bytes()
                self._pf.write(_LEN.pack(len(rec)) + rec)
            for s in samples.broker_samples:
                rec = s.to_bytes()
                self._bf.write(_LEN.pack(len(rec)) + rec)
            self._pf.flush()
            self._bf.flush()
            if self._fsync:
                os.fsync(self._pf.fileno())
                os.fsync(self._bf.fileno())
            self._maybe_compact_locked()

    @staticmethod
    def _read_records(path: str) -> Iterable[bytes]:
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            while True:
                head = f.read(_LEN.size)
                if len(head) < _LEN.size:
                    return
                (n,) = _LEN.unpack(head)
                rec = f.read(n)
                if len(rec) < n:
                    LOG.warning("truncated sample record in %s; stopping "
                                "load", path)
                    return
                yield rec

    # ------------------------------------------------------------------
    # retention compaction (durability fix): retention used to apply
    # only at LOAD, so a long-lived process grew both files unbounded —
    # now store_samples compacts on the retention cadence via
    # rewrite-temp-then-rename (utils/persist.py), keeping the on-disk
    # footprint proportional to the retention window
    # ------------------------------------------------------------------
    def _maybe_compact_locked(self) -> None:
        retentions = [r for r in (self._partition_retention_ms,
                                  self._broker_retention_ms)
                      if r is not None]
        if not retentions:
            return
        interval = (self._compaction_interval_ms
                    if self._compaction_interval_ms is not None
                    and self._compaction_interval_ms > 0
                    else min(retentions) / 4.0)
        now_ms = self._time() * 1000.0
        if self._last_compaction_ms is not None \
                and now_ms - self._last_compaction_ms < interval:
            return
        self._last_compaction_ms = now_ms
        if self._partition_retention_ms is not None:
            self._compact_locked(
                self.PARTITION_FILE, PartitionMetricSample,
                now_ms - self._partition_retention_ms)
        if self._broker_retention_ms is not None:
            self._compact_locked(
                self.BROKER_FILE, BrokerMetricSample,
                now_ms - self._broker_retention_ms)

    def evict_samples_before(self, timestamp_ms: float) -> None:
        """Retention SPI hook: drop stored samples older than
        `timestamp_ms` from BOTH files, on disk, immediately."""
        with self._lock:
            if self._pf is None:
                return
            self._compact_locked(self.PARTITION_FILE,
                                 PartitionMetricSample, timestamp_ms)
            self._compact_locked(self.BROKER_FILE, BrokerMetricSample,
                                 timestamp_ms)

    def _compact_locked(self, filename: str, sample_cls,
                        cutoff_ms: float) -> None:
        """Rewrite one record log keeping only samples at/after the
        cutoff (and dropping unreadable records): stream old -> temp,
        atomic rename, reopen the append handle.  A crash at any point
        leaves either the old complete file or the new complete file."""
        path = os.path.join(self._dir, filename)
        handle_attr = ("_pf" if filename == self.PARTITION_FILE
                       else "_bf")
        kept = dropped = 0

        def surviving_chunks():
            nonlocal kept, dropped
            for rec in self._read_records(path):
                try:
                    sample = sample_cls.from_bytes(rec)
                except (ValueError, struct.error):
                    dropped += 1
                    continue
                if sample.sample_time_ms < cutoff_ms:
                    dropped += 1
                    continue
                kept += 1
                yield _LEN.pack(len(rec)) + rec

        old = getattr(self, handle_attr)
        old.flush()
        try:
            persist.atomic_rewrite(path, surviving_chunks(),
                                   fsync=self._fsync)
        except OSError as exc:
            LOG.warning("sample-store compaction of %s failed (%s); "
                        "keeping the uncompacted file", path, exc)
            return
        old.close()
        setattr(self, handle_attr, open(path, "ab"))
        if dropped:
            self.evicted_samples += dropped
            LOG.info("sample store: compacted %s (%d kept, %d "
                     "evicted)", filename, kept, dropped)
        self.compactions += 1

    def load_samples(self, loader: SampleLoader) -> None:
        batch = Samples()
        n_bad = 0
        n_expired = 0
        now_ms = self._time() * 1000.0
        p_cut = (now_ms - self._partition_retention_ms
                 if self._partition_retention_ms else None)
        b_cut = (now_ms - self._broker_retention_ms
                 if self._broker_retention_ms else None)
        for rec in self._read_records(
                os.path.join(self._dir, self.PARTITION_FILE)):
            try:
                sample = PartitionMetricSample.from_bytes(rec)
            except (ValueError, struct.error):
                n_bad += 1
                continue
            if p_cut is not None and sample.sample_time_ms < p_cut:
                n_expired += 1
                continue
            batch.partition_samples.append(sample)
        for rec in self._read_records(
                os.path.join(self._dir, self.BROKER_FILE)):
            try:
                sample = BrokerMetricSample.from_bytes(rec)
            except (ValueError, struct.error):
                n_bad += 1
                continue
            if b_cut is not None and sample.sample_time_ms < b_cut:
                n_expired += 1
                continue
            batch.broker_samples.append(sample)
        if n_bad:
            LOG.warning("skipped %d unreadable stored samples", n_bad)
        if n_expired:
            LOG.info("dropped %d stored samples past retention", n_expired)
        loader.load_samples(batch)
        LOG.info("loaded %d partition + %d broker samples from %s",
                 len(batch.partition_samples), len(batch.broker_samples),
                 self._dir)

    def close(self) -> None:
        with self._lock:
            if self._pf is not None:
                self._pf.close()
                self._bf.close()
