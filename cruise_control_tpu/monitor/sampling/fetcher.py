"""Metric fetching: partition assignment + parallel sampler invocation.

Reference: CC/monitor/sampling/MetricFetcherManager.java:1-224 — N
metric-fetcher threads, each sampling a disjoint partition subset via the
configured `MetricSampler`, feeding the aggregators and the sample store;
the partition assignor hashes partitions across fetchers
(docs/wiki/Overview.md:13-27).
"""
from __future__ import annotations

import logging
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence, Set

from cruise_control_tpu.cluster.types import ClusterSnapshot, TopicPartition
from cruise_control_tpu.monitor.aggregators import (
    BrokerMetricSampleAggregator, PartitionMetricSampleAggregator)
from cruise_control_tpu.monitor.sampling.holder import quarantine_invalid
from cruise_control_tpu.monitor.sampling.sample_store import SampleStore
from cruise_control_tpu.monitor.sampling.sampler import (MetricSampler,
                                                         Samples,
                                                         SamplingMode)
from cruise_control_tpu.utils import faults

LOG = logging.getLogger(__name__)


def assign_partitions(partitions: Sequence[TopicPartition],
                      num_fetchers: int) -> List[Set[TopicPartition]]:
    """Deterministic hash assignment of partitions to fetchers
    (reference DefaultMetricSamplerPartitionAssignor)."""
    buckets: List[Set[TopicPartition]] = [set() for _ in range(num_fetchers)]
    for tp in partitions:
        buckets[hash((tp.topic, tp.partition)) % num_fetchers].add(tp)
    return buckets


class PartitionAssignor:
    """SPI: distribute partitions across fetchers (reference
    MetricSamplerPartitionAssignor, wired by
    `metric.sampler.partition.assignor.class`)."""

    def configure(self, props) -> None:  # pragma: no cover - plugin hook
        """Config hook for get_configured_instance."""

    def assign(self, partitions: Sequence[TopicPartition],
               num_fetchers: int) -> List[Set[TopicPartition]]:
        raise NotImplementedError


class DefaultPartitionAssignor(PartitionAssignor):
    """Hash-bucket assignment (the module-level assign_partitions)."""

    def assign(self, partitions: Sequence[TopicPartition],
               num_fetchers: int) -> List[Set[TopicPartition]]:
        return assign_partitions(partitions, num_fetchers)


class MetricFetcherManager:
    """Drives sampling rounds (reference MetricFetcherManager.java:1-224)."""

    def __init__(self, sampler: MetricSampler,
                 partition_aggregator: PartitionMetricSampleAggregator,
                 broker_aggregator: BrokerMetricSampleAggregator,
                 sample_store: Optional[SampleStore] = None,
                 num_fetchers: int = 1,
                 sampling_timeout_s: float = 60.0,
                 partition_assignor: "PartitionAssignor" = None):
        self._sampler = sampler
        self._partition_aggregator = partition_aggregator
        self._broker_aggregator = broker_aggregator
        self._sample_store = sample_store
        self._num_fetchers = max(1, num_fetchers)
        self._assignor = partition_assignor or DefaultPartitionAssignor()
        self._timeout_s = sampling_timeout_s
        self._pool = ThreadPoolExecutor(
            max_workers=self._num_fetchers,
            thread_name_prefix="metric-fetcher")
        # sampling stats for the REST state endpoint
        self.last_sampling_ms: float = 0.0
        self.last_sampling_duration_s: float = 0.0
        #: samples dropped by the ingest quarantine (NaN/Inf/negative
        #: values; holder.quarantine_invalid) — data loss made visible
        self.num_quarantined_samples: int = 0

    def fetch_metrics_for_model(self, cluster: ClusterSnapshot,
                                start_ms: float, end_ms: float,
                                mode: SamplingMode = SamplingMode.ALL
                                ) -> Samples:
        """One sampling round over all partitions; returns the merged
        samples after feeding aggregators + store."""
        t0 = time.time()
        partitions = [p.tp for p in cluster.partitions]
        buckets = [b for b in
                   self._assignor.assign(partitions,
                                         self._num_fetchers) if b]
        if not buckets:
            # no partitions yet — still collect broker metrics so
            # broker-level detection isn't blind on an empty cluster
            buckets = [set()]
        merged = Samples()
        futures = []
        for i, bucket in enumerate(buckets):
            # only fetcher 0 reports broker metrics to avoid duplicates
            if i == 0:
                m = mode
            elif mode == SamplingMode.BROKER_METRICS_ONLY:
                continue   # fetcher 0 already covers all broker metrics
            else:
                m = SamplingMode.PARTITION_METRICS_ONLY
            def fetch_one(bucket=bucket, m=m):
                faults.inject("monitor.sampler.fetch")
                return self._sampler.get_samples(cluster, bucket, start_ms,
                                                 end_ms, m)
            futures.append(self._pool.submit(fetch_one))
        for fut in futures:
            try:
                merged.merge(fut.result(timeout=self._timeout_s))
            except Exception:  # noqa: BLE001 - sampler is a plugin
                LOG.exception("metric sampler failed; continuing with "
                              "partial samples")
        # ingest quarantine: a NaN/Inf/negative value admitted into a
        # window poisons every model built from it — drop the sample
        # here, behind a counter, instead (holder.quarantine_invalid)
        merged.partition_samples, dropped_p = quarantine_invalid(
            merged.partition_samples)
        merged.broker_samples, dropped_b = quarantine_invalid(
            merged.broker_samples)
        if dropped_p or dropped_b:
            self.num_quarantined_samples += dropped_p + dropped_b
            LOG.warning(
                "ingest quarantine dropped %d partition and %d broker "
                "samples carrying NaN/Inf/negative values (%d total this "
                "process)", dropped_p, dropped_b,
                self.num_quarantined_samples)
        n_p = self._partition_aggregator.add_partition_samples(
            merged.partition_samples)
        n_b = self._broker_aggregator.add_broker_samples(
            merged.broker_samples)
        if self._sample_store is not None:
            try:
                faults.inject("monitor.sampler.store")
                self._sample_store.store_samples(merged)
            except Exception:  # noqa: BLE001 - store is a plugin
                LOG.exception("sample store failed to persist samples")
        self.last_sampling_ms = end_ms
        self.last_sampling_duration_s = time.time() - t0
        LOG.debug("sampling round accepted %d/%d partition and %d/%d broker "
                  "samples in %.2fs", n_p, len(merged.partition_samples),
                  n_b, len(merged.broker_samples),
                  self.last_sampling_duration_s)
        return merged

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)
        self._sampler.close()
