"""MetricSampler SPI + the simulated-cluster sampler.

Reference: CC/monitor/sampling/MetricSampler.java:1-92 — the pluggable
source of partition/broker metric samples, invoked by the fetcher threads
with an assigned partition set and a time range.  The default reference
implementation consumes the in-broker reporter's metrics topic
(CruiseControlMetricsReporterSampler.java:41-253); here the equivalent
default consumes a `MetricsChannel` fed by node agents
(cruise_control_tpu/agent), and `SimulatedClusterSampler` samples the
in-process simulated cluster directly.
"""
from __future__ import annotations

import abc
import dataclasses
import enum
from typing import List, Set

from cruise_control_tpu.cluster.simulated import SimulatedCluster
from cruise_control_tpu.cluster.types import ClusterSnapshot, TopicPartition
from cruise_control_tpu.model.builder import estimate_follower_cpu
from cruise_control_tpu.monitor import metricdef as MD
from cruise_control_tpu.monitor.sampling.holder import (
    BrokerMetricSample, PartitionMetricSample, complete_broker_values,
    complete_partition_values)


class SamplingMode(enum.Enum):
    """reference MetricSampler.SamplingMode"""

    ALL = "all"
    BROKER_METRICS_ONLY = "broker"
    PARTITION_METRICS_ONLY = "partition"


@dataclasses.dataclass
class Samples:
    """reference MetricSampler.Samples"""

    partition_samples: List[PartitionMetricSample] = dataclasses.field(
        default_factory=list)
    broker_samples: List[BrokerMetricSample] = dataclasses.field(
        default_factory=list)

    def merge(self, other: "Samples") -> None:
        self.partition_samples.extend(other.partition_samples)
        self.broker_samples.extend(other.broker_samples)


class MetricSampler(abc.ABC):
    """Pluggable metric source (reference MetricSampler.java:1-92)."""

    def configure(self, configs) -> None:  # pragma: no cover - plugin hook
        pass

    @abc.abstractmethod
    def get_samples(self, cluster: ClusterSnapshot,
                    assigned_partitions: Set[TopicPartition],
                    start_ms: float, end_ms: float,
                    mode: SamplingMode = SamplingMode.ALL) -> Samples:
        """Return samples for `assigned_partitions` (and their brokers)
        covering [start_ms, end_ms)."""

    def close(self) -> None:  # pragma: no cover - plugin hook
        pass


class NoopSampler(MetricSampler):
    """Returns no samples (reference NoopSampler)."""

    def get_samples(self, cluster, assigned_partitions, start_ms, end_ms,
                    mode=SamplingMode.ALL) -> Samples:
        return Samples()


class SimulatedClusterSampler(MetricSampler):
    """Samples a `SimulatedCluster`'s per-partition workload directly —
    the shortest path from simulated load to the monitor plane (used by
    integration tests and demos; the agent/channel path in
    cruise_control_tpu/agent is the production-shaped alternative)."""

    def __init__(self, cluster: SimulatedCluster,
                 cores_per_broker: float = 1.0):
        self._cluster = cluster
        self._cores = cores_per_broker
        cdef = MD.common_metric_def()
        self._cid = {name: cdef.metric_id(name) for name in
                     (MD.CPU_USAGE, MD.DISK_USAGE, MD.LEADER_BYTES_IN,
                      MD.LEADER_BYTES_OUT, MD.PRODUCE_RATE, MD.FETCH_RATE,
                      MD.MESSAGE_IN_RATE)}
        bdef = MD.broker_metric_def()
        self._bid = {name: bdef.metric_id(name) for name in
                     (MD.CPU_USAGE, MD.DISK_USAGE, MD.LEADER_BYTES_IN,
                      MD.LEADER_BYTES_OUT, MD.REPLICATION_BYTES_IN_RATE,
                      MD.REPLICATION_BYTES_OUT_RATE,
                      MD.BROKER_LOG_FLUSH_TIME_MS_999TH,
                      MD.BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT)}

    def get_samples(self, cluster: ClusterSnapshot,
                    assigned_partitions: Set[TopicPartition],
                    start_ms: float, end_ms: float,
                    mode: SamplingMode = SamplingMode.ALL) -> Samples:
        sim = self._cluster
        out = Samples()
        t = end_ms
        broker_cpu: dict = {}
        broker_bytes_in: dict = {}
        broker_bytes_out: dict = {}
        broker_repl_in: dict = {}
        broker_repl_out: dict = {}
        broker_disk: dict = {}

        # snapshot per-partition loads under the sim lock: sampling runs on
        # the load-monitor thread while tests/demos mutate the cluster
        with sim._lock:  # test-harness internal access
            loads = {tp: (part.leader_cpu, part.nw_in, part.nw_out,
                          part.size_bytes)
                     for tp, part in sim._partitions.items()}

        for pinfo in cluster.partitions:
            tp = pinfo.tp
            part_load = loads.get(tp)
            if part_load is None or pinfo.leader is None:
                continue
            leader = pinfo.leader
            leader_cpu, nw_in, nw_out, size_bytes = part_load
            n_followers = max(len(pinfo.replicas) - 1, 0)
            broker_cpu[leader] = broker_cpu.get(leader, 0.0) + leader_cpu
            broker_bytes_in[leader] = (broker_bytes_in.get(leader, 0.0)
                                       + nw_in)
            broker_bytes_out[leader] = (broker_bytes_out.get(leader, 0.0)
                                        + nw_out)
            for b in pinfo.replicas:
                broker_disk[b] = broker_disk.get(b, 0.0) + size_bytes
                if b != leader:
                    broker_repl_in[b] = (broker_repl_in.get(b, 0.0)
                                         + nw_in)
                    fcpu = estimate_follower_cpu(leader_cpu, nw_in, nw_out)
                    broker_cpu[b] = broker_cpu.get(b, 0.0) + fcpu
            broker_repl_out[leader] = (broker_repl_out.get(leader, 0.0)
                                       + nw_in * n_followers)

            if (mode != SamplingMode.BROKER_METRICS_ONLY
                    and tp in assigned_partitions):
                c = self._cid
                values = complete_partition_values({
                    c[MD.CPU_USAGE]: leader_cpu,
                    c[MD.DISK_USAGE]: size_bytes,
                    c[MD.LEADER_BYTES_IN]: nw_in,
                    c[MD.LEADER_BYTES_OUT]: nw_out,
                    c[MD.PRODUCE_RATE]: nw_in / 1024.0,
                    c[MD.FETCH_RATE]: nw_out / 1024.0,
                    c[MD.MESSAGE_IN_RATE]: nw_in / 512.0,
                })
                out.partition_samples.append(
                    PartitionMetricSample(leader, tp, t, values))

        if mode != SamplingMode.PARTITION_METRICS_ONLY:
            b = self._bid
            for binfo in cluster.brokers:
                if not binfo.alive:
                    continue
                bid = binfo.broker_id
                values = complete_broker_values({
                    b[MD.CPU_USAGE]: broker_cpu.get(bid, 0.0),
                    b[MD.DISK_USAGE]: broker_disk.get(bid, 0.0),
                    b[MD.LEADER_BYTES_IN]: broker_bytes_in.get(bid, 0.0),
                    b[MD.LEADER_BYTES_OUT]: broker_bytes_out.get(bid, 0.0),
                    b[MD.REPLICATION_BYTES_IN_RATE]:
                        broker_repl_in.get(bid, 0.0),
                    b[MD.REPLICATION_BYTES_OUT_RATE]:
                        broker_repl_out.get(bid, 0.0),
                    b[MD.BROKER_LOG_FLUSH_TIME_MS_999TH]: 1.0,
                    b[MD.BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT]: 0.9,
                })
                out.broker_samples.append(BrokerMetricSample(bid, t, values))
        return out
