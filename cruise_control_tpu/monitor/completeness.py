"""Model completeness requirements.

Reference: CC/monitor/ModelCompletenessRequirements.java:1-132 — every
operation declares how much metric history it needs before a cluster model
may be built from the aggregated samples; requirements combine by taking
the strictest value per field (`combine` == the reference's
stronger/weaker combination in MonitorUtils.combineLoadRequirementOptions).
"""
from __future__ import annotations

import dataclasses
from typing import Iterable, Optional


@dataclasses.dataclass(frozen=True)
class ModelCompletenessRequirements:
    min_required_num_windows: int = 1
    min_monitored_partitions_percentage: float = 0.0
    include_all_topics: bool = False

    def __post_init__(self):
        if self.min_required_num_windows < 1:
            raise ValueError("need at least one required window")
        if not 0.0 <= self.min_monitored_partitions_percentage <= 1.0:
            raise ValueError("partition percentage must be in [0, 1]")

    def combine(self, other: Optional["ModelCompletenessRequirements"]
                ) -> "ModelCompletenessRequirements":
        """Strictest-of-both (reference
        ModelCompletenessRequirements.stronger)."""
        if other is None:
            return self
        return ModelCompletenessRequirements(
            max(self.min_required_num_windows,
                other.min_required_num_windows),
            max(self.min_monitored_partitions_percentage,
                other.min_monitored_partitions_percentage),
            self.include_all_topics or other.include_all_topics)

    def weaker(self, other: Optional["ModelCompletenessRequirements"]
               ) -> "ModelCompletenessRequirements":
        """Loosest-of-both (reference weaker), used when any one of several
        goals being optimized would suffice."""
        if other is None:
            return self
        return ModelCompletenessRequirements(
            min(self.min_required_num_windows,
                other.min_required_num_windows),
            min(self.min_monitored_partitions_percentage,
                other.min_monitored_partitions_percentage),
            self.include_all_topics and other.include_all_topics)


def combined(requirements: Iterable[Optional[ModelCompletenessRequirements]]
             ) -> ModelCompletenessRequirements:
    out = ModelCompletenessRequirements()
    for r in requirements:
        if r is not None:
            out = out.combine(r)
    return out
