"""cruise_control_tpu — a TPU-native cluster-rebalancing framework.

A ground-up redesign of LinkedIn-style Cruise Control for Apache Kafka
(reference study: SURVEY.md): the cluster workload model is a device-resident
struct-of-arrays, goals are vectorized scoring/acceptance kernels, and
multi-goal proposal generation is a batched constrained-assignment search
under jit/vmap/pjit, wrapped by host-side monitoring, execution, anomaly
detection, and a REST API.
"""

__version__ = "0.1.0"
