"""Cluster admin SPI — the framework's act plane.

One abstract surface replaces the reference's split ZooKeeper/AdminClient
act plane: partition reassignment (reference ExecutorUtils.scala:31-93 wrote
reassignment znodes; the modern equivalent is the AdminClient
alterPartitionReassignments API targeted here), preferred-leader election
(ExecutorUtils.scala:95-101), intra-broker logdir moves
(CC/executor/ExecutorAdminUtils.java:1-124), replication throttles
(CC/executor/ReplicationThrottleHelper.java:1-256), logdir description
(CC/detector/DiskFailureDetector.java), topic configs
(CC/config/KafkaTopicConfigProvider.java), and liveness watching
(CC/detector/BrokerFailureDetector.java:85-90).
"""
from __future__ import annotations

import abc
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Set

from cruise_control_tpu.cluster.types import (ClusterSnapshot, LogDirInfo,
                                              ReassignmentState,
                                              TopicPartition)

#: liveness-watch callback: called with the new set of alive broker ids
LivenessListener = Callable[[Set[int]], None]


class ClusterAdminClient(abc.ABC):
    """Asynchronous-cluster admin operations.

    All mutating calls are *requests*: the cluster (simulated or real) acts
    on them over time; callers observe progress through `describe_cluster`
    and `list_partition_reassignments` polling, exactly as the reference's
    executor polls metadata (CC/executor/Executor.java:1169-1334).
    """

    # ---- observe ----
    @abc.abstractmethod
    def describe_cluster(self) -> ClusterSnapshot:
        """Current metadata snapshot."""

    @abc.abstractmethod
    def describe_log_dirs(self, broker_ids: Sequence[int]
                          ) -> Dict[int, List[LogDirInfo]]:
        """Per-broker logdir states (offline detection + JBOD capacity)."""

    @abc.abstractmethod
    def list_partition_reassignments(self) -> List[ReassignmentState]:
        """In-flight reassignments."""

    @abc.abstractmethod
    def topic_configs(self, topic: str) -> Mapping[str, str]:
        """Per-topic config map (e.g. min.insync.replicas)."""

    # ---- act ----
    @abc.abstractmethod
    def alter_partition_reassignments(
            self, targets: Mapping[TopicPartition,
                                   Optional[Sequence[int]]]) -> None:
        """Start (or, with value None, cancel) reassignments. Target lists
        are full desired replica orderings (leader candidate first)."""

    @abc.abstractmethod
    def elect_preferred_leaders(self, tps: Sequence[TopicPartition]) -> None:
        """Trigger preferred-leader election for the given partitions."""

    @abc.abstractmethod
    def alter_replica_log_dirs(
            self, moves: Mapping[TopicPartition, Mapping[int, str]]) -> None:
        """Move replicas between logdirs on the same broker (JBOD)."""

    @abc.abstractmethod
    def set_replication_throttle(self, broker_ids: Sequence[int],
                                 rate_bytes_per_s: float) -> None:
        """Apply leader+follower replication throttle to brokers."""

    @abc.abstractmethod
    def clear_replication_throttle(self, broker_ids: Sequence[int]) -> None:
        """Remove replication throttles set by this client."""

    # ---- watch ----
    @abc.abstractmethod
    def add_liveness_listener(self, listener: LivenessListener) -> None:
        """Subscribe to broker up/down transitions (reference ZK child watch
        on /brokers/ids)."""

    @abc.abstractmethod
    def remove_liveness_listener(self, listener: LivenessListener) -> None:
        """Unsubscribe."""

    def close(self) -> None:  # pragma: no cover - default no-op
        """Release resources."""


class TopicConfigProvider(abc.ABC):
    """SPI over per-topic config lookup (reference
    config/TopicConfigProvider.java, wired by
    `topic.config.provider.class`; the reference default reads configs
    from ZooKeeper — modernized here to the admin client)."""

    def configure(self, props) -> None:  # pragma: no cover - plugin hook
        """Config hook for get_configured_instance."""

    @abc.abstractmethod
    def topic_configs(self, topic: str) -> Mapping[str, str]:
        """Per-topic config map (e.g. min.insync.replicas)."""


class AdminTopicConfigProvider(TopicConfigProvider):
    """Default provider: delegates to the cluster admin client
    (reference KafkaTopicConfigProvider.java:1-105 behavioral
    equivalent)."""

    def __init__(self, admin: Optional[ClusterAdminClient] = None) -> None:
        self._admin = admin

    def bind(self, admin: ClusterAdminClient) -> None:
        """Late-bind the admin client (config-instantiated providers are
        constructed before the cluster connection exists)."""
        self._admin = admin

    def topic_configs(self, topic: str) -> Mapping[str, str]:
        if self._admin is None:
            raise RuntimeError("AdminTopicConfigProvider not bound to a "
                               "cluster admin client")
        return self._admin.topic_configs(topic)
