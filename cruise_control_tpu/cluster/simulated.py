"""In-process simulated cluster.

Plays the role the reference's embedded-Kafka integration harness plays in
its test strategy (reference cruise-control-metrics-reporter/src/test/...
/utils/CCKafkaIntegrationTestHarness.java boots real ZK + N KafkaServers in
one JVM; SURVEY.md §4.4): a full implementation of `ClusterAdminClient`
whose state actually *changes over time* — reassignments move data at a
finite (throttleable) rate, leadership elections occur, brokers die and
return, disks fail — so the executor's polling loop, the anomaly detectors'
watches, and end-to-end self-healing can be exercised without external
infrastructure.

Time is injectable (`time_fn`): tests may drive a virtual clock via
`advance()`, while demos run in wall-clock time.
"""
from __future__ import annotations

import itertools
import threading
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Set

from cruise_control_tpu.cluster.admin import (ClusterAdminClient,
                                              LivenessListener)
from cruise_control_tpu.cluster.types import (BrokerInfo, ClusterSnapshot,
                                              LogDirInfo, PartitionInfo,
                                              ReassignmentState,
                                              TopicPartition)


class _Partition:
    __slots__ = ("tp", "replicas", "leader", "logdir_by_broker", "size_bytes",
                 "leader_cpu", "nw_in", "nw_out", "target", "moved_bytes",
                 "move_total_bytes")

    def __init__(self, tp: TopicPartition, replicas: List[int],
                 leader: Optional[int], size_bytes: float):
        self.tp = tp
        self.replicas = list(replicas)
        self.leader = leader
        self.logdir_by_broker: Dict[int, str] = {}
        self.size_bytes = size_bytes
        self.leader_cpu = 0.0
        self.nw_in = 0.0
        self.nw_out = 0.0
        # in-flight reassignment
        self.target: Optional[List[int]] = None
        self.moved_bytes = 0.0
        self.move_total_bytes = 0.0


class _Broker:
    __slots__ = ("info_id", "host", "rack", "alive", "logdirs",
                 "offline_logdirs", "throttle")

    def __init__(self, broker_id: int, host: str, rack: Optional[str],
                 logdirs: Sequence[str]):
        self.info_id = broker_id
        self.host = host
        self.rack = rack
        self.alive = True
        self.logdirs = list(logdirs) or ["/data/d0"]
        self.offline_logdirs: Set[str] = set()
        self.throttle: Optional[float] = None


class SimulatedCluster(ClusterAdminClient):
    """Thread-safe simulated cluster with finite-rate data movement."""

    DEFAULT_MOVE_RATE = 100e6  # bytes/s replication rate when unthrottled

    def __init__(self, time_fn: Optional[Callable[[], float]] = None,
                 move_rate_bytes_per_s: float = DEFAULT_MOVE_RATE):
        self._lock = threading.RLock()
        self._brokers: Dict[int, _Broker] = {}
        self._partitions: Dict[TopicPartition, _Partition] = {}
        self._topic_configs: Dict[str, Dict[str, str]] = {}
        self._listeners: List[LivenessListener] = []
        self._generation = itertools.count(1)
        self._current_generation = 0
        self._move_rate = move_rate_bytes_per_s
        self._virtual_now: Optional[float] = 0.0 if time_fn is None else None
        self._time_fn = time_fn
        self._last_step = self._now()

    # ------------------------------------------------------------------
    # time
    # ------------------------------------------------------------------
    def _now(self) -> float:
        if self._time_fn is not None:
            return self._time_fn()
        return self._virtual_now or 0.0

    def now_ms(self) -> float:
        return self._now() * 1000.0

    def advance(self, seconds: float) -> None:
        """Advance the virtual clock (no-op effect when using a real
        time_fn) and progress in-flight work."""
        with self._lock:
            if self._virtual_now is not None:
                self._virtual_now += seconds
        self._step()

    # ------------------------------------------------------------------
    # topology construction (test/demo setup surface)
    # ------------------------------------------------------------------
    def add_broker(self, broker_id: int, rack: Optional[str] = None,
                   host: Optional[str] = None,
                   logdirs: Sequence[str] = ("/data/d0",)) -> None:
        with self._lock:
            self._brokers[broker_id] = _Broker(
                broker_id, host or f"host{broker_id}", rack, logdirs)
            self._bump()

    def create_topic(self, topic: str, assignments: Sequence[Sequence[int]],
                     size_bytes: float = 0.0,
                     configs: Optional[Mapping[str, str]] = None) -> None:
        """assignments[p] = replica list (index 0 = preferred leader)."""
        with self._lock:
            for p, replicas in enumerate(assignments):
                tp = TopicPartition(topic, p)
                part = _Partition(tp, list(replicas),
                                  replicas[0] if replicas else None,
                                  size_bytes)
                for b in replicas:
                    broker = self._brokers[b]
                    part.logdir_by_broker[b] = broker.logdirs[0]
                self._partitions[tp] = part
            if configs:
                self._topic_configs[topic] = dict(configs)
            self._bump()

    def set_partition_load(self, tp: TopicPartition, *, leader_cpu: float = 0.0,
                           nw_in: float = 0.0, nw_out: float = 0.0,
                           size_bytes: Optional[float] = None) -> None:
        with self._lock:
            part = self._partitions[tp]
            part.leader_cpu = leader_cpu
            part.nw_in = nw_in
            part.nw_out = nw_out
            if size_bytes is not None:
                part.size_bytes = size_bytes

    # ------------------------------------------------------------------
    # fault injection (reference tests kill embedded brokers;
    # ExecutorTest.java / BrokerFailureDetectorTest.java)
    # ------------------------------------------------------------------
    def kill_broker(self, broker_id: int) -> None:
        with self._lock:
            self._brokers[broker_id].alive = False
            for part in self._partitions.values():
                if part.leader == broker_id:
                    part.leader = next(
                        (b for b in part.replicas
                         if b != broker_id and self._brokers[b].alive), None)
            self._bump()
            alive = {b.info_id for b in self._brokers.values() if b.alive}
            listeners = list(self._listeners)
        for fn in listeners:
            fn(alive)

    def restart_broker(self, broker_id: int) -> None:
        with self._lock:
            self._brokers[broker_id].alive = True
            for part in self._partitions.values():
                if part.leader is None and any(
                        b == broker_id for b in part.replicas):
                    part.leader = broker_id
            self._bump()
            alive = {b.info_id for b in self._brokers.values() if b.alive}
            listeners = list(self._listeners)
        for fn in listeners:
            fn(alive)

    def fail_disk(self, broker_id: int, logdir: str) -> None:
        with self._lock:
            self._brokers[broker_id].offline_logdirs.add(logdir)
            self._bump()

    # ------------------------------------------------------------------
    # ClusterAdminClient — observe
    # ------------------------------------------------------------------
    def describe_cluster(self) -> ClusterSnapshot:
        self._step()
        with self._lock:
            brokers = tuple(
                BrokerInfo(b.info_id, b.host, b.rack, b.alive,
                           tuple(LogDirInfo(d, offline=d in b.offline_logdirs)
                                 for d in b.logdirs))
                for b in sorted(self._brokers.values(),
                                key=lambda x: x.info_id))
            partitions = []
            for part in self._partitions.values():
                offline = tuple(
                    b for b in part.replicas
                    if not self._brokers[b].alive
                    or part.logdir_by_broker.get(b)
                    in self._brokers[b].offline_logdirs)
                in_sync = tuple(b for b in part.replicas if b not in offline)
                partitions.append(PartitionInfo(
                    part.tp, part.leader, tuple(part.replicas), in_sync,
                    offline, dict(part.logdir_by_broker)))
            alive_ids = sorted(b.info_id for b in self._brokers.values()
                               if b.alive)
            return ClusterSnapshot(self._current_generation, brokers,
                                   tuple(partitions),
                                   alive_ids[0] if alive_ids else None)

    def describe_log_dirs(self, broker_ids: Sequence[int]
                          ) -> Dict[int, List[LogDirInfo]]:
        with self._lock:
            out: Dict[int, List[LogDirInfo]] = {}
            for bid in broker_ids:
                b = self._brokers.get(bid)
                if b is None or not b.alive:
                    continue
                used: Dict[str, float] = {d: 0.0 for d in b.logdirs}
                for part in self._partitions.values():
                    d = part.logdir_by_broker.get(bid)
                    if d in used:
                        used[d] += part.size_bytes
                out[bid] = [LogDirInfo(d, used_bytes=used[d],
                                       offline=d in b.offline_logdirs)
                            for d in b.logdirs]
            return out

    def list_partition_reassignments(self) -> List[ReassignmentState]:
        self._step()
        with self._lock:
            out = []
            for part in self._partitions.values():
                if part.target is None:
                    continue
                adding = tuple(b for b in part.target
                               if b not in part.replicas)
                removing = tuple(b for b in part.replicas
                                 if b not in part.target)
                out.append(ReassignmentState(part.tp, adding, removing,
                                             tuple(part.target)))
            return out

    def topic_configs(self, topic: str) -> Mapping[str, str]:
        with self._lock:
            return dict(self._topic_configs.get(topic, {}))

    # ------------------------------------------------------------------
    # ClusterAdminClient — act
    # ------------------------------------------------------------------
    def alter_partition_reassignments(
            self, targets: Mapping[TopicPartition,
                                   Optional[Sequence[int]]]) -> None:
        self._step()
        with self._lock:
            for tp, target in targets.items():
                part = self._partitions.get(tp)
                if part is None:
                    raise KeyError(f"unknown partition {tp}")
                if target is None:  # cancel
                    part.target = None
                    part.moved_bytes = part.move_total_bytes = 0.0
                    continue
                target = list(target)
                unknown = [b for b in target if b not in self._brokers]
                if unknown:
                    raise KeyError(f"unknown brokers {unknown} for {tp}")
                new = [b for b in target if b not in part.replicas]
                part.target = target
                part.moved_bytes = 0.0
                part.move_total_bytes = part.size_bytes * len(new)
                for b in new:
                    part.logdir_by_broker.setdefault(
                        b, self._brokers[b].logdirs[0])
                if not new:  # pure order change / shrink: instant
                    self._complete_move(part)
            self._bump()

    def elect_preferred_leaders(self, tps: Sequence[TopicPartition]) -> None:
        self._step()
        with self._lock:
            for tp in tps:
                part = self._partitions[tp]
                for b in part.replicas:
                    broker = self._brokers[b]
                    if broker.alive and part.logdir_by_broker.get(b) not in \
                            broker.offline_logdirs:
                        part.leader = b
                        break
            self._bump()

    def alter_replica_log_dirs(
            self, moves: Mapping[TopicPartition, Mapping[int, str]]) -> None:
        with self._lock:
            for tp, by_broker in moves.items():
                part = self._partitions[tp]
                for bid, logdir in by_broker.items():
                    if logdir not in self._brokers[bid].logdirs:
                        raise ValueError(
                            f"broker {bid} has no logdir {logdir}")
                    part.logdir_by_broker[bid] = logdir
            self._bump()

    def set_replication_throttle(self, broker_ids: Sequence[int],
                                 rate_bytes_per_s: float) -> None:
        with self._lock:
            for bid in broker_ids:
                self._brokers[bid].throttle = rate_bytes_per_s

    def clear_replication_throttle(self, broker_ids: Sequence[int]) -> None:
        with self._lock:
            for bid in broker_ids:
                self._brokers[bid].throttle = None

    # ------------------------------------------------------------------
    # ClusterAdminClient — watch
    # ------------------------------------------------------------------
    def add_liveness_listener(self, listener: LivenessListener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def remove_liveness_listener(self, listener: LivenessListener) -> None:
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    # ------------------------------------------------------------------
    # data-movement simulation
    # ------------------------------------------------------------------
    def _effective_rate(self, part: _Partition) -> float:
        rates = [self._move_rate]
        for b in (part.target or []):
            if b not in part.replicas:
                t = self._brokers[b].throttle
                if t is not None:
                    rates.append(t)
        return min(rates)

    def _complete_move(self, part: _Partition) -> None:
        assert part.target is not None
        part.replicas = list(part.target)
        part.target = None
        part.moved_bytes = part.move_total_bytes = 0.0
        for b in list(part.logdir_by_broker):
            if b not in part.replicas:
                del part.logdir_by_broker[b]
        if part.leader not in part.replicas or part.leader is None or \
                not self._brokers[part.leader].alive:
            part.leader = next(
                (b for b in part.replicas if self._brokers[b].alive), None)

    def _step(self) -> None:
        with self._lock:
            now = self._now()
            dt = max(0.0, now - self._last_step)
            self._last_step = now
            if dt == 0.0:
                return
            changed = False
            for part in self._partitions.values():
                if part.target is None:
                    continue
                # replication to a dead destination makes no progress
                if any(b not in self._brokers or not self._brokers[b].alive
                       for b in part.target if b not in part.replicas):
                    continue
                part.moved_bytes += self._effective_rate(part) * dt
                if part.moved_bytes >= part.move_total_bytes:
                    self._complete_move(part)
                    changed = True
            if changed:
                self._bump()

    def _bump(self) -> None:
        self._current_generation = next(self._generation)
