"""Cluster transport plane: metadata, admin SPI, simulated cluster.

The reference talks to its managed cluster over two control-plane backends —
the Kafka protocol (metadata refresh, AdminClient operations, consumers for
metric topics) and ZooKeeper (reassignment znodes, liveness watches,
preferred-leader election, throttle configs); see reference
cruise-control/src/main/java/com/linkedin/kafka/cruisecontrol/common/
MetadataClient.java and .../executor/ExecutorUtils.scala.  This package is
the framework's equivalent transport plane, reduced to one asynchronous
`ClusterAdminClient` SPI (the modern AdminClient-era surface) plus a cached
`MetadataClient` and an in-process `SimulatedCluster` that plays the role of
the reference's embedded-Kafka integration harness
(cruise-control-metrics-reporter/src/test/.../CCKafkaIntegrationTestHarness.java).
"""
from cruise_control_tpu.cluster.types import (BrokerInfo, ClusterSnapshot,
                                              LogDirInfo, PartitionInfo,
                                              ReassignmentState, TopicPartition)
from cruise_control_tpu.cluster.admin import ClusterAdminClient
from cruise_control_tpu.cluster.metadata import MetadataClient
from cruise_control_tpu.cluster.simulated import SimulatedCluster

__all__ = [
    "BrokerInfo", "ClusterSnapshot", "LogDirInfo", "PartitionInfo",
    "ReassignmentState", "TopicPartition", "ClusterAdminClient",
    "MetadataClient", "SimulatedCluster",
]
