"""TTL-cached cluster metadata with a generation counter.

Reference: CC/common/MetadataClient.java:1-171 — wraps the Kafka Metadata
object, refreshes when stale, and exposes a `clusterGeneration` so the
LoadMonitor/GoalOptimizer can key model/proposal caches on metadata change.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from cruise_control_tpu.cluster.admin import ClusterAdminClient
from cruise_control_tpu.cluster.types import ClusterSnapshot


class MetadataClient:
    """Caches `ClusterSnapshot`s from a `ClusterAdminClient` with a TTL."""

    def __init__(self, admin: ClusterAdminClient,
                 metadata_ttl_ms: float = 5_000.0,
                 time_fn: Callable[[], float] = time.time):
        self._admin = admin
        self._ttl_s = metadata_ttl_ms / 1000.0
        self._time_fn = time_fn
        self._lock = threading.Lock()
        self._snapshot: Optional[ClusterSnapshot] = None
        self._fetched_at = -float("inf")

    def cluster(self) -> ClusterSnapshot:
        """Possibly-stale snapshot (refreshes if past TTL)."""
        with self._lock:
            if (self._snapshot is None
                    or self._time_fn() - self._fetched_at > self._ttl_s):
                self._refresh_locked()
            return self._snapshot

    def refresh_metadata(self) -> ClusterSnapshot:
        """Force a refresh (reference MetadataClient.refreshMetadata)."""
        with self._lock:
            self._refresh_locked()
            return self._snapshot

    @property
    def cluster_generation(self) -> int:
        return self.cluster().generation

    def _refresh_locked(self) -> None:
        self._snapshot = self._admin.describe_cluster()
        self._fetched_at = self._time_fn()
