"""Value types describing the managed cluster.

These are the framework's wire-free analogs of the Kafka metadata objects the
reference consumes (org.apache.kafka.common.Cluster / Node / PartitionInfo as
used in reference CC/common/MetadataClient.java and
CC/monitor/MonitorUtils.java).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Mapping, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True, order=True)
class TopicPartition:
    """(topic, partition) id — reference org.apache.kafka.common.TopicPartition."""

    topic: str
    partition: int

    def __str__(self) -> str:
        return f"{self.topic}-{self.partition}"


@dataclasses.dataclass(frozen=True)
class LogDirInfo:
    """One logdir on a broker (JBOD disk).

    Mirrors what the reference learns from AdminClient.describeLogDirs
    (CC/detector/DiskFailureDetector.java:1-123)."""

    path: str
    capacity_bytes: float = 0.0
    used_bytes: float = 0.0
    offline: bool = False


@dataclasses.dataclass(frozen=True)
class BrokerInfo:
    """Broker endpoint + placement (reference kafka Node + rack)."""

    broker_id: int
    host: str = "localhost"
    rack: Optional[str] = None
    alive: bool = True
    logdirs: Tuple[LogDirInfo, ...] = ()


@dataclasses.dataclass(frozen=True)
class PartitionInfo:
    """Replica list (leader first position is NOT implied; `leader` is
    explicit), in-sync set, and per-replica logdir placement."""

    tp: TopicPartition
    leader: Optional[int]
    replicas: Tuple[int, ...]
    in_sync: Tuple[int, ...] = ()
    offline_replicas: Tuple[int, ...] = ()
    # broker id -> logdir path for that broker's replica
    logdir_by_broker: Mapping[int, str] = dataclasses.field(
        default_factory=dict)

    @property
    def size_bytes(self) -> float:  # filled by monitors when known
        return 0.0


@dataclasses.dataclass(frozen=True)
class ReassignmentState:
    """An in-flight partition reassignment (reference
    Executor.hasOngoingPartitionReassignments, CC/executor/Executor.java:687)."""

    tp: TopicPartition
    adding_replicas: Tuple[int, ...]
    removing_replicas: Tuple[int, ...]
    target_replicas: Tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class ClusterSnapshot:
    """Point-in-time cluster metadata with a monotonically increasing
    generation (reference MetadataClient keeps (metadata, generation);
    CC/common/MetadataClient.java:1-171)."""

    generation: int
    brokers: Tuple[BrokerInfo, ...]
    partitions: Tuple[PartitionInfo, ...]
    controller_id: Optional[int] = None

    # ---- queries used throughout the monitor/executor planes ----
    def broker(self, broker_id: int) -> Optional[BrokerInfo]:
        for b in self.brokers:
            if b.broker_id == broker_id:
                return b
        return None

    @property
    def alive_broker_ids(self) -> FrozenSet[int]:
        return frozenset(b.broker_id for b in self.brokers if b.alive)

    @property
    def all_broker_ids(self) -> FrozenSet[int]:
        return frozenset(b.broker_id for b in self.brokers)

    @property
    def topics(self) -> FrozenSet[str]:
        return frozenset(p.tp.topic for p in self.partitions)

    def partition(self, tp: TopicPartition) -> Optional[PartitionInfo]:
        for p in self.partitions:
            if p.tp == tp:
                return p
        return None

    def partitions_of(self, topic: str) -> List[PartitionInfo]:
        return [p for p in self.partitions if p.tp.topic == topic]

    def partitions_with_offline_replicas(self) -> List[PartitionInfo]:
        return [p for p in self.partitions if p.offline_replicas]

    def replica_count(self) -> int:
        return sum(len(p.replicas) for p in self.partitions)


def partitions_by_index(partitions: Sequence[PartitionInfo]
                        ) -> Dict[TopicPartition, PartitionInfo]:
    return {p.tp: p for p in partitions}
