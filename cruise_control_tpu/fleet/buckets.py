"""Shape buckets: pad every tenant's model to power-of-two geometry so
tenants share compiled device programs.

One compiled fused-pipeline program exists per (array shapes, goal list)
— that is how XLA works and how the process-wide program cache
(analyzer/optimizer._SHARED_PROGRAMS, scenario/engine program LRU) is
keyed.  K tenants with K slightly-different cluster sizes would compile
K copies of every program; padding each tenant's `ClusterState` up to
the next power-of-two bucket on every axis makes tenants of similar size
land on ONE shape, so the first tenant in a bucket pays the compile and
the rest reuse it (the sublinear-compile-count claim bench.py
BENCH_CONFIG=fleet measures).

Padding follows THE dead-row convention of `parallel/mesh.DEAD_ROW_FILLS`
(shared with the replica-axis mesh padding and the scenario compiler's
broker padding, so the three padders cannot drift): padded brokers are
dead with zero capacity, padded replicas are invalid and weightless,
padded partitions own no replicas, padded disks are dead.  Every goal
and statistic masks on aliveness/validity, so a bucket-padded solve
returns results identical to the unpadded solve (pinned in
tests/test_fleet.py: bucket-padding no-leak pin).

The static axes (racks, hosts, topics) bucket too — they are static
fields of the state pytree, and two states whose static fields differ
can never share a program.  Extra racks/hosts/topics are simply empty.

`BucketIndex` is the fleet-wide accountant: it tracks which (bucket,
goal-list) combos exist, meters `fleet-bucket-compiles` when a NEW combo
appears (each one is a full pipeline compile somewhere downstream — the
operator's bucket-explosion alarm), and LRU-bounds its tracking table.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
from collections import OrderedDict
from typing import Callable, Optional, Tuple

from cruise_control_tpu.model.state import ClusterState
from cruise_control_tpu.parallel.mesh import (pad_broker_axis,
                                              pad_disk_axis,
                                              pad_partition_axis,
                                              pad_replica_axis)

LOG = logging.getLogger(__name__)

#: smallest bucket edge: clusters below this pad up to it, so tiny
#: tenants (3 vs 5 brokers) land in one bucket instead of two
DEFAULT_BUCKET_FLOOR = 8


def next_pow2(n: int, floor: int = 1) -> int:
    """Smallest power of two >= max(n, floor)."""
    target = max(int(n), int(floor), 1)
    return 1 << (target - 1).bit_length()


@dataclasses.dataclass(frozen=True)
class FleetBucket:
    """One shape bucket: the padded geometry every tenant inside it
    shares.  Hashable — it IS the program-sharing key (joined with the
    goal list by the BucketIndex)."""

    brokers: int
    replicas: int
    partitions: int
    disks: int           #: 0/1 disk axes stay as-is (the JBOD dummy)
    racks: int
    hosts: int
    topics: int

    def signature(self) -> Tuple[int, ...]:
        return (self.brokers, self.replicas, self.partitions, self.disks,
                self.racks, self.hosts, self.topics)

    def to_json(self) -> dict:
        return {"brokers": self.brokers, "replicas": self.replicas,
                "partitions": self.partitions, "disks": self.disks,
                "racks": self.racks, "hosts": self.hosts,
                "topics": self.topics}


def bucket_of(state: ClusterState,
              floor: int = DEFAULT_BUCKET_FLOOR) -> FleetBucket:
    """The power-of-two bucket `state` belongs to.  The disk axis only
    buckets when JBOD is actually modeled (num_disks > 1): the D == 1
    dummy axis must stay width 1, or every non-JBOD tenant would pay a
    phantom JBOD table."""
    return FleetBucket(
        brokers=next_pow2(state.num_brokers, floor),
        replicas=next_pow2(state.num_replicas, floor),
        partitions=next_pow2(state.num_partitions, floor),
        disks=(next_pow2(state.num_disks, floor)
               if state.num_disks > 1 else state.num_disks),
        racks=next_pow2(state.num_racks),
        hosts=next_pow2(state.num_hosts),
        topics=next_pow2(state.num_topics),
    )


def pad_state_to_bucket(state: ClusterState,
                        bucket: FleetBucket) -> ClusterState:
    """`state` padded up to `bucket` on every axis (dead-row convention;
    see module docstring).  A state already at the bucket shape is
    returned unchanged — the identity the single-tenant byte-identical
    pin relies on when no fleet is configured is that this function is
    never called at all."""
    padded = pad_replica_axis(state, bucket.replicas)
    padded = pad_partition_axis(padded, bucket.partitions)
    padded = pad_broker_axis(padded, bucket.brokers)
    if bucket.disks > state.num_disks:
        padded = pad_disk_axis(padded, bucket.disks)
    if (bucket.racks != state.num_racks or bucket.hosts != state.num_hosts
            or bucket.topics != state.num_topics):
        padded = padded.replace(num_racks=bucket.racks,
                                num_hosts=bucket.hosts,
                                num_topics=bucket.topics)
    return padded


class BucketIndex:
    """Fleet-wide (bucket, goal-list) accounting with an LRU cap.

    `observe(state, goal_key)` returns the bucket and marks
    `fleet-bucket-compiles` whenever the combo is NEW — each new combo
    means a full pipeline compile somewhere downstream (the optimizer's
    shared program cache / the scenario engine LRU key on exactly these
    shapes), so the meter's rate is the operator's signal that tenant
    geometry is too diverse for the configured floor (docs/FLEET.md
    "bucket explosion").  The cap bounds the TRACKING table only; it
    cannot evict XLA executables, so crossing it logs a warning instead
    of silently rolling over."""

    def __init__(self, floor: int = DEFAULT_BUCKET_FLOOR,
                 max_tracked: int = 64, metrics=None) -> None:
        self.floor = max(1, int(floor))
        self.max_tracked = max(1, int(max_tracked))
        self._metrics = metrics
        self._lock = threading.Lock()
        #: (bucket signature, goal key) -> solve count, LRU-ordered
        self._combos: "OrderedDict[tuple, int]" = OrderedDict()
        self.total_combos = 0          #: lifetime distinct combos seen
        self._warned_cap = False

    def attach_metrics(self, registry) -> None:
        self._metrics = registry

    def bucket_for(self, state: ClusterState) -> FleetBucket:
        return bucket_of(state, self.floor)

    def pad(self, state: ClusterState) -> ClusterState:
        return pad_state_to_bucket(state, self.bucket_for(state))

    def observe(self, state: ClusterState,
                goal_key: Optional[tuple]) -> FleetBucket:
        """Record one solve landing in `state`'s bucket under
        `goal_key` (the optimizer's goals-share key; callers whose goal
        list cannot share programs pass a per-tenant surrogate key —
        FleetBinding.pad_state — so unshareable compiles meter once per
        tenant, not once fleet-wide)."""
        bucket = self.bucket_for(state)
        key = (bucket.signature(), goal_key)
        with self._lock:
            if key in self._combos:
                self._combos[key] += 1
                self._combos.move_to_end(key)
                return bucket
            self.total_combos += 1
            self._combos[key] = 1
            if len(self._combos) > self.max_tracked:
                evicted, _ = self._combos.popitem(last=False)
                if not self._warned_cap:
                    self._warned_cap = True
                    LOG.warning(
                        "fleet bucket/goal combos exceed the tracking cap "
                        "(%d): tenant geometry is too diverse to share "
                        "programs — raise fleet.bucket.floor or expect "
                        "one compile per tenant (first evicted: %r)",
                        self.max_tracked, evicted)
        if self._metrics is not None:
            self._metrics.meter("fleet-bucket-compiles").mark()
        return bucket

    def to_json(self) -> dict:
        with self._lock:
            return {
                "bucketFloor": self.floor,
                "trackedCombos": len(self._combos),
                "totalCombos": self.total_combos,
                "maxTracked": self.max_tracked,
            }


#: type of the facade's state padder hook (fleet binding installs
#: BucketIndex.pad here; None = no padding, the pre-fleet path)
StatePadder = Callable[[ClusterState], ClusterState]
