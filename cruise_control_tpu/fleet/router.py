"""Cross-tenant solve router: batch compatible queued solves from
DIFFERENT tenants into one vmapped device dispatch.

PR 4's scheduler already folds compatible SCENARIO_SWEEP jobs; the fleet
extends the same fold hook to the bread-and-butter request-path solves:
when the dispatch loop pops a tenant's proposal solve and finds other
tenants' solves queued with the same fold key (same goal list, same
options, fold-eligible), all of them hand their payloads to
`FleetRouter.fold_run`, which

1. materializes each tenant's bucket-padded model (fleet/buckets.py —
   same bucket => same array shapes),
2. groups lanes whose pytree structure/shapes/static fields actually
   match (the fold key is necessary but not sufficient: rf_max or
   max_replicas_per_broker can differ per tenant config overlay),
3. stacks each group into a `CompiledBatch` with
   ``shared_membership=False`` — unlike a scenario batch, every lane is
   a DIFFERENT base model, so the engine fetches the full [K, R]
   initial placement planes and diffs each lane against its own
   membership (scenario/compiler.py groundwork),
4. runs the group through the scenario engine's batched fused pipeline
   (one compile amortized across tenants, `fleet-folded-solves` meter),
   and
5. splits the outcomes back per tenant as `OptimizerResult`s; a lane's
   solver verdict (hard-goal violation, regression, invalid input)
   fails ONLY that tenant's ticket (`FoldedFailure`).

Isolation: the router owns NO ladder and touches NO tenant ladder.  If
the batched dispatch itself fails (compile error, device fault, OOM the
halving cannot fix), the router falls back to running every payload's
inline solve individually — each tenant's own PR-2 degradation ladder
then classifies ITS failure, so a fault injected into one tenant's solve
degrades one rung in one tenant (tenant-isolation chaos pin,
tests/test_fleet.py).

Folded results carry PER-LANE final states: the engine's fetched final
placement planes are split back per lane (`ScenarioOutcome.
final_placement`) and re-attached to each lane's own bucket-padded
input state, so a folded solve seeds the tenant's warm start exactly
like its inline solve would have.  The facade tags every stored seed
with (tenant scope, model generation) — a seed can never warm-start a
different tenant or a generation it did not see (facade._warm_seed).
"""
from __future__ import annotations

import contextlib
import dataclasses
import logging
import threading
from typing import Any, Callable, List, Optional, Tuple

from cruise_control_tpu.analyzer.context import (make_context,
                                                 partition_replica_index)
from cruise_control_tpu.analyzer.degradation import InvalidModelInputError
from cruise_control_tpu.analyzer.goals.base import OptimizationFailure
from cruise_control_tpu.analyzer.optimizer import OptimizerResult
from cruise_control_tpu.scenario.compiler import CompiledBatch
from cruise_control_tpu.scenario.engine import (ScenarioEngine,
                                                ScenarioOutcome)
from cruise_control_tpu.scenario.spec import ScenarioSpec
from cruise_control_tpu.sched.runtime import SolvePreempted, shielded
from cruise_control_tpu.sched.scheduler import FoldedFailure

LOG = logging.getLogger(__name__)


@dataclasses.dataclass
class FleetSolvePayload:
    """One tenant's request-path solve, offered to the cross-tenant
    fold.  `materialize` returns (bucket-padded state, topology,
    generated options) for THIS solve; `run_inline` is the tenant's full
    single-solve path (degradation ladder included) used when the job
    dispatches alone or the batched path fails; `commit` stores a folded
    result into the tenant's proposal cache exactly like the inline path
    would have."""

    tenant_id: str
    optimizer: Any                                  #: GoalOptimizer
    constraint: Any                                 #: BalancingConstraint
    balancedness_weights: Tuple[float, float]
    materialize: Callable[[], tuple]
    run_inline: Callable[[], OptimizerResult]
    commit: Callable[[OptimizerResult], None]
    #: False while the tenant's degradation ladder is off the FUSED
    #: rung: a degraded tenant must keep its pinned rung (EAGER/CPU)
    #: instead of riding a fused cross-tenant batch
    fused_ok: Callable[[], bool] = lambda: True


class FleetRouter:
    """See module docstring.  One per fleet; stateless apart from the
    shared program cache (the engine's LRU) and telemetry counters —
    tenant state lives in the registry only (lint-enforced)."""

    def __init__(self, metrics=None, max_group: int = 8,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        import time as _t
        self._metrics = metrics
        self.max_group = max(1, max_group)
        self._time = time_fn or _t.time
        # program-cache host only: the router always passes the
        # optimizer explicitly (solve_compiled), so the engine's
        # evaluate()-path factory must never be consulted
        self._engine = ScenarioEngine(
            _no_factory, max_batch_size=self.max_group,
            time_fn=self._time)
        self._lock = threading.Lock()
        self.total_folded = 0        #: solves served from a shared batch
        self.total_fold_batches = 0
        self.total_fallbacks = 0     #: batched failures -> inline retries

    # ------------------------------------------------------------------
    def fold_run(self, payloads: List[FleetSolvePayload]) -> List[Any]:
        """The scheduler's fold entry point: one result (or
        FoldedFailure) per payload, in order."""
        if len(payloads) == 1:
            return [payloads[0].run_inline()]
        lanes = []
        for p in payloads:
            if not p.fused_ok():
                # the tenant's ladder is pinned below FUSED: its solve
                # runs inline on its own rung, never in a fused batch
                lanes.append((p, None, None, None, None))
                continue
            try:
                state, topo, options = p.materialize()
                ctx = make_context(state, p.constraint, options, topo)
                lanes.append((p, state, topo, options, ctx))
            except Exception as exc:  # noqa: BLE001 - lane-local failure
                LOG.warning("fleet fold: materialize failed for tenant "
                            "%r: %s", p.tenant_id, exc)
                lanes.append((p, None, None, None, None))
        groups: dict = {}
        order: List[tuple] = []
        for lane in lanes:
            if lane[1] is None:
                order.append(("solo", lane))
                continue
            key = self._lane_group_key(lane)
            if key is None:
                order.append(("solo", lane))
                continue
            if key not in groups:
                groups[key] = []
                order.append(("group", key))
            groups[key].append(lane)

        results: dict = {}
        #: completed work units.  A fold spanning several groups commits
        #: results as each group finishes; once ANY unit is done, a
        #: SolvePreempted would make the scheduler re-queue (and re-run)
        #: finished work, so every later unit runs with the preemption
        #: checkpoint shielded — only the FIRST unit may yield cleanly.
        done: List[bool] = []

        def shield():
            return shielded() if done else contextlib.nullcontext()

        for kind, ref in order:
            chunks = ([[ref]] if kind == "solo"
                      else [groups[ref][i:i + self.max_group]
                            for i in range(0, len(groups[ref]),
                                           self.max_group)])
            for chunk in chunks:
                with shield():
                    if len(chunk) == 1:
                        results[id(chunk[0][0])] = \
                            self._run_one(chunk[0][0])
                    else:
                        for payload, result in self._run_group(chunk,
                                                               done):
                            results[id(payload)] = result
                done.append(True)
        return [results[id(p)] for p in payloads]

    # ------------------------------------------------------------------
    def _lane_group_key(self, lane) -> Optional[tuple]:
        """Lanes may stack only when state AND context agree in pytree
        structure (static fields included), shapes and dtypes —
        table_slots excluded (unified to the group max before
        stacking)."""
        import jax
        payload, state, _topo, _options, ctx = lane
        try:
            s_leaves, s_def = jax.tree.flatten(state)
            c_leaves, c_def = jax.tree.flatten(
                dataclasses.replace(ctx, table_slots=0))
            return (s_def,
                    tuple((x.shape, str(x.dtype)) for x in s_leaves),
                    c_def,
                    tuple((x.shape, str(x.dtype)) for x in c_leaves),
                    payload.optimizer.pipeline_segment_size)
        except Exception as exc:  # noqa: BLE001 - ungroupable lane runs
            # alone rather than poisoning the batch
            LOG.warning("fleet fold: lane for tenant %r not groupable "
                        "(%s); running it alone", payload.tenant_id, exc)
            return None

    def _run_one(self, payload: FleetSolvePayload):
        try:
            return payload.run_inline()
        except SolvePreempted:
            raise
        except BaseException as exc:  # noqa: BLE001 - fail ONE ticket
            return FoldedFailure(exc)

    def _run_group(self, group, done: List[bool]) -> List[tuple]:
        """One batched dispatch for `group`; per-payload results.  Any
        batched failure (except preemption) falls back to per-tenant
        inline solves so one tenant's fault cannot fail its peers."""
        payloads = [lane[0] for lane in group]
        try:
            batch = self._build_batch(group)
            telemetry = self._engine.solve_compiled(
                payloads[0].optimizer, batch, include_proposals=True)
        except SolvePreempted:
            raise
        except Exception as exc:  # noqa: BLE001 - isolation fallback
            with self._lock:
                self.total_fallbacks += 1
            if self._metrics is not None:
                self._metrics.meter("fleet-fold-fallbacks").mark()
            LOG.warning(
                "fleet fold of %d tenants (%s) failed batched (%s: %s); "
                "falling back to per-tenant inline solves",
                len(payloads), [p.tenant_id for p in payloads],
                type(exc).__name__, exc)
            out = []
            for p in payloads:
                # same completed-work rule as fold_run: after the first
                # inline result, a preemption would discard it
                with (shielded() if (done or out)
                      else contextlib.nullcontext()):
                    out.append((p, self._run_one(p)))
            return out
        with self._lock:
            self.total_folded += len(payloads)
            self.total_fold_batches += 1
        if self._metrics is not None:
            self._metrics.meter("fleet-folded-solves").mark(len(payloads))
        out = []
        for lane, outcome in zip(group, telemetry.outcomes):
            payload = lane[0]
            try:
                result = self._result_from_outcome(payload, outcome,
                                                   telemetry.duration_s,
                                                   lane_state=lane[1])
                payload.commit(result)
                out.append((payload, result))
            except BaseException as exc:  # noqa: BLE001 - one lane's
                # verdict fails one ticket
                out.append((payload, FoldedFailure(exc)))
        return out

    def _build_batch(self, group) -> CompiledBatch:
        specs, states, contexts, topologies, rows_per = [], [], [], [], []
        slots = max(lane[4].table_slots for lane in group)
        for payload, state, topo, _options, ctx in group:
            specs.append(ScenarioSpec(name=f"fleet:{payload.tenant_id}"))
            states.append(state)
            contexts.append(ctx if ctx.table_slots == slots
                            else dataclasses.replace(ctx,
                                                     table_slots=slots))
            topologies.append(topo)
            rows_per.append(partition_replica_index(
                state, rf_max=ctx.rf_max))
        return CompiledBatch(
            specs=specs, states=states, contexts=contexts,
            topologies=topologies, num_brokers=states[0].num_brokers,
            partition_rows=rows_per[0],
            shared_membership=False, partition_rows_per=rows_per)

    def _result_from_outcome(self, payload: FleetSolvePayload,
                             outcome: ScenarioOutcome,
                             duration_s: float,
                             lane_state=None) -> OptimizerResult:
        """One lane's ScenarioOutcome as the OptimizerResult the inline
        path would have returned.  Lane VERDICTS re-raise exactly like
        the single-solve path raises them (the batched engine reports
        them as infeasibility so one doomed lane cannot poison the
        batch; here each lane has its own ticket to fail).

        `lane_state` (the lane's bucket-padded INPUT state) plus the
        outcome's fetched final placement reconstruct this lane's final
        ClusterState: membership/topology/capacity are solve-invariant,
        only the placement planes moved — exactly the fields a warm
        start transplants (GoalOptimizer.optimizations warm_start) and
        the compatibility gate reads (facade._warm_start_compatible),
        so the rebuilt seed behaves identically to an inline final
        state."""
        if not outcome.feasible:
            if outcome.invalid_input:
                raise InvalidModelInputError(outcome.reason)
            raise OptimizationFailure(outcome.reason)
        final_state = None
        if lane_state is not None and outcome.final_placement is not None:
            import jax.numpy as jnp
            fp = outcome.final_placement
            final_state = lane_state.replace(
                replica_broker=jnp.asarray(fp["replica_broker"]),
                replica_is_leader=jnp.asarray(fp["replica_is_leader"]),
                **({"replica_disk": jnp.asarray(fp["replica_disk"])}
                   if "replica_disk" in fp else {}))
        goals = payload.optimizer.goals
        return OptimizerResult(
            proposals=list(outcome.proposals),
            stats_before=outcome.stats_before,
            stats_after=outcome.stats_after,
            stats_by_goal=dict(outcome.stats_by_goal),
            violated_goals_before=list(outcome.violated_goals_before),
            violated_goals_after=list(outcome.violated_goals_after),
            regressed_goals=list(outcome.regressed_goals),
            final_state=final_state,
            duration_s=duration_s,
            violated_broker_counts=dict(outcome.violated_broker_counts),
            entry_broker_counts=dict(outcome.entry_broker_counts),
            rounds_by_goal=dict(outcome.rounds_by_goal),
            converged_at_by_goal=dict(outcome.converged_at_by_goal),
            hard_goal_names=frozenset(g.name for g in goals
                                      if g.is_hard),
            balancedness_weights=payload.balancedness_weights)

    # ------------------------------------------------------------------
    def to_json(self) -> dict:
        with self._lock:
            return {
                "totalFoldedSolves": self.total_folded,
                "totalFoldBatches": self.total_fold_batches,
                "totalFallbacks": self.total_fallbacks,
                "maxGroup": self.max_group,
            }


def _no_factory(names):
    raise RuntimeError(
        "the fleet router's engine is program-cache host only; solves "
        "always pass their optimizer explicitly (solve_compiled)")
