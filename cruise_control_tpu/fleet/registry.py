"""Fleet registry: the ONE root object holding every tenant.

A *tenant* is one Kafka cluster served by this process: its own admin
client, LoadMonitor, detector wiring, degradation ladder, proposal cache
and config overlay — i.e. a full `CruiseControl` facade — while the
expensive substrate is SHARED across the fleet: one device, one PR-4
device-time scheduler (every tenant's solves queue through the same
priority/coalescing/backpressure gateway), one bucket index (shape
buckets so tenants share compiled programs, fleet/buckets.py) and one
router (cross-tenant batched dispatch, fleet/router.py).

Isolation contract (pinned in tests/test_fleet.py): per-tenant state is
reachable ONLY through this registry — tools/lint.py forbids mutable
module-level state in fleet/ so no tenant data can leak into process
globals — and each tenant keeps its own ladder/breaker/caches, so one
tenant's faults or OOM halvings never move another tenant's rung.

Lifecycle: `register` adds a tenant (the facade must have been built
with this registry's `binding_for(cluster_id)` and `scheduler`);
`drain` stops admitting new mutating work while reads and in-flight
solves finish; `unregister` shuts the drained tenant's facade down
(monitor, detectors, executor — NOT the shared scheduler) and removes
it.  The default tenant serves every request that names no `?cluster=`
and cannot be drained or unregistered while other tenants exist.
"""
from __future__ import annotations

import dataclasses
import enum
import logging
import threading
import time as _time
from typing import Callable, Dict, List, Optional

from cruise_control_tpu.fleet.buckets import (DEFAULT_BUCKET_FLOOR,
                                              BucketIndex)
from cruise_control_tpu.fleet.router import FleetRouter
from cruise_control_tpu.utils.metrics import MetricRegistry

LOG = logging.getLogger(__name__)


class UnknownTenantError(KeyError):
    """No such cluster registered — the REST layer answers 404."""

    def __init__(self, cluster_id: str, known: List[str]) -> None:
        super().__init__(
            f"unknown cluster {cluster_id!r}; registered: "
            f"{sorted(known) or '[]'}")
        self.cluster_id = cluster_id


class TenantDrainingError(RuntimeError):
    """The tenant is draining: no new mutating work is admitted — the
    REST layer answers 503 so clients fail over."""

    def __init__(self, cluster_id: str) -> None:
        super().__init__(f"cluster {cluster_id!r} is draining; no new "
                         f"operations are admitted")
        self.cluster_id = cluster_id


class TenantStatus(enum.Enum):
    ACTIVE = "ACTIVE"
    DRAINING = "DRAINING"


@dataclasses.dataclass
class Tenant:
    """One registered cluster."""

    cluster_id: str
    facade: object                       #: CruiseControl
    status: TenantStatus = TenantStatus.ACTIVE
    registered_at: float = 0.0

    def to_json(self, default_id: Optional[str] = None) -> dict:
        return {
            "clusterId": self.cluster_id,
            "status": self.status.value,
            "isDefault": self.cluster_id == default_id,
            "registeredAtMs": int(self.registered_at * 1000.0),
        }


@dataclasses.dataclass
class FleetBinding:
    """What a tenant facade holds of the fleet: its identity plus the
    shared bucket index and router.  The facade uses it to (a) pad every
    solve's model to the shape bucket and (b) offer compatible solves to
    the cross-tenant fold.  It deliberately does NOT expose other
    tenants — the registry is the only tenant root."""

    tenant_id: str
    buckets: BucketIndex
    router: Optional[FleetRouter] = None

    def pad_state(self, state, goal_key=None):
        """Bucket-pad one solve's ClusterState, accounting the (bucket,
        goal-list) combo in the fleet-bucket-compiles meter.  A None
        goal key means the goal list cannot share programs across
        tenants (non-primitive goal state, scenario goal overrides), so
        the compile it stands for is per-tenant: it is tracked under a
        per-tenant surrogate key — K tenants on unshareable goals meter
        as K combos, not one."""
        if goal_key is None:
            goal_key = ("unshared", self.tenant_id)
        self.buckets.observe(state, goal_key)
        return self.buckets.pad(state)


class FleetRegistry:
    """See module docstring.  Construction order in main.py:

        registry = FleetRegistry(scheduler=shared_scheduler, ...)
        cc = build_cruise_control(tenant_config, admin,
                                  solve_scheduler=registry.scheduler,
                                  fleet_binding=registry.binding_for(cid))
        registry.register(cid, cc, default=...)
    """

    def __init__(self, scheduler,
                 bucket_floor: int = DEFAULT_BUCKET_FLOOR,
                 bucket_max_tracked: int = 64,
                 fold_enabled: bool = True,
                 max_tenants: int = 64,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self.scheduler = scheduler
        self._time = time_fn or _time.time
        self.max_tenants = max(1, int(max_tenants))
        #: fleet-level sensors (fleet-bucket-compiles,
        #: fleet-folded-solves, fleet-fold-fallbacks); per-tenant sensors
        #: stay in each facade's own registry and are exported tagged
        #: (see sensors_json)
        self.metrics = MetricRegistry(self._time)
        self.buckets = BucketIndex(floor=bucket_floor,
                                   max_tracked=bucket_max_tracked,
                                   metrics=self.metrics)
        self.router = (FleetRouter(metrics=self.metrics,
                                   time_fn=self._time)
                       if fold_enabled else None)
        self._lock = threading.Lock()
        self._tenants: Dict[str, Tenant] = {}
        self._default_id: Optional[str] = None
        self.metrics.gauge("fleet-tenant-count",
                           lambda: float(len(self._tenants)))

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def binding_for(self, cluster_id: str) -> FleetBinding:
        return FleetBinding(tenant_id=cluster_id, buckets=self.buckets,
                            router=self.router)

    def register(self, cluster_id: str, facade,
                 default: bool = False) -> Tenant:
        with self._lock:
            if cluster_id in self._tenants:
                raise ValueError(f"cluster {cluster_id!r} is already "
                                 f"registered")
            if len(self._tenants) >= self.max_tenants:
                raise ValueError(
                    f"fleet is at its tenant cap ({self.max_tenants}); "
                    f"raise fleet.max.tenants to register more")
            binding = getattr(facade, "_fleet_binding", None)
            if binding is not None and binding.tenant_id != cluster_id:
                raise ValueError(
                    f"facade was bound as {binding.tenant_id!r}, cannot "
                    f"register as {cluster_id!r}")
            tenant = Tenant(cluster_id=cluster_id, facade=facade,
                            registered_at=self._time())
            self._tenants[cluster_id] = tenant
            if default or self._default_id is None:
                self._default_id = cluster_id
        LOG.info("fleet: registered tenant %r (default=%s, %d total)",
                 cluster_id, self._default_id == cluster_id,
                 len(self._tenants))
        # tenant onboarding warms from the persistent program cache
        # (outside the lock — hydration may compile deserialized
        # modules): a tenant whose shape bucket + goal list another
        # tenant (or a previous process) already compiled reaches
        # FUSED/MESH with zero source-program compiles.  No-op when the
        # cache is off/empty; best-effort by contract (the facade method
        # never raises) — and tolerant of stub facades in tests.
        warm = getattr(facade, "warm_programs_from_cache", None)
        if warm is not None:
            hydrated = warm()
            if hydrated:
                LOG.info("fleet: tenant %r hydrated %d compiled "
                         "programs from the program cache", cluster_id,
                         hydrated)
        # crash recovery at onboarding: replay this tenant's executor
        # journal (its own subdirectory of executor.journal.dir) and
        # resume/abort whatever the previous process left in flight.
        # Idempotent — start_up() reaches the same guard-flagged method
        # — and best-effort by the facade's contract (never raises);
        # tolerant of stub facades in tests.
        recover = getattr(facade, "recover_interrupted_execution", None)
        if recover is not None:
            report = recover()
            if report:
                LOG.warning(
                    "fleet: tenant %r recovered interrupted execution "
                    "%s (mode=%s, resumed=%s)", cluster_id,
                    report.get("uuid"), report.get("mode"),
                    report.get("resumed"))
        return tenant

    def drain(self, cluster_id: str) -> Tenant:
        """Stop admitting new mutating work for the tenant; reads and
        already-queued solves finish normally."""
        with self._lock:
            tenant = self._get_locked(cluster_id)
            if (cluster_id == self._default_id
                    and len(self._tenants) > 1):
                raise ValueError(
                    f"cluster {cluster_id!r} is the default tenant; "
                    f"drain the others first or re-register a new "
                    f"default")
            tenant.status = TenantStatus.DRAINING
        LOG.info("fleet: draining tenant %r", cluster_id)
        return tenant

    def unregister(self, cluster_id: str) -> None:
        """Remove a DRAINING tenant: shuts its facade down (monitor,
        detectors, executor) and drops it.  The shared scheduler keeps
        running — the facade knows it does not own it."""
        with self._lock:
            tenant = self._get_locked(cluster_id)
            if tenant.status is not TenantStatus.DRAINING:
                raise ValueError(
                    f"cluster {cluster_id!r} must be drained before "
                    f"unregistering")
            del self._tenants[cluster_id]
            if self._default_id == cluster_id:
                self._default_id = next(iter(self._tenants), None)
        tenant.facade.shutdown()
        LOG.info("fleet: unregistered tenant %r", cluster_id)

    def shutdown(self) -> None:
        """Shut every tenant down, then stop the shared scheduler."""
        with self._lock:
            tenants = list(self._tenants.values())
            self._tenants.clear()
            self._default_id = None
        for tenant in tenants:
            try:
                tenant.facade.shutdown()
            except Exception:  # noqa: BLE001 - best-effort teardown
                LOG.exception("fleet: shutdown of tenant %r failed",
                              tenant.cluster_id)
        self.scheduler.stop()

    # ------------------------------------------------------------------
    # resolution
    # ------------------------------------------------------------------
    def _get_locked(self, cluster_id: str) -> Tenant:
        tenant = self._tenants.get(cluster_id)
        if tenant is None:
            raise UnknownTenantError(cluster_id, list(self._tenants))
        return tenant

    def get(self, cluster_id: Optional[str] = None,
            for_write: bool = False) -> Tenant:
        """Resolve a tenant (default when `cluster_id` is None).  Raises
        UnknownTenantError (-> 404) for unknown ids and, when
        `for_write`, TenantDrainingError (-> 503) for draining ones."""
        with self._lock:
            if cluster_id is None:
                if self._default_id is None:
                    raise UnknownTenantError("<default>", [])
                tenant = self._tenants[self._default_id]
            else:
                tenant = self._get_locked(cluster_id)
        if for_write and tenant.status is not TenantStatus.ACTIVE:
            raise TenantDrainingError(tenant.cluster_id)
        return tenant

    def facade_for(self, cluster_id: Optional[str] = None,
                   for_write: bool = False):
        return self.get(cluster_id, for_write=for_write).facade

    @property
    def default_id(self) -> Optional[str]:
        return self._default_id

    def tenants(self) -> List[Tenant]:
        with self._lock:
            return list(self._tenants.values())

    # ------------------------------------------------------------------
    # observability (FLEET endpoint + STATE substates=fleet)
    # ------------------------------------------------------------------
    def state_json(self) -> dict:
        """FleetState: tenant list + shared-substrate telemetry."""
        out = {
            "tenants": [t.to_json(self._default_id)
                        for t in self.tenants()],
            "defaultTenant": self._default_id,
            "buckets": self.buckets.to_json(),
            "foldEnabled": self.router is not None,
        }
        if self.router is not None:
            out["router"] = self.router.to_json()
        return out

    def fleet_json(self, verbose: bool = False) -> dict:
        """FLEET endpoint body: per-tenant status + state summary."""
        clusters = []
        for tenant in self.tenants():
            entry = tenant.to_json(self._default_id)
            cc = tenant.facade
            try:
                ms = cc.load_monitor.get_state()
                entry["monitor"] = {"state": ms.state,
                                    "numValidWindows": ms.num_valid_windows}
                entry["solverRung"] = cc.solver_ladder.rung.name
                entry["hasOngoingExecution"] = \
                    cc.executor.has_ongoing_execution
                if verbose:
                    entry["state"] = cc.state(
                        ("monitor", "analyzer", "executor"))
            except Exception as exc:  # noqa: BLE001 - one sick tenant
                # must not take the fleet listing down with it
                LOG.warning("fleet: state of tenant %r unavailable: %s",
                            tenant.cluster_id, exc)
                entry["stateError"] = f"{type(exc).__name__}: {exc}"
            clusters.append(entry)
        shared = self.state_json()
        # `clusters` above IS the tenant list (with live monitor/ladder
        # summaries) — FleetState's bare `tenants` array would duplicate
        # every row in the FLEET body
        del shared["tenants"]
        return {"clusters": clusters, **shared}

    def sensors_json(self) -> dict:
        """Fleet sensors + every tenant's sensors tagged
        `cluster.<id>.<sensor>` so one scrape sees the whole fleet."""
        out = dict(self.metrics.to_json())
        for tenant in self.tenants():
            tagged = tenant.facade.metrics.to_json()
            out.update({f"cluster.{tenant.cluster_id}.{name}": value
                        for name, value in tagged.items()})
        return out
