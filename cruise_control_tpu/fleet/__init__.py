"""Fleet serving: K independent Kafka clusters (tenants) in one process
sharing one device, one PR-4 scheduler, and shape-bucketed compiled
programs.  See docs/FLEET.md."""
from cruise_control_tpu.fleet.buckets import (BucketIndex, FleetBucket,
                                              bucket_of, next_pow2,
                                              pad_state_to_bucket)
from cruise_control_tpu.fleet.registry import (FleetBinding, FleetRegistry,
                                               Tenant, TenantDrainingError,
                                               TenantStatus,
                                               UnknownTenantError)
from cruise_control_tpu.fleet.router import FleetRouter, FleetSolvePayload

__all__ = [
    "BucketIndex", "FleetBucket", "bucket_of", "next_pow2",
    "pad_state_to_bucket",
    "FleetBinding", "FleetRegistry", "Tenant", "TenantDrainingError",
    "TenantStatus", "UnknownTenantError",
    "FleetRouter", "FleetSolvePayload",
]
