"""Resource definitions for the TPU-native cruise-control framework.

Mirrors the semantics of the reference's Resource enum
(reference: cruise-control/src/main/java/com/linkedin/kafka/cruisecontrol/
common/Resource.java:18-26):
four balanced resources with per-resource comparison epsilons and
host/broker-level distinctions.  Here resources are plain integer ids so they
can index tensor axes directly (broker_load[B, NUM_RESOURCES]).
"""
from __future__ import annotations

import enum
from typing import List

import numpy as np


class Resource(enum.IntEnum):
    """A balanced resource.

    CPU is a host- and broker-level resource, NW_IN/NW_OUT are host-level,
    DISK is broker-level (reference Resource.java:14-26).  The integer value
    is the tensor-axis index.
    """

    CPU = 0
    NW_IN = 1
    NW_OUT = 2
    DISK = 3

    @property
    def is_host_resource(self) -> bool:
        return self in (Resource.CPU, Resource.NW_IN, Resource.NW_OUT)

    @property
    def is_broker_resource(self) -> bool:
        return self in (Resource.CPU, Resource.DISK)

    @property
    def base_epsilon(self) -> float:
        return _BASE_EPSILON[int(self)]

    def epsilon(self, value1: float, value2: float) -> float:
        """Comparison epsilon for two utilization values.

        Follows the reference's rule max(base, EPSILON_PERCENT*(v1+v2))
        (Resource.java:92-94), where EPSILON_PERCENT was tuned on an
        ~800K-replica stress test (Resource.java:28-32).
        """
        return max(self.base_epsilon, EPSILON_PERCENT * (value1 + value2))

    @classmethod
    def cached_values(cls) -> List["Resource"]:
        return _CACHED_VALUES


# Acceptable relative nuance from float summation over very large replica
# counts (reference Resource.java:28-32).
EPSILON_PERCENT = 0.0008

_BASE_EPSILON = (0.001, 10.0, 10.0, 100.0)

_CACHED_VALUES = [Resource.CPU, Resource.NW_IN, Resource.NW_OUT, Resource.DISK]

NUM_RESOURCES = 4

#: Per-resource base epsilons as an array usable inside jitted kernels.
BASE_EPSILON_ARRAY = np.asarray(_BASE_EPSILON, dtype=np.float32)

#: Resources for which expected utilization is the *average* over windows;
#: DISK uses the *latest* window (reference model/Load.java:25-120).
AVG_RESOURCES = (Resource.CPU, Resource.NW_IN, Resource.NW_OUT)

#: Goal-name prefixes per resource id, matching the reference's goal class
#: names (CpuCapacityGoal, NetworkInboundUsageDistributionGoal, ...).
RESOURCE_GOAL_NAMES = {
    0: "Cpu", 1: "NetworkInbound", 2: "NetworkOutbound", 3: "Disk",
}
