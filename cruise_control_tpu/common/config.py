"""Typed configuration framework.

A Python re-design of the Kafka-style ConfigDef the reference vendors into its
core module (reference: cruise-control-core/src/main/java/com/linkedin/
cruisecontrol/common/config/ConfigDef.java:1-1253 and AbstractConfig).  It
provides typed key definitions with defaults, validators, importance and doc
strings; parsing from untyped dicts / properties files; and dynamic
instantiation of pluggable classes (the reference's getConfiguredInstance
pattern used for goals, samplers, notifiers, ...).
"""
from __future__ import annotations

import enum
import importlib
import os
import re
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Mapping, Optional


class ConfigException(Exception):
    """Raised on invalid configuration (reference ConfigException)."""


class Type(enum.Enum):
    BOOLEAN = "boolean"
    STRING = "string"
    INT = "int"
    LONG = "long"
    DOUBLE = "double"
    LIST = "list"
    CLASS = "class"
    PASSWORD = "password"


class Importance(enum.Enum):
    HIGH = "high"
    MEDIUM = "medium"
    LOW = "low"


class Password:
    """Opaque secret wrapper that never prints its value
    (reference CORE/common/config/types/Password.java)."""

    def __init__(self, value: str):
        self.value = value

    def __repr__(self) -> str:  # pragma: no cover - trivial
        return "[hidden]"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Password) and other.value == self.value

    def __hash__(self) -> int:
        return hash(self.value)


#: Sentinel meaning "no default — the key is required".
NO_DEFAULT = object()


Validator = Callable[[str, Any], None]


def in_range(min_value=None, max_value=None) -> Validator:
    """Range validator (reference ConfigDef.Range.between/atLeast)."""

    def validate(name: str, value: Any) -> None:
        if value is None:
            return
        if min_value is not None and value < min_value:
            raise ConfigException(f"{name}: value {value} must be >= {min_value}")
        if max_value is not None and value > max_value:
            raise ConfigException(f"{name}: value {value} must be <= {max_value}")

    return validate


def in_values(*allowed: Any) -> Validator:
    """Enumerated-value validator (reference ConfigDef.ValidString)."""

    def validate(name: str, value: Any) -> None:
        if value not in allowed:
            raise ConfigException(f"{name}: value {value!r} not in {allowed}")

    return validate


def non_empty(name: str, value: Any) -> None:
    if value is None or (isinstance(value, (str, list)) and not value):
        raise ConfigException(f"{name}: must be non-empty")


@dataclass
class ConfigKey:
    name: str
    type: Type
    default: Any = NO_DEFAULT
    validator: Optional[Validator] = None
    importance: Importance = Importance.MEDIUM
    doc: str = ""

    @property
    def has_default(self) -> bool:
        return self.default is not NO_DEFAULT


def _parse_bool(name: str, value: Any) -> bool:
    if isinstance(value, bool):
        return value
    if isinstance(value, str):
        lowered = value.strip().lower()
        if lowered == "true":
            return True
        if lowered == "false":
            return False
    raise ConfigException(f"{name}: expected boolean, got {value!r}")


def _parse_list(name: str, value: Any) -> List[Any]:
    if value is None:
        return []
    if isinstance(value, str):
        return [item.strip() for item in value.split(",") if item.strip()]
    if isinstance(value, (list, tuple)):
        return list(value)
    raise ConfigException(f"{name}: expected list, got {value!r}")


def parse_type(name: str, value: Any, expected: Type) -> Any:
    """Parse an untyped value to the declared type
    (reference ConfigDef.parseType)."""
    if value is None:
        return None
    try:
        if expected is Type.BOOLEAN:
            return _parse_bool(name, value)
        if expected is Type.STRING:
            return str(value)
        if expected in (Type.INT, Type.LONG):
            if isinstance(value, bool):
                raise ConfigException(f"{name}: expected int, got bool")
            return int(value)
        if expected is Type.DOUBLE:
            return float(value)
        if expected is Type.LIST:
            return _parse_list(name, value)
        if expected is Type.CLASS:
            return value  # resolved lazily by get_configured_instance
        if expected is Type.PASSWORD:
            return value if isinstance(value, Password) else Password(str(value))
    except (TypeError, ValueError) as exc:
        raise ConfigException(f"{name}: cannot parse {value!r} as {expected.value}: {exc}")
    raise ConfigException(f"{name}: unknown type {expected}")


class ConfigDef:
    """Registry of typed config keys (reference ConfigDef.java:1-1253)."""

    def __init__(self):
        self._keys: Dict[str, ConfigKey] = {}

    def define(self, name: str, type: Type, default: Any = NO_DEFAULT,
               validator: Optional[Validator] = None,
               importance: Importance = Importance.MEDIUM, doc: str = "") -> "ConfigDef":
        if name in self._keys:
            raise ConfigException(f"Config key {name} defined twice")
        if default is not NO_DEFAULT and default is not None:
            default = parse_type(name, default, type)
        self._keys[name] = ConfigKey(name, type, default, validator, importance, doc)
        return self

    def merge(self, other: "ConfigDef") -> "ConfigDef":
        for key in other._keys.values():
            if key.name not in self._keys:
                self._keys[key.name] = key
        return self

    def keys(self) -> Mapping[str, ConfigKey]:
        return dict(self._keys)

    def parse(self, props: Mapping[str, Any]) -> Dict[str, Any]:
        values: Dict[str, Any] = {}
        for name, key in self._keys.items():
            if name in props:
                value = parse_type(name, props[name], key.type)
            elif key.has_default:
                value = key.default
            else:
                raise ConfigException(f"Missing required configuration {name}")
            if key.validator is not None:
                key.validator(name, value)
            values[name] = value
        return values

    def document(self) -> str:
        """Render a markdown doc table of all keys (reference ConfigDef.toHtml)."""
        lines = ["| name | type | default | importance | doc |", "|---|---|---|---|---|"]
        for key in sorted(self._keys.values(), key=lambda k: k.name):
            default = "(required)" if not key.has_default else repr(key.default)
            lines.append(f"| {key.name} | {key.type.value} | {default} | "
                         f"{key.importance.value} | {key.doc} |")
        return "\n".join(lines)


def resolve_class(spec: Any):
    """Resolve a class from a "module.path:ClassName" or "module.path.ClassName"
    string, or pass through an actual class object."""
    if isinstance(spec, type):
        return spec
    if callable(spec) and not isinstance(spec, str):
        return spec
    if not isinstance(spec, str):
        raise ConfigException(f"Cannot resolve class from {spec!r}")
    module_name, _, cls_name = spec.replace(":", ".").rpartition(".")
    if not module_name:
        raise ConfigException(f"Class spec {spec!r} must be fully qualified")
    try:
        module = importlib.import_module(module_name)
        return getattr(module, cls_name)
    except (ImportError, AttributeError) as exc:
        raise ConfigException(f"Cannot load class {spec!r}: {exc}")


class AbstractConfig:
    """Parsed config values with typed accessors and pluggable-class
    instantiation (reference CORE/common/config/AbstractConfig.java)."""

    def __init__(self, definition: ConfigDef, props: Mapping[str, Any]):
        self.definition = definition
        self.originals = dict(props)
        self.values = definition.parse(props)
        self._used: set = set()

    def get(self, name: str) -> Any:
        if name not in self.values:
            raise ConfigException(f"Unknown configuration {name}")
        self._used.add(name)
        return self.values[name]

    def get_boolean(self, name: str) -> bool:
        return self.get(name)

    def get_int(self, name: str) -> int:
        return self.get(name)

    def get_long(self, name: str) -> int:
        return self.get(name)

    def get_double(self, name: str) -> float:
        return self.get(name)

    def get_string(self, name: str) -> str:
        return self.get(name)

    def get_list(self, name: str) -> List[Any]:
        return self.get(name)

    def unused(self) -> List[str]:
        return [k for k in self.originals if k not in self._used]

    def get_configured_instance(self, name: str, expected_type: type = object,
                                **extra) -> Any:
        """Instantiate the class named by config key `name` and, if it defines
        `configure(config_dict)`, pass it the full original config plus any
        `extra` overrides (reference AbstractConfig.getConfiguredInstance)."""
        cls = resolve_class(self.get(name))
        instance = cls()
        if not isinstance(instance, expected_type):
            raise ConfigException(
                f"{name}: {cls} is not an instance of {expected_type}")
        self._configure(instance, extra)
        return instance

    def get_configured_instances(self, name: str, expected_type: type = object,
                                 **extra) -> List[Any]:
        instances = []
        for spec in self.get_list(name):
            cls = resolve_class(spec)
            instance = cls()
            if not isinstance(instance, expected_type):
                raise ConfigException(
                    f"{name}: {cls} is not an instance of {expected_type}")
            self._configure(instance, extra)
            instances.append(instance)
        return instances

    def _configure(self, instance: Any, extra: Mapping[str, Any]) -> None:
        configure = getattr(instance, "configure", None)
        if callable(configure):
            merged = dict(self.originals)
            merged.update(extra)
            configure(merged)


#: ${env:NAME} indirection in property values (reference
#: CC/config/EnvConfigProvider.java — secrets such as passwords reference
#: environment variables instead of living in the properties file).
#: `$${env:NAME}` escapes the indirection, yielding a literal ${env:NAME}.
_ENV_REF = re.compile(r"(\$?)\$\{env:([A-Za-z_][A-Za-z0-9_]*)\}")


def resolve_env_references(value: str) -> str:
    """Substitute every `${env:NAME}` in `value` from the environment.

    Unset variables raise (a silently-empty secret is worse than failing
    at startup).  A value that needs the literal text writes `$${env:...}`
    — the reference only substitutes via its explicitly-configured
    ConfigProvider, so an escape hatch is required here where resolution
    happens at load time."""
    def sub(match):
        if match.group(1):                   # $${env:X} -> literal ${env:X}
            return match.group(0)[1:]
        name = match.group(2)
        if name not in os.environ:
            raise KeyError(
                f"config references ${{env:{name}}} but {name} is not set")
        return os.environ[name]
    return _ENV_REF.sub(sub, value)


def load_properties(path: str) -> Dict[str, str]:
    """Parse a Java-style .properties file (reference reads config via
    KafkaCruiseControlUtils.readConfig), resolving ${env:NAME} secrets."""
    props: Dict[str, str] = {}
    with open(path, "r", encoding="utf-8") as handle:
        for raw in handle:
            line = raw.strip()
            if not line or line.startswith("#") or line.startswith("!"):
                continue
            # first-occurring separator wins (Java .properties semantics)
            positions = [(line.index(sep), sep) for sep in ("=", ":")
                         if sep in line]
            if positions:
                pos, sep = min(positions)
                props[line[:pos].strip()] = resolve_env_references(
                    line[pos + len(sep):].strip())
    return props
