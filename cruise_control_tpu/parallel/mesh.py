"""Device-mesh sharding for the cluster model.

The 200K-partition replica axis is the framework's "long sequence": the
reference handles it with per-broker incremental search and sorted-replica
caches (SURVEY.md §5.7); here it is a sharded tensor dimension.  Replica-
major arrays shard across a 1-D `replica` mesh axis; broker/partition-level
arrays replicate.  Under jit, segment-sum load accounting over the sharded
replica axis lowers to per-shard partial sums + an all-reduce over ICI —
XLA inserts the collectives (psum pattern) from the sharding annotations
alone, which is the whole point of the pjit design: no hand-written
communication.

The HOT steady-state path, however, is not the [R] arrays but the
resident per-broker tables (RoundCache.broker_table [B, S] and its aux
planes — see context.py): those shard along the BROKER axis over the
same 1-D mesh (different arrays, same devices), so per-round candidate
selection (row reductions, top-k) and the [C, K] assignment planes are
broker-parallel while the small [B] accounting vectors all-reduce over
ICI.  `solver_mesh(mesh)` activates these constraints inside the round
kernels (they are no-ops off-mesh); the constraint surface is
`constrain(...)` below.
"""
from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cruise_control_tpu.model.state import ClusterState

REPLICA_AXIS = "replica"


# ----------------------------------------------------------------------
# program-cache key anatomy (shared by every program keyspace)
#
# THREE subsystems cache compiled pipeline programs: the optimizer's
# in-process `_aot`/`_SHARED_PROGRAMS` dicts (analyzer/optimizer.py),
# the scenario engine's per-batch LRU (scenario/engine.py), and the
# persistent on-disk cache (parallel/progcache.py).  They used to build
# their keys independently ("@meshN" suffixes here, a shapes tuple
# there), which is exactly how keyspaces drift apart; every key is now
# assembled from the helpers below — (program key incl. mesh span,
# goal-list signature, input-tree signature, environment fingerprint) —
# so an entry written by one path is addressable by every other.
# ----------------------------------------------------------------------

def program_key(program: str, mesh_devices: int = 1) -> str:
    """Canonical program name: the pipeline program id plus the
    ``@meshN`` span suffix for multi-chip traces.  Single-chip programs
    keep the bare name — mesh=1 must stay byte-identical to the
    pre-mesh path, including its cache keys."""
    return (program if mesh_devices <= 1
            else f"{program}@mesh{int(mesh_devices)}")


def goal_list_signature(share_key) -> Optional[str]:
    """Stable digest of a GoalOptimizer._goals_share_key() tuple, or
    None when the goal list cannot be shared (non-primitive goal state)
    — an unshareable list is never persisted: a recycled in-memory id
    must not address another process's entry."""
    if share_key is None:
        return None
    return hashlib.sha256(repr(share_key).encode()).hexdigest()[:16]


def tree_signature(*trees) -> str:
    """Digest of the input pytrees' STRUCTURE and avals: treedef repr
    (which carries every static dataclass field — register_dataclass
    puts them in the aux data) plus per-leaf shape/dtype.  Two argument
    sets with equal signatures lower to the same program, so this is
    the shape-bucket axis of the persistent cache key."""
    leaves, treedef = jax.tree_util.tree_flatten(trees)
    parts = [repr(treedef)]
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            parts.append(f"{tuple(shape)}:{getattr(leaf, 'dtype', '?')}")
        else:
            parts.append(repr(leaf))
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


#: memoized (override -> fingerprint) — source hashing walks the solver
#: packages once per process
_FINGERPRINT_CACHE: dict = {}
#: packages whose sources define what the pipeline programs COMPUTE —
#: any edit must invalidate every cached executable (a stale entry is a
#: miss, never a wrong answer)
_FINGERPRINT_PACKAGES = ("analyzer", "model", "parallel", "scenario",
                         "common")


def _source_hash() -> str:
    """Content hash over the kernel/goal/model program sources."""
    import os
    h = hashlib.sha256()
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for pkg in _FINGERPRINT_PACKAGES:
        root = os.path.join(pkg_root, pkg)
        for dirpath, dirnames, filenames in sorted(os.walk(root)):
            dirnames.sort()
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                h.update(os.path.relpath(path, pkg_root).encode())
                with open(path, "rb") as fh:
                    h.update(fh.read())
    return h.hexdigest()[:16]


def program_fingerprint(override: Optional[str] = None) -> str:
    """Environment fingerprint of a compiled program: jax + jaxlib
    version, backend platform, device kind, and a content hash of the
    solver sources.  Any mismatch makes every entry under the old
    fingerprint a MISS — the cache can serve a stale executable only if
    all five terms collide, i.e. never.  `override` (the
    progcache.fingerprint.override key) replaces the source-hash term
    so operators can pin sharing across builds they know are
    program-equivalent (e.g. docs-only changes)."""
    if override in _FINGERPRINT_CACHE:
        return _FINGERPRINT_CACHE[override]
    import jaxlib
    devices = jax.devices()
    dev_kind = (getattr(devices[0], "device_kind", devices[0].platform)
                if devices else "none")
    terms = (jax.__version__, jaxlib.__version__, jax.default_backend(),
             str(dev_kind), override if override else _source_hash())
    fp = hashlib.sha256("|".join(terms).encode()).hexdigest()[:16]
    _FINGERPRINT_CACHE[override] = fp
    return fp

_ACTIVE = threading.local()


@contextlib.contextmanager
def solver_mesh(mesh: Mesh):
    """Activate broker/replica-axis sharding constraints inside the round
    kernels traced under this context (thread-local; trace-time only)."""
    prev = getattr(_ACTIVE, "mesh", None)
    _ACTIVE.mesh = mesh
    try:
        yield mesh
    finally:
        _ACTIVE.mesh = prev


def active_mesh() -> Optional[Mesh]:
    return getattr(_ACTIVE, "mesh", None)


def constrain(x: jax.Array, *spec) -> jax.Array:
    """with_sharding_constraint(x, P(*spec)) against the active solver
    mesh; identity when no mesh is active.  Use axis position 0 =
    REPLICA_AXIS for both replica-major [R, ...] arrays and broker-major
    [B, S, ...] table planes — they shard over the same 1-D device axis."""
    mesh = active_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*spec)))


def constrain_cache(cache):
    """Apply the table-plane sharding constraints to a RoundCache: the
    [B, S, ...] resident tables shard on the broker axis (the hot-path
    layout — round-2's gather-resident redesign moved steady-state work
    onto these planes, so replicating them would serialize every round);
    [R]-sized arrays shard on the replica axis; the small [B]-sized
    accounting vectors replicate (they are all-reduced each round)."""
    if active_mesh() is None:
        return cache
    ax = REPLICA_AXIS
    return dataclasses.replace(
        cache,
        replica_load=constrain(cache.replica_load, ax, None),
        broker_table=constrain(cache.broker_table, ax, None),
        table_fill=constrain(cache.table_fill, ax),
        table_load=constrain(cache.table_load, ax, None, None),
        table_bonus=constrain(cache.table_bonus, ax, None, None),
        table_leader=constrain(cache.table_leader, ax, None),
        table_ok=constrain(cache.table_ok, ax, None),
    )


def make_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or given) devices, replica-axis parallel."""
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (REPLICA_AXIS,))


class MeshToken:
    """First-class runtime handle to the solve mesh.

    The PR-4 dispatch thread owns ONE of these instead of a bare device
    token: every scheduled solve runs with the token in scope
    (sched/runtime.mesh_token_scope), so the whole stack — the fused
    goal pipeline, the scenario engine's lane batching, the fleet fold —
    resolves its device topology through the scheduler rather than
    acquiring devices ad hoc (the mesh half of the single-gateway rule,
    tools/lint.py).

    `mesh is None` is the DEGENERATE single-chip token: every consumer
    must treat it exactly like the pre-mesh code path (no padding, no
    sharding constraints, no program-key suffix), which is what keeps
    the mesh=1 case byte-identical to the single-device pin — the same
    trick as the scheduler's K=1 inline pin."""

    __slots__ = ("mesh",)

    def __init__(self, mesh: Optional[Mesh] = None) -> None:
        self.mesh = mesh if (mesh is not None and mesh.size > 1) else None

    @property
    def size(self) -> int:
        return 1 if self.mesh is None else self.mesh.size

    @property
    def is_multichip(self) -> bool:
        return self.mesh is not None

    def to_json(self) -> dict:
        return {
            "devices": self.size,
            "axis": REPLICA_AXIS if self.mesh is not None else None,
            "platform": (self.mesh.devices.flat[0].platform
                         if self.mesh is not None else None),
        }


def runtime_mesh(enabled: Optional[bool] = None,
                 max_devices: Optional[int] = None,
                 devices=None) -> MeshToken:
    """Build the process's solve-mesh token.

    `enabled=None` (the config default, mesh.enabled=auto) activates the
    mesh only on non-CPU backends: >1 "CPU devices" in this codebase
    means the virtual 8-device host-platform test rig
    (testing/virtual_mesh.py), where the single-chip byte-identical pins
    must keep running on the degenerate token unless a test FORCES the
    mesh on (mesh_enabled=True).  On real multi-chip hardware (v5e-8)
    auto resolves to enabled.

    Degenerates to a single-chip token (mesh=None) whenever 0/1 devices
    remain after the `max_devices` clip — single-chip stays the exact
    pre-mesh code path."""
    if enabled is False:
        return MeshToken(None)
    devices = list(devices if devices is not None else jax.devices())
    if enabled is None and (not devices
                            or devices[0].platform == "cpu"):
        return MeshToken(None)
    if max_devices is not None and max_devices > 0:
        devices = devices[:max_devices]
    if len(devices) <= 1:
        return MeshToken(None)
    return MeshToken(make_mesh(devices))


def _pad_to_multiple(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def pad_leading(x, pad: int, fill):
    """Pad `pad` rows of `fill` onto the leading axis of `x` (later axes
    untouched) — shared by the replica-axis mesh padding below and the
    scenario compiler's broker-axis padding (scenario/compiler.py), so
    heterogeneous shapes always pad the same way.  Numpy inputs stay on
    host (np.pad): the scenario compiler pads many small host arrays
    per batch and must not pay a device round trip per array."""
    if pad <= 0:
        return x
    widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
    if isinstance(x, np.ndarray):
        return np.pad(x, widths, constant_values=fill)
    return jnp.pad(x, widths, constant_values=fill)


#: THE dead-row convention: the fill that keeps a padded leading-axis row
#: inert for every ClusterState field.  One table serves all three
#: padders — the replica-axis mesh padding (`pad_state`), the scenario
#: compiler's broker-axis padding (scenario/compiler.py) and the fleet
#: shape buckets (fleet/buckets.py) — so membership/weight conventions
#: cannot drift between them: padded replicas are invalid and weightless
#: (parked on broker 0, no disk), padded brokers are dead with zero
#: capacity in rack/host 0, padded disks are dead with zero capacity on
#: broker 0.  Every statistic and goal masks on replica_valid /
#: broker_alive / disk_alive, so dead rows can never leak load (pinned in
#: tests/test_scenario.py and tests/test_fleet.py).
DEAD_ROW_FILLS = {
    # replica axis [R, ...]
    "replica_valid": False,
    "replica_partition": 0,
    "replica_broker": 0,
    "replica_disk": -1,
    "replica_is_leader": False,
    "replica_offline": False,
    "replica_original_offline": False,
    "replica_base_load": 0.0,
    # partition axis [P, ...] (fleet shape buckets pad partitions too:
    # a padded partition belongs to topic 0 but owns NO replicas, so it
    # contributes to no count, load, or topic statistic)
    "partition_topic": 0,
    "partition_leader_bonus": 0.0,
    # broker axis [B, ...]
    "broker_alive": False,
    "broker_new": False,
    "broker_demoted": False,
    "broker_bad_disks": False,
    "broker_capacity": 0.0,
    "broker_rack": 0,
    "broker_host": 0,
    # disk axis [D]
    "disk_broker": 0,
    "disk_capacity": 0.0,
    "disk_alive": False,
}

#: ClusterState fields per paddable leading axis (the other fields of
#: each axis group are untouched by that axis's padding)
REPLICA_AXIS_FIELDS = ("replica_valid", "replica_partition",
                       "replica_broker", "replica_disk",
                       "replica_is_leader", "replica_offline",
                       "replica_original_offline", "replica_base_load")
PARTITION_AXIS_FIELDS = ("partition_topic", "partition_leader_bonus")
BROKER_AXIS_FIELDS = ("broker_alive", "broker_new", "broker_demoted",
                      "broker_bad_disks", "broker_capacity", "broker_rack",
                      "broker_host")
DISK_AXIS_FIELDS = ("disk_broker", "disk_capacity", "disk_alive")


def pad_field(name: str, x, pad: int):
    """pad_leading with the registered dead-row fill for `name`."""
    return pad_leading(x, pad, DEAD_ROW_FILLS[name])


def _pad_axis(state: ClusterState, fields, target: int,
              current: int) -> ClusterState:
    if target <= current:
        return state
    pad = target - current
    return state.replace(**{f: pad_field(f, getattr(state, f), pad)
                            for f in fields})


def pad_replica_axis(state: ClusterState, target: int) -> ClusterState:
    """Pad the replica axis to exactly `target` rows; padding rows are
    invalid replicas parked on broker 0 (dead-row convention above)."""
    return _pad_axis(state, REPLICA_AXIS_FIELDS, target,
                     state.num_replicas)


def pad_partition_axis(state: ClusterState, target: int) -> ClusterState:
    """Pad the partition axis to exactly `target` rows; padding rows are
    empty partitions of topic 0 holding no replicas (no replica ever
    references a padded partition index, so they carry no load)."""
    return _pad_axis(state, PARTITION_AXIS_FIELDS, target,
                     state.num_partitions)


def pad_broker_axis(state: ClusterState, target: int) -> ClusterState:
    """Pad the broker axis to exactly `target` rows; padding rows are
    dead brokers with zero capacity in rack/host 0 (the scenario
    compiler's convention, now shared)."""
    return _pad_axis(state, BROKER_AXIS_FIELDS, target,
                     state.num_brokers)


def pad_disk_axis(state: ClusterState, target: int) -> ClusterState:
    """Pad the disk axis to exactly `target` rows; padding rows are dead
    zero-capacity disks parked on broker 0."""
    return _pad_axis(state, DISK_AXIS_FIELDS, target, state.num_disks)


def pad_state(state: ClusterState, multiple: int) -> ClusterState:
    """Pad the replica axis so it divides the mesh size; padding rows are
    invalid replicas parked on broker 0."""
    num_r = state.num_replicas
    return pad_replica_axis(state, _pad_to_multiple(max(num_r, 1),
                                                    multiple))


def state_shardings(state: ClusterState, mesh: Mesh) -> ClusterState:
    """A ClusterState-shaped pytree of NamedShardings: replica-axis arrays
    shard over the mesh, everything else replicates."""
    shard = NamedSharding(mesh, P(REPLICA_AXIS))
    shard2 = NamedSharding(mesh, P(REPLICA_AXIS, None))
    rep = NamedSharding(mesh, P())
    rep2 = NamedSharding(mesh, P(None, None))
    return ClusterState(
        replica_valid=shard,
        replica_partition=shard,
        replica_broker=shard,
        replica_disk=shard,
        replica_is_leader=shard,
        replica_offline=shard,
        replica_original_offline=shard,
        replica_base_load=shard2,
        partition_topic=rep,
        partition_leader_bonus=rep2,
        broker_alive=rep,
        broker_new=rep,
        broker_demoted=rep,
        broker_bad_disks=rep,
        broker_capacity=rep2,
        broker_rack=rep,
        broker_host=rep,
        disk_broker=rep,
        disk_capacity=rep,
        disk_alive=rep,
        num_racks=state.num_racks,
        num_hosts=state.num_hosts,
        num_topics=state.num_topics,
    )


def unpad_replica_axis(state: ClusterState, target: int) -> ClusterState:
    """Drop mesh-padding rows so the replica axis is exactly `target`
    rows again (the inverse of pad_state for a solve's FINAL state: a
    warm-start seed must match the raw model's shapes, and padded rows
    are dead by construction so slicing them off loses nothing).  The
    slices are lazy device ops — nothing is fetched here."""
    if state.num_replicas <= target:
        return state
    return state.replace(**{f: getattr(state, f)[:target]
                            for f in REPLICA_AXIS_FIELDS})


def shard_state(state: ClusterState, mesh: Optional[Mesh] = None
                ) -> ClusterState:
    """Place a ClusterState onto the mesh with replica-axis sharding."""
    mesh = mesh or make_mesh()
    state = pad_state(state, mesh.size)
    shardings = state_shardings(state, mesh)

    def place(x, s):
        if isinstance(x, (int,)):
            return x
        return jax.device_put(x, s)

    fields = {}
    for f in dataclasses.fields(ClusterState):
        val = getattr(state, f.name)
        tgt = getattr(shardings, f.name)
        fields[f.name] = val if f.metadata.get("static") else place(val, tgt)
    return ClusterState(**fields)
