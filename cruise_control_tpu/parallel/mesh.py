"""Device-mesh sharding for the cluster model.

The 200K-partition replica axis is the framework's "long sequence": the
reference handles it with per-broker incremental search and sorted-replica
caches (SURVEY.md §5.7); here it is a sharded tensor dimension.  Replica-
major arrays shard across a 1-D `replica` mesh axis; broker/partition-level
arrays replicate.  Under jit, segment-sum load accounting over the sharded
replica axis lowers to per-shard partial sums + an all-reduce over ICI —
XLA inserts the collectives (psum pattern) from the sharding annotations
alone, which is the whole point of the pjit design: no hand-written
communication.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cruise_control_tpu.model.state import ClusterState

REPLICA_AXIS = "replica"


def make_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or given) devices, replica-axis parallel."""
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (REPLICA_AXIS,))


def _pad_to_multiple(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def pad_state(state: ClusterState, multiple: int) -> ClusterState:
    """Pad the replica axis so it divides the mesh size; padding rows are
    invalid replicas parked on broker 0."""
    num_r = state.num_replicas
    target = _pad_to_multiple(max(num_r, 1), multiple)
    if target == num_r:
        return state
    pad = target - num_r

    def pad_arr(x, fill):
        widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, widths, constant_values=fill)

    return state.replace(
        replica_valid=pad_arr(state.replica_valid, False),
        replica_partition=pad_arr(state.replica_partition, 0),
        replica_broker=pad_arr(state.replica_broker, 0),
        replica_disk=pad_arr(state.replica_disk, -1),
        replica_is_leader=pad_arr(state.replica_is_leader, False),
        replica_offline=pad_arr(state.replica_offline, False),
        replica_original_offline=pad_arr(state.replica_original_offline,
                                         False),
        replica_base_load=pad_arr(state.replica_base_load, 0.0),
    )


def state_shardings(state: ClusterState, mesh: Mesh) -> ClusterState:
    """A ClusterState-shaped pytree of NamedShardings: replica-axis arrays
    shard over the mesh, everything else replicates."""
    shard = NamedSharding(mesh, P(REPLICA_AXIS))
    shard2 = NamedSharding(mesh, P(REPLICA_AXIS, None))
    rep = NamedSharding(mesh, P())
    rep2 = NamedSharding(mesh, P(None, None))
    return ClusterState(
        replica_valid=shard,
        replica_partition=shard,
        replica_broker=shard,
        replica_disk=shard,
        replica_is_leader=shard,
        replica_offline=shard,
        replica_original_offline=shard,
        replica_base_load=shard2,
        partition_topic=rep,
        partition_leader_bonus=rep2,
        broker_alive=rep,
        broker_new=rep,
        broker_demoted=rep,
        broker_bad_disks=rep,
        broker_capacity=rep2,
        broker_rack=rep,
        broker_host=rep,
        disk_broker=rep,
        disk_capacity=rep,
        disk_alive=rep,
        num_racks=state.num_racks,
        num_hosts=state.num_hosts,
        num_topics=state.num_topics,
    )


def shard_state(state: ClusterState, mesh: Optional[Mesh] = None
                ) -> ClusterState:
    """Place a ClusterState onto the mesh with replica-axis sharding."""
    mesh = mesh or make_mesh()
    state = pad_state(state, mesh.size)
    shardings = state_shardings(state, mesh)

    def place(x, s):
        if isinstance(x, (int,)):
            return x
        return jax.device_put(x, s)

    fields = {}
    for f in dataclasses.fields(ClusterState):
        val = getattr(state, f.name)
        tgt = getattr(shardings, f.name)
        fields[f.name] = val if f.metadata.get("static") else place(val, tgt)
    return ClusterState(**fields)
