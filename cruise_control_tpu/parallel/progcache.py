"""Persistent compiled-program cache: the on-disk tier every pipeline
compile goes through.

BENCH_r05 put the warmup AOT compile at ~300s — 10x the solve it
enables — and every process bounce, tenant register() and ladder
re-probe re-paid it for programs compiled a thousand times before.
PR-5's power-of-two shape buckets and PR-6's ``@meshN`` program keys
already canonicalize geometry, so compiled executables are reusable
across restarts, tenants and mesh spans; this module makes them
DURABLE:

* **upper tier** — serialized StableHLO (``jax.export``) keyed by
  (program key incl. mesh span, goal-list signature, input-tree
  signature, environment fingerprint — see parallel/mesh.py).  A hit
  skips tracing the Python pipeline entirely;
* **lower tier** — the XLA persistent compilation cache
  (``jax_compilation_cache_dir``), which serves the backend compile of
  the deserialized module.  The compile gateways deliberately compile
  the ROUND-TRIPPED module even on a store (fresh compile), so the cold
  and warm paths share one XLA-cache key and cached-vs-fresh results
  are trivially identical.

Safety contract: a stale or mismatched entry is a MISS, never a wrong
answer.  The fingerprint covers jax/jaxlib version, backend + device
kind, and a content hash of the solver sources; an entry that fails to
deserialize is QUARANTINED (moved aside, ``progcache-corrupt-entries``
meter) and the caller falls back to the compile path.  Stores are
atomic (write-temp-then-rename), so two processes racing on one key
leave exactly one valid entry.

The process-wide singleton (`get_cache()`) starts DISABLED — nothing
changes for code that never configures it.  The facade configures it
from the ``progcache.*`` keys; ``progcache.dir`` empty keeps it off.
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time as _time
from typing import Dict, List, Optional

from cruise_control_tpu.utils import persist

LOG = logging.getLogger(__name__)

#: default size cap: 2 GiB of serialized StableHLO (entries at 2.6K-
#: broker scale run single-digit MBs; the cap evicts oldest-first)
DEFAULT_MAX_BYTES = 2 * 1024 * 1024 * 1024

_BLOB_SUFFIX = ".hlo"
_META_SUFFIX = ".json"
_QUARANTINE_DIR = "quarantine"

#: one-time jax.export pytree-serialization registration flag
_EXPORT_REGISTERED = False


def ensure_export_registrations() -> None:
    """Register the solver's custom pytree dataclasses with jax.export
    so their treedefs (including static aux fields: table widths,
    topology counts, option flags) serialize into the StableHLO
    envelope and round-trip exactly.  Idempotent; called lazily by the
    load/store paths so plain (cache-off) runs never import
    jax.export."""
    global _EXPORT_REGISTERED
    if _EXPORT_REGISTERED:
        return
    import pickle
    from jax import export as jexport
    from cruise_control_tpu.analyzer.context import (OptimizationContext,
                                                     RoundCache)
    from cruise_control_tpu.model.state import ClusterState
    from cruise_control_tpu.model.stats import ClusterModelStats
    for cls in (ClusterState, ClusterModelStats, OptimizationContext,
                RoundCache):
        try:
            jexport.register_pytree_node_serialization(
                cls,
                serialized_name=f"cruise_control_tpu.{cls.__name__}",
                serialize_auxdata=pickle.dumps,
                deserialize_auxdata=pickle.loads)
        except ValueError as exc:
            # already registered (module reload) — registration is
            # process-global in jax, the cache just needs it present
            LOG.debug("progcache: export registration of %s skipped: "
                      "%s", cls.__name__, exc)
    _EXPORT_REGISTERED = True


def _safe_name(program: str) -> str:
    """Filesystem-safe spelling of a program key (``__seg_0_4__@mesh8``
    is already safe; this guards plugin-provided names)."""
    return "".join(c if (c.isalnum() or c in "_@.-") else "_"
                   for c in program)


@dataclasses.dataclass
class CacheEntry:
    """One on-disk entry (blob + sidecar meta) as the CLI sees it."""

    path: str
    program: str
    goal_sig: str
    shape_sig: str
    fingerprint: str
    size_bytes: int
    age_s: float
    hits: int
    meta: dict

    def to_json(self) -> dict:
        return {
            "path": self.path,
            "program": self.program,
            "goalSig": self.goal_sig,
            "shapeSig": self.shape_sig,
            "fingerprint": self.fingerprint,
            "sizeBytes": self.size_bytes,
            "ageS": round(self.age_s, 1),
            "hits": self.hits,
        }


class ProgramCache:
    """Disk-backed program cache (see module docstring).

    All methods are safe to call while disabled (they no-op / miss), so
    the compile gateways need no enabled-checks of their own — the
    byte-identical-when-disabled guarantee costs one attribute read."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.enabled = False
        self.cache_dir: Optional[str] = None
        self.max_bytes = DEFAULT_MAX_BYTES
        self.fingerprint_override: Optional[str] = None
        # counters (exported as progcache-* sensors by the facade)
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.corrupt_entries = 0
        self.evictions = 0
        self.export_errors = 0
        #: compiles that had to TRACE a source program (cache miss or
        #: cache off) — the coldstart bench pins this to 0 on a warm run
        self.fresh_compiles = 0

    # ------------------------------------------------------------------
    # configuration
    # ------------------------------------------------------------------
    def configure(self, enabled: Optional[bool] = None,
                  cache_dir: Optional[str] = None,
                  max_bytes: Optional[int] = None,
                  fingerprint_override: Optional[str] = None) -> None:
        """Apply the progcache.* config; None leaves a field unchanged
        (multi-tenant facades configure the shared singleton with
        identical values, so re-configuration is idempotent)."""
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if cache_dir is not None:
                self.cache_dir = cache_dir or None
            if max_bytes is not None and max_bytes > 0:
                self.max_bytes = int(max_bytes)
            if fingerprint_override is not None:
                self.fingerprint_override = fingerprint_override or None

    @property
    def active(self) -> bool:
        return self.enabled and bool(self.cache_dir)

    def is_active(self, goal_sig: Optional[str]) -> bool:
        """Usable for this goal list?  A None signature (unshareable
        goal state) never touches disk."""
        return self.active and goal_sig is not None

    def fingerprint(self) -> str:
        from cruise_control_tpu.parallel.mesh import program_fingerprint
        return program_fingerprint(self.fingerprint_override)

    # ------------------------------------------------------------------
    # paths
    # ------------------------------------------------------------------
    def _entry_base(self, program: str, goal_sig: str,
                    shape_sig: str) -> str:
        return os.path.join(self.cache_dir, self.fingerprint(), goal_sig,
                            f"{_safe_name(program)}.{shape_sig}")

    # ------------------------------------------------------------------
    # load / store
    # ------------------------------------------------------------------
    def load_exported(self, program: str, goal_sig: Optional[str],
                      shape_sig: str):
        """The stored ``jax.export.Exported`` for a key, or None (miss).
        Corrupt/undeserializable entries are quarantined, metered, and
        reported as misses — the caller falls back to compiling."""
        if not self.is_active(goal_sig):
            return None
        from cruise_control_tpu.obs import trace as obs_trace
        base = self._entry_base(program, goal_sig, shape_sig)
        path = base + _BLOB_SUFFIX
        if not os.path.exists(path):
            with self._lock:
                self.misses += 1
            obs_trace.event("progcache.miss", program=program)
            return None
        try:
            from jax import export as jexport
            ensure_export_registrations()
            with open(path, "rb") as fh:
                blob = fh.read()
            exported = jexport.deserialize(bytearray(blob))
        except Exception as exc:  # noqa: BLE001 - ANY bad entry is a miss
            LOG.warning("progcache: corrupt entry %s (%s): quarantined, "
                        "falling back to compile", path,
                        str(exc).splitlines()[0][:120])
            self.quarantine(program, goal_sig, shape_sig)
            with self._lock:
                self.misses += 1
            return None
        with self._lock:
            self.hits += 1
        # hit/miss/hydrate ride the active solve trace (no-op outside
        # one): a cold-start trace shows WHICH programs compiled fresh
        obs_trace.event("progcache.hit", program=program)
        self._bump_meta_hits(base)
        return exported

    def store(self, program: str, goal_sig: Optional[str],
              shape_sig: str, blob: bytes,
              meta_extra: Optional[dict] = None) -> Optional[str]:
        """Atomically persist one serialized export (+ sidecar meta).
        write-temp-then-rename: concurrent writers of the same key each
        publish a complete file and the LAST rename wins — a reader can
        never observe a torn entry.  Returns the blob path, or None
        when inactive or the write failed (disk full etc. must never
        fail the solve that produced the program)."""
        if not self.is_active(goal_sig):
            return None
        base = self._entry_base(program, goal_sig, shape_sig)
        meta = {
            "program": program,
            "goalSig": goal_sig,
            "shapeSig": shape_sig,
            "fingerprint": self.fingerprint(),
            "createdAt": _time.time(),
            "sizeBytes": len(blob),
            "hits": 0,
        }
        meta.update(meta_extra or {})
        try:
            os.makedirs(os.path.dirname(base), exist_ok=True)
            self._atomic_write(base + _BLOB_SUFFIX, blob)
            self._atomic_write(base + _META_SUFFIX,
                               json.dumps(meta, indent=1).encode())
        except OSError as exc:
            LOG.warning("progcache: store of %s failed (%s); entry "
                        "skipped (solve unaffected)", program, exc)
            return None
        with self._lock:
            self.stores += 1
        self._enforce_size_cap()
        return base + _BLOB_SUFFIX

    def _atomic_write(self, path: str, data: bytes) -> None:
        # the shared durable-write helper (utils/persist.py): same
        # write-temp-then-rename contract this cache always had, now
        # one audited implementation for every store in the framework
        persist.atomic_write(path, data)

    def _bump_meta_hits(self, base: str) -> None:
        """Best-effort hit accounting in the sidecar (operator CLI
        telemetry only; failures are irrelevant to correctness)."""
        path = base + _META_SUFFIX
        try:
            with open(path) as fh:
                meta = json.load(fh)
            meta["hits"] = int(meta.get("hits", 0)) + 1
            meta["lastHitAt"] = _time.time()
            self._atomic_write(path, json.dumps(meta, indent=1).encode())
        except (OSError, ValueError) as exc:
            LOG.debug("progcache: hit-count update of %s skipped: %s",
                      path, exc)

    def quarantine(self, program: str, goal_sig: str,
                   shape_sig: str) -> None:
        """Move a bad entry (blob + meta) aside so it cannot be served
        again; increments `corrupt_entries` (the
        progcache-corrupt-entries meter)."""
        base = self._entry_base(program, goal_sig, shape_sig)
        qdir = os.path.join(self.cache_dir, _QUARANTINE_DIR)
        try:
            os.makedirs(qdir, exist_ok=True)
            stamp = f"{int(_time.time() * 1e3):x}"
            for suffix in (_BLOB_SUFFIX, _META_SUFFIX):
                src = base + suffix
                if os.path.exists(src):
                    persist.replace(src, os.path.join(
                        qdir,
                        f"{os.path.basename(base)}.{stamp}{suffix}"))
        except OSError as exc:
            LOG.warning("progcache: quarantine of %s failed: %s", base,
                        exc)
        with self._lock:
            self.corrupt_entries += 1

    def flush(self) -> int:
        """Settle the cache directory for shutdown: stores are
        write-through (atomic write-temp-then-rename at compile time),
        so the only pending state is temp files orphaned by a writer
        that died mid-store — sweep them and fsync the directory so
        the rename journal reaches disk before the process exits (the
        graceful-drain path calls this after the last solve).  Returns
        the number of orphans swept; safe (0) when inactive."""
        if not self.active:
            return 0
        swept = 0

        def fsync_dir(path: str) -> None:
            fd = os.open(path, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)

        try:
            # entries live in nested <fingerprint>/<goal_sig>/ dirs and
            # _atomic_write creates its temp files NEXT TO the entry —
            # walk the whole tree, and fsync each directory so the
            # renames' journal entries reach disk where they happened
            for dirpath, _dirnames, filenames in os.walk(self.cache_dir):
                for name in filenames:
                    if name.startswith(".tmp-") and name.endswith("~"):
                        try:
                            os.unlink(os.path.join(dirpath, name))
                            swept += 1
                        except OSError:
                            pass
                try:
                    fsync_dir(dirpath)
                except OSError:
                    pass
        except OSError as exc:
            LOG.debug("progcache: flush skipped (%s)", exc)
        return swept

    # ------------------------------------------------------------------
    # accounting used by the compile gateways
    # ------------------------------------------------------------------
    def count_fresh_compile(self) -> None:
        with self._lock:
            self.fresh_compiles += 1

    def count_export_error(self) -> None:
        with self._lock:
            self.export_errors += 1

    def reset_counters(self) -> None:
        with self._lock:
            self.hits = self.misses = self.stores = 0
            self.corrupt_entries = self.evictions = 0
            self.export_errors = self.fresh_compiles = 0

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "dir": self.cache_dir,
                "hits": self.hits,
                "misses": self.misses,
                "stores": self.stores,
                "corruptEntries": self.corrupt_entries,
                "evictions": self.evictions,
                "exportErrors": self.export_errors,
                "freshCompiles": self.fresh_compiles,
            }

    # ------------------------------------------------------------------
    # enumeration / eviction (hydration + operator CLI)
    # ------------------------------------------------------------------
    def entries(self, goal_sig: Optional[str] = None,
                all_fingerprints: bool = False) -> List[CacheEntry]:
        """On-disk entries, oldest first.  By default only the CURRENT
        fingerprint's entries (the addressable ones); the CLI passes
        all_fingerprints=True to show stale generations too."""
        if not self.active:
            return []
        now = _time.time()
        out: List[CacheEntry] = []
        try:
            fingerprints = sorted(os.listdir(self.cache_dir))
        except OSError:
            return []
        current = self.fingerprint()
        for fp in fingerprints:
            if fp == _QUARANTINE_DIR:
                continue
            if not all_fingerprints and fp != current:
                continue
            fp_dir = os.path.join(self.cache_dir, fp)
            if not os.path.isdir(fp_dir):
                continue
            for gs in sorted(os.listdir(fp_dir)):
                if goal_sig is not None and gs != goal_sig:
                    continue
                gdir = os.path.join(fp_dir, gs)
                if not os.path.isdir(gdir):
                    continue
                for name in sorted(os.listdir(gdir)):
                    if not name.endswith(_BLOB_SUFFIX):
                        continue
                    path = os.path.join(gdir, name)
                    meta = {}
                    try:
                        with open(path[:-len(_BLOB_SUFFIX)]
                                  + _META_SUFFIX) as fh:
                            meta = json.load(fh)
                    except (OSError, ValueError):
                        pass
                    stem = name[:-len(_BLOB_SUFFIX)]
                    program, _, shape_sig = stem.rpartition(".")
                    try:
                        st = os.stat(path)
                    except OSError:
                        continue
                    out.append(CacheEntry(
                        path=path,
                        program=meta.get("program", program),
                        goal_sig=gs, shape_sig=shape_sig,
                        fingerprint=fp, size_bytes=st.st_size,
                        age_s=max(0.0, now - st.st_mtime),
                        hits=int(meta.get("hits", 0)), meta=meta))
        out.sort(key=lambda e: -e.age_s)
        return out

    def evict_entry(self, entry: CacheEntry) -> bool:
        try:
            os.unlink(entry.path)
            meta = entry.path[:-len(_BLOB_SUFFIX)] + _META_SUFFIX
            if os.path.exists(meta):
                os.unlink(meta)
        except OSError as exc:
            LOG.warning("progcache: eviction of %s failed: %s",
                        entry.path, exc)
            return False
        with self._lock:
            self.evictions += 1
        return True

    def _enforce_size_cap(self) -> None:
        entries = self.entries(all_fingerprints=True)
        total = sum(e.size_bytes for e in entries)
        if total <= self.max_bytes:
            return
        for entry in entries:          # oldest first
            if total <= self.max_bytes:
                break
            if self.evict_entry(entry):
                total -= entry.size_bytes
                LOG.info("progcache: size cap %d exceeded; evicted %s "
                         "(%d bytes)", self.max_bytes, entry.path,
                         entry.size_bytes)


#: process-wide singleton — one disk cache serves every optimizer,
#: scenario engine and tenant in the process (sharing across tenants in
#: one bucket is the whole point)
_CACHE = ProgramCache()


def get_cache() -> ProgramCache:
    return _CACHE


def configure(enabled: Optional[bool] = None,
              cache_dir: Optional[str] = None,
              max_bytes: Optional[int] = None,
              fingerprint_override: Optional[str] = None) -> ProgramCache:
    _CACHE.configure(enabled=enabled, cache_dir=cache_dir,
                     max_bytes=max_bytes,
                     fingerprint_override=fingerprint_override)
    return _CACHE


#: export-metadata helper shared by the optimizer/engine gateways
def export_meta(exported) -> Dict[str, object]:
    import jax
    import jaxlib
    return {
        "jaxVersion": jax.__version__,
        "jaxlibVersion": jaxlib.__version__,
        "backend": jax.default_backend(),
        "nrDevices": int(getattr(exported, "nr_devices", 1)),
    }
