"""Mesh supervisor: watched device dispatch, chip probing, and the
span-shrink ladder that lets the serving loop ride through chip loss
and wedged collectives without a process bounce.

PR 6 made the production solve depend on every chip in the mesh, which
multiplied the blast radius of one bad device: a wedged all-reduce
captures the scheduler's single dispatch thread FOREVER (Python cannot
abort an XLA dispatch), and a dead chip fails every whole-mesh solve
until someone bounces the process.  This module applies the paper's
detect→degrade→recover discipline to our own substrate — the mesh —
in three pieces:

* **watched dispatch** (`watched_call`): device execution runs on a
  watched worker thread under a `mesh.watchdog.ms` deadline.  A wedged
  dispatch is ABANDONED — the worker thread stays blocked (nothing can
  unblock it) but is replaced, its executable is quarantined, and the
  dispatch thread gets `DispatchWedgedError` within the deadline
  instead of hanging forever.  Disarmed (the default, and whenever no
  deadline is configured) the gateway is a plain call — byte-identical
  behavior, one fault-site check of overhead.

* **per-chip probe** (`probe_devices`): a tiny per-device program (the
  degenerate single-chip case of the `('replica',)` all-reduce) run
  under its own deadline on a fresh thread per device, so a dead or
  wedged chip shows up as a probe failure instead of hanging the
  prober.  Fault sites `mesh.probe` / `mesh.probe.dev<N>` make chip
  loss scriptable on the virtual 8-CPU rig.

* **span ladder** (`MeshSupervisor`): the PR-6 `SolverRung.MESH` rung
  generalized to SPAN-parameterized rungs — MESH8→MESH4→MESH2→FUSED.
  On a wedge or collective failure the supervisor condemns failing
  devices and rebuilds the MeshToken over survivors one span down
  (span 1 = the degenerate single-chip token, i.e. exactly FUSED);
  the facade then hydrates the shrunk span's `@meshN` programs from
  the persistent program cache (PR 7), so a shrink costs seconds, not
  a 300s recompile.  Probe recovery climbs back one span per probe
  cycle — the same one-rung-per-solve discipline as the PR-2 ladder.

The supervisor is owned by the scheduler (one per process/fleet, like
the mesh token it wraps) and consulted per dispatch, so every consumer
— request solves, scenario lanes, fleet folds — re-shards over the
surviving span automatically.
"""
from __future__ import annotations

import contextlib
import logging
import queue as queue_mod
import threading
import time as _time
from typing import Callable, List, Optional

from cruise_control_tpu.obs import trace as obs_trace
from cruise_control_tpu.parallel.mesh import MeshToken, make_mesh
from cruise_control_tpu.sched.runtime import SolvePreempted
from cruise_control_tpu.utils import faults

LOG = logging.getLogger(__name__)


class DispatchWedgedError(RuntimeError):
    """A watched device dispatch overran its watchdog deadline: the
    worker thread is presumed wedged (stuck collective, dead chip,
    hung transport) and has been abandoned.  `program` names the
    executable that wedged (now quarantined); classified as WEDGE by
    the degradation ladder."""

    def __init__(self, site: str, program: Optional[str] = None,
                 deadline_ms: float = 0.0) -> None:
        super().__init__(
            f"device dispatch at {site} "
            f"({program or 'unknown program'}) exceeded its "
            f"{deadline_ms:.0f}ms watchdog deadline; worker abandoned")
        self.site = site
        self.program = program
        self.deadline_ms = deadline_ms


class MeshRecoveryRequeue(SolvePreempted):
    """Control flow, not an error: the mesh supervisor shrank the span
    under an in-flight scheduled solve — the dispatch loop re-queues
    the job (aging intact, exactly the PR-4 preemption machinery) and
    the redispatch solves on the surviving span.  Raised only under an
    asynchronous dispatch; inline solves retry on the shrunk span in
    place."""


# ---------------------------------------------------------------------------
# watched dispatch gateway
# ---------------------------------------------------------------------------

#: process-wide watchdog switch (progcache configure pattern: only an
#: EXPLICIT facade/config setting touches it, so embedders and the
#: existing test suite see zero behavior change)
_WATCHDOG = {"enabled": False, "deadline_ms": 0.0}
_WATCH_LOCK = threading.Lock()
#: lifetime watchdog fires in this process (the mesh-watchdog-fires
#: sensor reads it)
_FIRES = 0
#: lifetime watched-dispatch count (armed or not) — the per-solve
#: dispatch-budget instrument: every AOT program invocation goes
#: through watched_call, so `dispatch_count()` deltas around a warmed
#: solve measure its device dispatches (bench table + the
#: dispatch-count pin in tests/test_dispatch_budget.py).  The inline
#: jit fallback is NOT counted — it may be a cold compile, which is
#: not a dispatch-budget question — so counters are only meaningful
#: after warmup()/hydration.
_DISPATCHES = 0
#: per-program-key dispatch counts (bounded by the program keyspace:
#: a few dozen pipeline keys per goal list)
_DISPATCHES_BY_PROGRAM: dict = {}
#: wall seconds the dispatch thread was actually blocked at the last
#: fire — the meshchaos bench's released-in-time assertion
_LAST_FIRE_WAIT_S = 0.0
#: program keys whose executable wedged a worker -> monotonic expiry:
#: dispatching them again would likely wedge the replacement too, so
#: they are refused for a bounded cooldown.  TIME-BOUNDED on purpose —
#: on a single-chip facade there is no supervisor to clear the set, and
#: a legitimate one-off overrun (deadline set too tight for the slowest
#: segment) must not pin the process degraded until restart.  Probe
#: recovery at full span still clears it early.
_QUARANTINED: dict = {}
#: quarantine cooldown = max(this floor, 4x deadline) — long enough
#: that a genuinely wedged program is not immediately re-dispatched,
#: short enough that a false fire self-heals
_QUARANTINE_MIN_TTL_S = 60.0


def configure_watchdog(enabled: Optional[bool] = None,
                       deadline_ms: Optional[float] = None) -> None:
    with _WATCH_LOCK:
        if enabled is not None:
            _WATCHDOG["enabled"] = bool(enabled)
        if deadline_ms is not None:
            _WATCHDOG["deadline_ms"] = float(deadline_ms)


def watchdog_config() -> dict:
    with _WATCH_LOCK:
        return dict(_WATCHDOG)


def watchdog_fires() -> int:
    return _FIRES


def dispatch_count() -> int:
    """Lifetime watched-dispatch count (see _DISPATCHES)."""
    return _DISPATCHES


def dispatches_by_program() -> dict:
    """Snapshot of per-program-key watched-dispatch counts."""
    with _WATCH_LOCK:
        return dict(_DISPATCHES_BY_PROGRAM)


def _count_dispatch(program: Optional[str]) -> None:
    global _DISPATCHES
    with _WATCH_LOCK:
        _DISPATCHES += 1
        if program:
            _DISPATCHES_BY_PROGRAM[program] = \
                _DISPATCHES_BY_PROGRAM.get(program, 0) + 1


def last_fire_wait_s() -> float:
    return _LAST_FIRE_WAIT_S


def quarantine_program(key: Optional[str],
                       deadline_ms: float = 0.0) -> None:
    if key:
        ttl = max(_QUARANTINE_MIN_TTL_S, 4.0 * deadline_ms / 1000.0)
        with _WATCH_LOCK:
            _QUARANTINED[key] = _time.monotonic() + ttl


def is_quarantined(key: Optional[str]) -> bool:
    if not key:
        return False
    with _WATCH_LOCK:
        expiry = _QUARANTINED.get(key)
        if expiry is None:
            return False
        if _time.monotonic() >= expiry:
            del _QUARANTINED[key]
            return False
        return True


def clear_quarantine() -> None:
    with _WATCH_LOCK:
        _QUARANTINED.clear()


class _Call:
    __slots__ = ("fn", "done", "result", "exc", "abandoned")

    def __init__(self, fn) -> None:
        self.fn = fn
        self.done = threading.Event()
        self.result = None
        self.exc: Optional[BaseException] = None
        self.abandoned = False


class _Worker:
    """One watched worker thread with its own queue.  A wedged worker
    is abandoned in place (its thread stays blocked on the wedged
    dispatch until the process exits — daemon) and replaced; when the
    wedge eventually releases, the worker sees it was abandoned,
    discards the result and exits instead of racing its successor."""

    def __init__(self) -> None:
        self.queue: "queue_mod.Queue[_Call]" = queue_mod.Queue()
        self.abandoned = False
        self.thread = threading.Thread(target=self._loop,
                                       name="watched-dispatch",
                                       daemon=True)
        self.thread.start()

    def _loop(self) -> None:
        while True:
            call = self.queue.get()
            try:
                call.result = call.fn()
            except BaseException as exc:  # noqa: BLE001 - relayed
                call.exc = exc
                LOG.debug("watched dispatch raised %s (relayed to the "
                          "caller)", type(exc).__name__)
            call.done.set()
            if self.abandoned:
                return


#: one watched worker PER CALLING THREAD (not one global): concurrent
#: inline solves (scheduler disabled, USER_TASKS pool threads) must not
#: queue behind each other inside the gateway — a shared worker would
#: both serialize previously-parallel dispatches and count the queue
#: wait against the deadline, firing the watchdog on a healthy program
#: that merely waited its turn.  The caller population is bounded (the
#: dispatch thread, the USER_TASKS pool, the precompute thread), so the
#: idle-worker cost is a handful of parked daemon threads.
_WORKER_TLS = threading.local()


def _current_worker() -> _Worker:
    worker = getattr(_WORKER_TLS, "worker", None)
    if worker is None or worker.abandoned \
            or not worker.thread.is_alive():
        worker = _Worker()
        _WORKER_TLS.worker = worker
    return worker


def _abandon_worker(worker: _Worker) -> None:
    worker.abandoned = True
    if getattr(_WORKER_TLS, "worker", None) is worker:
        _WORKER_TLS.worker = None


def watched_call(fn: Callable[[], object], *,
                 program: Optional[str] = None,
                 site: str = "mesh.dispatch"):
    """THE device-execution gateway (watchdog-gateway lint rule): every
    compiled-program invocation — the optimizer's AOT/shared
    executables, the scenario engine's batched programs — runs through
    here.  Disarmed, it is the direct call plus one fault-site check;
    armed (mesh.watchdog.ms via the facade), the call runs on the
    watched worker under the deadline and a wedge surfaces as
    `DispatchWedgedError` on the CALLING thread within the deadline.

    The `site` fault point fires on whichever thread executes the
    program, so a scripted hang (FaultPlan.hang_nth) wedges the worker
    exactly like a stuck collective would."""
    cfg = watchdog_config()
    armed = cfg["enabled"] and cfg["deadline_ms"] > 0
    _count_dispatch(program)

    def _invoke():
        faults.inject(site)
        return fn()

    if not armed:
        return _invoke()
    if is_quarantined(program):
        raise DispatchWedgedError(site, program, cfg["deadline_ms"])
    worker = _current_worker()
    call = _Call(_invoke)
    t0 = _time.monotonic()
    worker.queue.put(call)
    if not call.done.wait(cfg["deadline_ms"] / 1000.0):
        global _FIRES, _LAST_FIRE_WAIT_S
        call.abandoned = True
        _abandon_worker(worker)
        with _WATCH_LOCK:
            _FIRES += 1
            _LAST_FIRE_WAIT_S = _time.monotonic() - t0
        quarantine_program(program, deadline_ms=cfg["deadline_ms"])
        LOG.error("watchdog: dispatch of %s at %s exceeded %.0fms; "
                  "worker thread abandoned, executable quarantined",
                  program or "<unknown>", site, cfg["deadline_ms"])
        raise DispatchWedgedError(site, program, cfg["deadline_ms"])
    if call.exc is not None:
        raise call.exc
    return call.result


# ---------------------------------------------------------------------------
# per-chip probe
# ---------------------------------------------------------------------------

_PROBE_FN = None


def _probe_fn():
    """The probe program, compiled once: the single-chip degenerate
    case of the ('replica',) all-reduce — a tiny reduction whose known
    answer proves the device still computes.  jax.jit here is
    sanctioned (cache-gateway allowlist): a four-float reduction is
    not persistent-cache material."""
    global _PROBE_FN
    if _PROBE_FN is None:
        import jax
        import jax.numpy as jnp
        _PROBE_FN = jax.jit(lambda a: jnp.sum(a) * 2.0)
    return _PROBE_FN


def _probe_one(device) -> None:
    import jax
    import numpy as np
    faults.inject("mesh.probe")
    faults.inject(f"mesh.probe.dev{device.id}")
    x = jax.device_put(np.arange(4, dtype=np.float32), device)
    got = float(jax.device_get(_probe_fn()(x)))
    if got != 12.0:
        raise RuntimeError(f"probe on {device} computed {got} != 12.0")


#: device id -> still-running probe thread from an earlier cycle: a
#: chip wedged hard enough to HANG its probe (rather than raise) keeps
#: exactly ONE abandoned thread parked per device — later probe cycles
#: see the old thread still alive and fail the device immediately
#: instead of leaking a fresh blocked thread every interval
_PROBE_WEDGED: dict = {}
_PROBE_LOCK = threading.Lock()


def probe_devices(devices, deadline_ms: float = 2000.0):
    """(healthy, failed) split of `devices`: each device runs the probe
    program on its own daemon thread under `deadline_ms` — a wedged
    chip times out (thread abandoned; at most one parked thread per
    device, see _PROBE_WEDGED) instead of hanging the prober, and one
    bad device cannot shadow the others' verdicts."""
    results = {}
    threads = {}

    def run(d):
        try:
            _probe_one(d)
            results[d.id] = None
        except BaseException as exc:  # noqa: BLE001 - verdict, not crash
            results[d.id] = exc
            LOG.warning("mesh probe failed on device %s: %s: %s", d.id,
                        type(exc).__name__, exc)

    for d in devices:
        with _PROBE_LOCK:
            stuck = _PROBE_WEDGED.get(d.id)
            if stuck is not None and stuck.is_alive():
                continue             # prior probe still wedged: fail it
            _PROBE_WEDGED.pop(d.id, None)
        t = threading.Thread(target=run, args=(d,),
                             name=f"mesh-probe-{d.id}", daemon=True)
        t.start()
        threads[d.id] = t
    deadline = _time.monotonic() + deadline_ms / 1000.0
    for t in threads.values():
        t.join(max(0.0, deadline - _time.monotonic()))
    healthy, failed = [], []
    for d in devices:
        t = threads.get(d.id)
        if t is not None and t.is_alive():
            with _PROBE_LOCK:
                _PROBE_WEDGED[d.id] = t      # hung, not erroring
        if d.id in results and results[d.id] is None:
            healthy.append(d)
        else:
            failed.append(d)
    return healthy, failed


# ---------------------------------------------------------------------------
# span ladder + supervisor
# ---------------------------------------------------------------------------

def span_ladder(n_devices: int, min_devices: int = 1) -> List[int]:
    """Descending halving spans ending at the degenerate single chip:
    8 → [8, 4, 2, 1].  Spans below `min_devices` are skipped (except
    the terminal 1 — below the minimum the mesh is not worth its
    collectives and service drops straight to single-chip FUSED)."""
    spans: List[int] = []
    s = max(1, n_devices)
    while s > 1:
        if s >= max(2, min_devices):
            spans.append(s)
        s //= 2
    spans.append(1)
    return spans


class MeshSupervisor:
    """Runtime health authority for one solve mesh.

    Wraps the scheduler's base MeshToken: `current_token()` is the
    LIVE topology — the first `span` healthy (non-condemned) devices —
    and every dispatch resolves through it, so a shrink between
    dispatches re-shards request solves, scenario lanes and fleet
    folds alike.  Thread-safe; one instance per scheduler (fleet-wide
    under shared scheduling, exactly like the token it supervises).

    `mesh.recovery.enabled=false` is the manual override: the
    supervisor still reports (probes can be run via tools), but
    failures fall through to the classic MESH→FUSED ladder descent of
    PR 6 — the pre-PR-12 behavior."""

    def __init__(self, base_token: MeshToken, *,
                 enabled: bool = True,
                 watchdog_ms: float = 120_000.0,
                 probe_interval_ms: float = 15_000.0,
                 min_devices: int = 1,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self.recovery_enabled = bool(enabled)
        self.watchdog_ms = float(watchdog_ms)
        self.probe_interval_ms = float(probe_interval_ms)
        self.min_devices = max(1, int(min_devices))
        self._time = time_fn or _time.time
        self._lock = threading.Lock()
        self._base_token = base_token
        self._devices = (list(base_token.mesh.devices.flat)
                         if base_token.is_multichip else [])
        self._ladder = span_ladder(len(self._devices) or 1,
                                   self.min_devices)
        self._span = self._ladder[0]
        self._condemned: set = set()        # device ids
        self._token = base_token
        # counters (sensor food)
        self.shrinks = 0
        self.probe_failures = 0
        self.recoveries = 0
        self._last_change_at = -float("inf")
        self._last_probe_at = -float("inf")

    # -- topology ------------------------------------------------------
    @property
    def span(self) -> int:
        with self._lock:
            return self._span

    @property
    def condemned(self) -> List[int]:
        with self._lock:
            return sorted(self._condemned)

    def current_token(self) -> MeshToken:
        with self._lock:
            return self._token

    def _probe_deadline_ms(self) -> float:
        """Per-chip probe deadline: capped by the watchdog deadline but
        FLOORED at 250ms and defaulting to 5s when the watchdog is
        disarmed (watchdog_ms=0 disables the DISPATCH watchdog, it must
        not give probes a zero deadline that condemns every healthy
        chip)."""
        base = self.watchdog_ms if self.watchdog_ms > 0 else 5000.0
        return max(250.0, min(base, 5000.0))

    def _healthy_locked(self) -> list:
        return [d for d in self._devices if d.id not in self._condemned]

    def _rebuild_locked(self) -> None:
        """Rebuild the live token AND normalize the span to a ladder
        width the healthy set can actually fill: `healthy[:span]` with
        fewer survivors than the span would silently build a
        non-ladder-width mesh (e.g. 3 chips) that no `@meshN` cache
        entry or warmup ever covered — the span steps down to the
        largest feasible rung instead, so span and token never
        disagree."""
        healthy = self._healthy_locked()
        target = 1
        for s in self._ladder:               # descending: first fit =
            if s <= self._span and s <= len(healthy):
                target = s                   # largest feasible
                break
        self._span = target
        if target <= 1 or len(healthy) < 2:
            self._token = MeshToken(None)
        else:
            self._token = MeshToken(make_mesh(healthy[:target]))

    def _feasible_below_locked(self, span: int,
                               healthy: int) -> Optional[int]:
        for s in self._ladder:
            if s < span and s <= healthy:
                return s
        return None

    # -- failure handling ----------------------------------------------
    def handle_wedge(self, program: Optional[str] = None
                     ) -> Optional[dict]:
        """A watched dispatch wedged at the current span.  No probe
        (nothing measurable failed — the wedge may be transient): step
        ONE span down so the redispatch stops depending on whichever
        chip/collective wedged.  Returns a shrink summary, or None
        when recovery is disabled or the span is already degenerate
        (the classic ladder takes over)."""
        if not self.recovery_enabled:
            return None
        with self._lock:
            if self._span <= 1:
                return None
            from_span = self._span
            nxt = self._feasible_below_locked(from_span,
                                              len(self._healthy_locked()))
            self._span = nxt if nxt is not None else 1
            self._rebuild_locked()
            self.shrinks += 1
            self._last_change_at = self._time()
            to_span, condemned = self._span, sorted(self._condemned)
        LOG.warning("mesh supervisor: wedged dispatch (%s) — span "
                    "%d -> %d", program or "?", from_span, to_span)
        return {"fromSpan": from_span, "toSpan": to_span,
                "condemned": condemned, "wedged": True,
                "program": program}

    def handle_collective_failure(self) -> Optional[dict]:
        """A mesh-rung solve FAILED (collective error, chip loss).
        Probe every device, condemn the failures, and rebuild one span
        down (lower still when survivors demand it).  Returns a shrink
        summary, or None when recovery is disabled or there is nothing
        left to shrink."""
        if not self.recovery_enabled:
            return None
        with self._lock:
            if self._span <= 1:
                return None
            devices = list(self._devices)
            from_span = self._span
        with obs_trace.span("mesh.probe", devices=len(devices)):
            _healthy, failed = probe_devices(
                devices, deadline_ms=self._probe_deadline_ms())
        with self._lock:
            newly = {d.id for d in failed} - self._condemned
            self._condemned |= {d.id for d in failed}
            self.probe_failures += len(newly)
            self._last_probe_at = self._time()
            if not newly:
                # every chip answered: the failure was transient (or
                # not mesh material at all) — shrinking would degrade
                # capacity without fixing anything.  Hand the failure
                # back to the classic ladder, which retries at the
                # CURRENT span with backoff before descending
                # MESH→FUSED (hangs are different: handle_wedge shrinks
                # un-probed, because re-dispatching the same span
                # likely re-wedges).
                LOG.info("mesh supervisor: collective failure but every "
                         "probe answered — span %d kept, classic ladder "
                         "handles the retry", from_span)
                return None
            nxt = self._feasible_below_locked(
                from_span, len(self._healthy_locked()))
            self._span = nxt if nxt is not None else 1
            self._rebuild_locked()
            self.shrinks += 1
            self._last_change_at = self._time()
            to_span, condemned = self._span, sorted(self._condemned)
        LOG.warning("mesh supervisor: collective failure — probe "
                    "condemned %s; span %d -> %d",
                    condemned or "none", from_span, to_span)
        return {"fromSpan": from_span, "toSpan": to_span,
                "condemned": condemned, "wedged": False,
                "program": None}

    # -- recovery ------------------------------------------------------
    def maybe_recover(self) -> bool:
        """Probe-gated climb-back, one span per probe cycle: when the
        probe interval has elapsed since the last change, re-probe the
        full device set; recovered chips leave the condemned set and
        the span climbs ONE ladder rung if the healthy count supports
        it.  Back at the full span with nothing condemned, the
        program quarantine is cleared (the wedged executables' devices
        proved healthy).  Returns True when the span climbed."""
        if not self.recovery_enabled:
            return False
        with self._lock:
            if self._span >= self._ladder[0] and not self._condemned:
                return False
            now = self._time()
            since = (now - max(self._last_change_at,
                               self._last_probe_at)) * 1000.0
            if since < max(self.probe_interval_ms, 1.0):
                return False
            self._last_probe_at = now
            devices = list(self._devices)
            from_span = self._span
        with obs_trace.span("mesh.probe", devices=len(devices),
                            recovery=True):
            healthy, failed = probe_devices(
                devices, deadline_ms=self._probe_deadline_ms())
        with self._lock:
            newly = {d.id for d in failed} - self._condemned
            self._condemned = {d.id for d in failed}
            self.probe_failures += len(newly)
            target = None
            for s in self._ladder:           # descending
                if s > from_span and s <= len(self._healthy_locked()):
                    target = s               # keep the SMALLEST above
            if target is None:
                self._rebuild_locked()       # condemned set may have
                return False                 # changed under same span
            # one rung per probe cycle: the smallest feasible span
            # above the current one
            self._span = target
            self._rebuild_locked()
            self.recoveries += 1
            self._last_change_at = self._time()
            to_span = self._span
            clear = (to_span >= self._ladder[0]
                     and not self._condemned)
        if clear:
            clear_quarantine()
        LOG.info("mesh supervisor: probe recovery — span %d -> %d "
                 "(condemned now %s)", from_span, to_span,
                 self.condemned or "none")
        return True

    # -- reporting -----------------------------------------------------
    def to_json(self) -> dict:
        with self._lock:
            return {
                "enabled": self.recovery_enabled,
                "span": self._span,
                "fullSpan": self._ladder[0],
                "spanLadder": list(self._ladder),
                "condemnedDevices": sorted(self._condemned),
                "shrinks": self.shrinks,
                "probeFailures": self.probe_failures,
                "recoveries": self.recoveries,
                "watchdogMs": self.watchdog_ms,
                "watchdogFires": watchdog_fires(),
                "probeIntervalMs": self.probe_interval_ms,
                "minDevices": self.min_devices,
            }


@contextlib.contextmanager
def watchdog_armed(deadline_ms: float):
    """Scoped watchdog arming for tests/tools: arm, yield, restore."""
    prev = watchdog_config()
    configure_watchdog(enabled=True, deadline_ms=deadline_ms)
    try:
        yield
    finally:
        configure_watchdog(enabled=prev["enabled"],
                           deadline_ms=prev["deadline_ms"])
