"""Python client for the REST API.

Reference cruise-control-client/ (~2K LoC): Endpoint classes with allowed
parameters, a Responder that long-polls async responses via the
`User-Task-ID` header, and the `cccli` CLI on top.  Stdlib urllib only.
"""
from __future__ import annotations

import hashlib
import json
import os
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Mapping, Optional, Sequence

from cruise_control_tpu.api.parameters import GET_ENDPOINTS, VALID_PARAMS
from cruise_control_tpu.api.user_tasks import USER_TASK_ID_HEADER


class CruiseControlClientError(Exception):
    def __init__(self, status: int, message: str,
                 backpressure: bool = False) -> None:
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message
        #: True when the failure was GENUINE backpressure the client
        #: retried and gave up on (429, or 503 with a Retry-After
        #: drain hint) — a bare 503 is a server fault, not
        #: backpressure, and consumers like the load harness must not
        #: score it against the lenient rejected-rate cap
        self.backpressure = backpressure


class CruiseControlClient:
    """One method per endpoint; async responses are long-polled to
    completion (reference Responder.py / ExecutionContext)."""

    def __init__(self, base_url: str,
                 auth_header: Optional[str] = None,
                 poll_interval_s: float = 1.0,
                 timeout_s: float = 600.0,
                 wait_default: bool = True,
                 max_retries_429: int = 4,
                 retry_backoff_base_s: float = 1.0,
                 retry_backoff_max_s: float = 30.0,
                 retry_jitter_token: Optional[str] = None,
                 cluster: Optional[str] = None,
                 sleep_fn: Optional[Callable[[float], None]] = None,
                 on_retry: Optional[Callable[[str, int, int, float],
                                             None]] = None
                 ) -> None:
        self._base = base_url.rstrip("/")
        #: fleet tenant this client addresses: `cluster=<id>` rides on
        #: every request (server default tenant when None); an unknown
        #: tenant's 404 surfaces as CruiseControlClientError(404)
        self._cluster = cluster
        self._auth = auth_header
        self._poll_s = poll_interval_s
        self._timeout_s = timeout_s
        #: long-poll async operations to completion unless overridden
        self._wait_default = wait_default
        #: HTTP 429 (scheduler backpressure) retry policy: honor
        #: `Retry-After` with capped exponential backoff + deterministic
        #: jitter; 0 restores fail-fast
        self._max_retries_429 = max(0, max_retries_429)
        self._retry_base_s = retry_backoff_base_s
        self._retry_max_s = retry_backoff_max_s
        #: per-client jitter identity: each client hashes to its own
        #: point in the [0.5, 1.0) jitter window, so a fleet rejected
        #: together does not retry together; pass an explicit token for
        #: reproducible delays
        self._jitter_token = (retry_jitter_token
                              if retry_jitter_token is not None
                              else f"{os.getpid()}:{id(self):x}")
        self._sleep = sleep_fn or time.sleep
        #: backpressure observer hook: called with (endpoint, status,
        #: attempt, delay_s) BEFORE each 429/503-draining backoff sleep
        #: — the load harness counts rejections per request through it;
        #: exceptions are the caller's problem (None = no observer)
        self._on_retry = on_retry

    # ------------------------------------------------------------------
    def request(self, endpoint: str,
                params: Optional[Mapping[str, object]] = None,
                wait: Optional[bool] = None,
                body: Optional[dict] = None) -> dict:
        """`body` (a JSON-serializable dict) becomes the POST request
        body — SCENARIOS carries its spec list there.  Sent on the
        first request only; once a `User-Task-ID` is attached, re-polls
        go header-only (the server attaches by task id)."""
        if wait is None:
            wait = self._wait_default
        endpoint = endpoint.upper()
        legal = VALID_PARAMS.get(endpoint)
        if legal is None:
            raise ValueError(f"unknown endpoint {endpoint}")
        method = "GET" if endpoint in GET_ENDPOINTS else "POST"
        data = (json.dumps(body).encode() if body is not None else None)
        params = dict(params or {})
        if self._cluster is not None and "cluster" in legal \
                and "cluster" not in params:
            # thread the client's tenant through every subcommand
            # (FLEET spans the whole fleet and takes no cluster)
            params["cluster"] = self._cluster
        query = {}
        for k, v in params.items():
            if v is None:
                continue
            if k.lower() not in legal:
                raise ValueError(f"{endpoint} does not accept {k!r}; "
                                 f"legal: {sorted(legal)}")
            if isinstance(v, bool):
                v = "true" if v else "false"
            elif isinstance(v, (list, tuple)):
                v = ",".join(str(x) for x in v)
            elif isinstance(v, (set, frozenset)):
                v = ",".join(str(x) for x in sorted(v))
            query[k.lower()] = str(v)
        url = (f"{self._base}/{endpoint.lower()}"
               + (f"?{urllib.parse.urlencode(query)}" if query else ""))
        deadline = time.time() + self._timeout_s
        task_id: Optional[str] = None
        retries_429 = 0
        while True:
            # once a task id is attached, re-polls go header-only: the
            # server allows body-less re-polls, and re-uploading a large
            # spec body every poll interval is pure waste
            status, headers, body = self._http(
                method, url, task_id, data=None if task_id else data)
            task_id = headers.get(USER_TASK_ID_HEADER, task_id)
            if status == 200:
                return body
            if status == 429 or (status == 503
                                 and self._is_draining(headers, body)):
                # backpressure, both flavors: 429 = scheduler queue at
                # its cap, 503-draining = the process is shutting down
                # gracefully (api/server drain: Retry-After names when
                # the replacement should be up).  Same discipline for
                # both — honor Retry-After with capped exponential
                # backoff + deterministic jitter, then resubmit.  A
                # plain 503 WITHOUT a retry hint (e.g. a draining fleet
                # tenant mid-rebalance of tenants) still surfaces as an
                # error below.  The response carries the FAILED task's
                # User-Task-ID for diagnostics — drop it, or the retry
                # would attach to the dead task (and replay its cached
                # rejection) instead of resubmitting
                task_id = None
                delay = self._retry_delay_429(endpoint, retries_429,
                                              headers, body)
                if (retries_429 >= self._max_retries_429
                        or time.time() + delay > deadline):
                    raise CruiseControlClientError(
                        status, body.get(
                            "errorMessage",
                            "rejected: solve queue full" if status == 429
                            else "server draining")
                        + f" (gave up after {retries_429} retries)",
                        backpressure=True)
                retries_429 += 1
                if self._on_retry is not None:
                    self._on_retry(endpoint, status, retries_429, delay)
                self._sleep(delay)
                continue
            if status == 202 and "reviewResult" in body:
                # two-step verification parked the request — re-polling
                # would file duplicate reviews; hand the review back
                return body
            if status == 202 and wait:
                if time.time() > deadline:
                    raise CruiseControlClientError(
                        202, f"operation did not finish within "
                             f"{self._timeout_s}s (task {task_id})")
                time.sleep(self._poll_s)
                continue
            if status == 202:
                return body
            raise CruiseControlClientError(
                status, body.get("errorMessage", str(body)))

    @staticmethod
    def _is_draining(headers: Mapping[str, str], body: Mapping) -> bool:
        """A 503 is RETRYABLE only when the server says when to come
        back (Retry-After header or retryAfterSeconds in the body) —
        the graceful-drain signature.  A bare 503 (misconfigured
        proxy, tenant drained for good) stays a hard error: blind
        retries against those just hammer a server that never asked
        for patience."""
        if any(k.lower() == "retry-after" for k in headers):
            return True
        try:
            return float(body.get("retryAfterSeconds", 0.0)) > 0
        except (TypeError, ValueError, AttributeError):
            return False

    def _retry_delay_429(self, endpoint: str, attempt: int,
                         headers: Mapping[str, str], body: Mapping
                         ) -> float:
        """Backoff before resubmitting a 429-rejected request: the
        server's `Retry-After` (header, or `retryAfterSeconds` in the
        body) floors a capped exponential backoff, and BOTH terms are
        scaled by a DETERMINISTIC jitter — same client token +
        endpoint + attempt always waits the same time (reproducible),
        while distinct clients hash to distinct points in the jitter
        window.  Retry-After is jittered UPWARD (never below the
        server's floor): when it dominates the backoff, an unjittered
        max() would have every rejected client sleep exactly the
        server's value and re-stampede the queue in lockstep."""
        retry_after = 0.0
        for k, v in headers.items():
            if k.lower() == "retry-after":
                try:
                    retry_after = float(v)
                except ValueError:
                    retry_after = 0.0
        if not retry_after:
            try:
                retry_after = float(body.get("retryAfterSeconds", 0.0))
            except (TypeError, ValueError):
                retry_after = 0.0
        backoff = min(self._retry_max_s,
                      self._retry_base_s * (2 ** attempt))
        seed = hashlib.sha256(
            f"{self._jitter_token}:{endpoint}:{attempt}".encode()).digest()
        jitter = 0.5 + seed[0] / 512.0          # [0.5, 1.0)
        jitter_up = 1.0 + seed[1] / 512.0       # [1.0, 1.5)
        return max(retry_after * jitter_up, backoff * jitter)

    def _http(self, method: str, url: str, task_id: Optional[str],
              data: Optional[bytes] = None):
        req = urllib.request.Request(url, method=method, data=data)
        if data is not None:
            req.add_header("Content-Type", "application/json")
        if self._auth:
            req.add_header("Authorization", self._auth)
        if task_id:
            req.add_header(USER_TASK_ID_HEADER, task_id)
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return (resp.status, dict(resp.headers.items()),
                        json.loads(resp.read() or b"{}"))
        except urllib.error.HTTPError as exc:
            try:
                body = json.loads(exc.read() or b"{}")
            except json.JSONDecodeError:
                body = {"errorMessage": str(exc)}
            return exc.code, dict(exc.headers.items() if exc.headers
                                  else {}), body

    # ------------------------------------------------------------------
    # endpoint convenience wrappers (reference Endpoint.py classes)
    # ------------------------------------------------------------------
    def state(self, substates: Optional[Sequence[str]] = None) -> dict:
        return self.request("STATE", {"substates": substates})

    def fleet(self, verbose: bool = False) -> dict:
        """Fleet tenant listing (404 on a non-fleet server)."""
        return self.request("FLEET", {"verbose": verbose or None})

    def load(self) -> dict:
        return self.request("LOAD")

    def partition_load(self, resource: str = "disk",
                       entries: Optional[int] = None,
                       topic: Optional[str] = None) -> dict:
        return self.request("PARTITION_LOAD", {
            "resource": resource, "entries": entries, "topic": topic})

    def proposals(self, goals: Optional[Sequence[str]] = None,
                  verbose: bool = False,
                  ignore_proposal_cache: bool = False,
                  portfolio_width: Optional[int] = None) -> dict:
        return self.request("PROPOSALS", {
            "goals": goals, "verbose": verbose,
            "ignore_proposal_cache": ignore_proposal_cache,
            "portfolio_width": portfolio_width})

    def kafka_cluster_state(self) -> dict:
        return self.request("KAFKA_CLUSTER_STATE")

    def user_tasks(self) -> dict:
        return self.request("USER_TASKS")

    def rebalance(self, dryrun: bool = True,
                  goals: Optional[Sequence[str]] = None,
                  verbose: bool = False, **params) -> dict:
        return self.request("REBALANCE", {
            "dryrun": dryrun, "goals": goals, "verbose": verbose, **params})

    def add_broker(self, broker_ids: Sequence[int], dryrun: bool = True,
                   **params) -> dict:
        return self.request("ADD_BROKER", {
            "brokerid": list(broker_ids), "dryrun": dryrun, **params})

    def remove_broker(self, broker_ids: Sequence[int], dryrun: bool = True,
                      **params) -> dict:
        return self.request("REMOVE_BROKER", {
            "brokerid": list(broker_ids), "dryrun": dryrun, **params})

    def demote_broker(self, broker_ids: Sequence[int], dryrun: bool = True,
                      **params) -> dict:
        return self.request("DEMOTE_BROKER", {
            "brokerid": list(broker_ids), "dryrun": dryrun, **params})

    def fix_offline_replicas(self, dryrun: bool = True, **params) -> dict:
        return self.request("FIX_OFFLINE_REPLICAS",
                            {"dryrun": dryrun, **params})

    def stop_execution(self, force: bool = False) -> dict:
        return self.request("STOP_PROPOSAL_EXECUTION",
                            {"force_stop": force})

    def pause_sampling(self, reason: str = "") -> dict:
        return self.request("PAUSE_SAMPLING",
                            {"reason": reason} if reason else {})

    def resume_sampling(self, reason: str = "") -> dict:
        return self.request("RESUME_SAMPLING",
                            {"reason": reason} if reason else {})

    def admin(self, **params) -> dict:
        return self.request("ADMIN", params)

    def topic_configuration(self, topic: str, replication_factor: int,
                            dryrun: bool = True, **params) -> dict:
        return self.request("TOPIC_CONFIGURATION", {
            "topic": topic, "replication_factor": replication_factor,
            "dryrun": dryrun, **params})

    def review(self, approve: Optional[Sequence[int]] = None,
               discard: Optional[Sequence[int]] = None,
               reason: str = "") -> dict:
        return self.request("REVIEW", {
            "approve": list(approve) if approve else None,
            "discard": list(discard) if discard else None,
            "reason": reason or None})

    def review_board(self) -> dict:
        return self.request("REVIEW_BOARD")

    def traces(self, trace_id: Optional[str] = None,
               outcome: Optional[str] = None,
               limit: Optional[int] = None,
               verbose: bool = False,
               since_ms: Optional[float] = None,
               min_duration_ms: Optional[float] = None) -> dict:
        """Flight-recorder query (obs/): the span trees of recent
        solves.  Fetch the tree a solve response's `traceId` named with
        `trace_id=`, the pinned incident traces with
        `outcome="degraded"`.  `since_ms` (epoch ms) and
        `min_duration_ms` bound drill queries under load so a tail
        never pages the whole ring."""
        return self.request("TRACES", {
            "trace_id": trace_id, "outcome": outcome, "limit": limit,
            "verbose": verbose or None, "since": since_ms,
            "min_duration_ms": min_duration_ms})

    def slo_status(self) -> dict:
        """The per-class SLO burn block (obs/slo.py): STATE's
        `sloStatus` substate — burn rate, queue-wait vs device-time
        decomposition and budget remaining per scheduler class."""
        return self.state(substates=["slo"]).get("sloStatus", {})

    def portfolio_status(self) -> dict:
        """The portfolio-search block (portfolio/): STATE's `portfolio`
        substate — width/seed config, search + ladder telemetry, the
        improvement/stale-drop counters and the portfolio-vs-greedy
        fitness gap."""
        return self.state(substates=["portfolio"]).get(
            "PortfolioState", {})

    def metrics_text(self) -> str:
        """The raw OpenMetrics page (`/metrics`) — what a Prometheus
        scrape sees.  Served OUTSIDE the API prefix."""
        # /metrics lives one level above the API prefix: strip ONLY the
        # last path segment so a path-mounting reverse proxy
        # ("https://proxy/cc/kafkacruisecontrol" -> ".../cc/metrics")
        # keeps routing to the same backend
        parsed = urllib.parse.urlsplit(self._base)
        parent = parsed.path.rstrip("/").rsplit("/", 1)[0]
        root = urllib.parse.urlunsplit(
            (parsed.scheme, parsed.netloc, parent, "", ""))
        req = urllib.request.Request(f"{root}/metrics", method="GET")
        if self._auth:
            req.add_header("Authorization", self._auth)
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.read().decode("utf-8")

    def scenarios(self, scenarios: Sequence[dict],
                  goals: Optional[Sequence[str]] = None,
                  include_base: bool = True,
                  verbose: bool = False, **params) -> dict:
        """Batched what-if analysis (dry-run only).  `scenarios` is a
        list of scenario objects in the JSON form of
        scenario/spec.py::SCENARIO_SPEC_SCHEMA."""
        body: dict = {"scenarios": list(scenarios)}
        if goals:
            body["goals"] = list(goals)
        if not include_base:
            body["includeBase"] = False
        return self.request("SCENARIOS", {"verbose": verbose, **params},
                            body=body)
