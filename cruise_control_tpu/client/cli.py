"""`cccli`-style command-line client.

Reference cruise-control-client/cruisecontrolclient/client/cccli.py +
docs/wiki "cccli Command Line Usage": one subcommand per endpoint with
typed flags, printing the JSON response.
"""
from __future__ import annotations

import argparse
import base64
import json
import sys
from typing import List, Optional

from cruise_control_tpu.client.client import (CruiseControlClient,
                                              CruiseControlClientError)


def _csv_ints(s: str) -> List[int]:
    return [int(x) for x in s.split(",") if x.strip()]


def _csv(s: str) -> List[str]:
    return [x for x in s.split(",") if x.strip()]


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="cccli",
        description="Command-line client for the cruise-control-tpu REST "
                    "API")
    parser.add_argument("-a", "--address", default="http://127.0.0.1:9090"
                        "/kafkacruisecontrol",
                        help="base URL of the REST API")
    parser.add_argument("--user", help="basic-auth user:password")
    parser.add_argument("--no-wait", action="store_true",
                        help="do not poll async operations to completion")
    parser.add_argument("--max-retries", type=int, default=4,
                        help="retries after HTTP 429 (scheduler "
                             "backpressure), honoring Retry-After with "
                             "capped exponential backoff + deterministic "
                             "jitter; 0 fails fast (default: 4)")
    parser.add_argument("--cluster",
                        help="fleet tenant to address (rides as "
                             "cluster=<id> on every subcommand; the "
                             "server's default tenant when omitted; an "
                             "unknown tenant is a clean 404 error)")
    sub = parser.add_subparsers(dest="command", required=True)

    def add(name: str, **kwargs) -> argparse.ArgumentParser:
        return sub.add_parser(name, **kwargs)

    p = add("state", help="component states")
    p.add_argument("--substates", type=_csv)

    add("load", help="per-broker load stats")

    p = add("partition_load", help="per-partition load")
    p.add_argument("--resource", default="disk")
    p.add_argument("--entries", type=int)
    p.add_argument("--topic")

    p = add("proposals", help="current rebalance proposals")
    p.add_argument("--goals", type=_csv)
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--ignore-proposal-cache", action="store_true")
    p.add_argument("--portfolio-width", type=int, metavar="K",
                   help="search K perturbed solver candidates in one "
                        "batched device solve and answer with the "
                        "best-by-fitness winner (portfolio/); the "
                        "response's solverProvenance says which solver "
                        "won (server default when omitted)")

    add("kafka_cluster_state", help="raw cluster metadata")
    add("user_tasks", help="async task history")
    add("review_board", help="pending two-step reviews")

    p = add("fleet", help="fleet tenant listing (multi-cluster servers)")
    p.add_argument("--verbose", action="store_true",
                   help="include each tenant's full state")

    for name, needs_brokers in (("rebalance", False), ("add_broker", True),
                                ("remove_broker", True),
                                ("demote_broker", True),
                                ("fix_offline_replicas", False)):
        p = add(name, help=f"{name.replace('_', ' ')} (POST)")
        if needs_brokers:
            p.add_argument("brokers", type=_csv_ints,
                           help="CSV broker ids")
        p.add_argument("--execute", action="store_true",
                       help="actually execute (default is dry run)")
        if name in ("rebalance", "add_broker", "remove_broker",
                    "fix_offline_replicas"):
            p.add_argument("--goals", type=_csv)
        if name == "rebalance":
            p.add_argument("--portfolio-width", type=int, metavar="K",
                           help="device-parallel portfolio search width "
                                "(see `proposals --portfolio-width`)")
        p.add_argument("--verbose", action="store_true")
        p.add_argument("--reason")
        p.add_argument("--review-id", type=int)

    p = add("scenarios", help="batched what-if analysis (dry run)")
    p.add_argument("--spec-file",
                   help="JSON file with the request body "
                        '({"scenarios": [...]}) or a bare scenario list')
    p.add_argument("--spec",
                   help="inline JSON (same format as --spec-file)")
    p.add_argument("--goals", type=_csv,
                   help="goal-list override for every scenario")
    p.add_argument("--no-base", action="store_true",
                   help="skip the implicit base (do-nothing) solve")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--reason")
    p.add_argument("--review-id", type=int)

    p = add("topic_configuration", help="change topic replication factor")
    p.add_argument("topic")
    p.add_argument("replication_factor", type=int)
    p.add_argument("--execute", action="store_true")

    p = add("stop_execution", help="stop the ongoing execution")
    p.add_argument("--force", action="store_true")

    p = add("pause_sampling", help="pause metric sampling")
    p.add_argument("--reason", default="paused via cccli")
    p = add("resume_sampling", help="resume metric sampling")
    p.add_argument("--reason", default="resumed via cccli")

    p = add("admin", help="toggle self-healing etc.")
    p.add_argument("--enable-self-healing-for", type=_csv)
    p.add_argument("--disable-self-healing-for", type=_csv)

    p = add("review", help="approve/discard two-step requests")
    p.add_argument("--approve", type=_csv_ints)
    p.add_argument("--discard", type=_csv_ints)
    p.add_argument("--reason", default="")

    p = add("traces", help="flight-recorder trace query (obs/)")
    p.add_argument("--trace-id",
                   help="fetch the span tree a solve response's traceId "
                        "named")
    p.add_argument("--outcome",
                   choices=["ok", "failed", "degraded", "fallback",
                            "preempted", "rejected"],
                   help="filter by outcome (degraded/failed traces are "
                        "pinned until exported)")
    p.add_argument("--limit", type=int)
    p.add_argument("--verbose", action="store_true",
                   help="include full span trees in listings")
    p.add_argument("--since", type=float, metavar="EPOCH_MS",
                   help="only traces started at/after this epoch-ms "
                        "timestamp (drills under load: never page the "
                        "whole ring)")
    p.add_argument("--min-duration-ms", type=float,
                   help="only traces at least this slow")

    add("metrics", help="raw OpenMetrics page (/metrics scrape)")

    add("slo", help="per-class SLO burn status (STATE sloStatus: burn "
                    "rate, queue-wait vs device-time, budget remaining)")

    add("portfolio", help="portfolio-search status (STATE "
                          "PortfolioState: width/seed, ladder rung, "
                          "improvement/stale-drop counters, "
                          "portfolio-vs-greedy fitness gap)")

    p = add("loadgen",
            help="trace-replay load harness (cruise_control_tpu/"
                 "loadgen/): replay a seeded workload profile against "
                 "the server and print the run artifact")
    p.add_argument("--profile", default="soak-mixed",
                   help="built-in profile name (smoke, soak-mixed, "
                        "fleet-churn; default soak-mixed)")
    p.add_argument("--profile-file",
                   help="JSON profile file (overrides --profile; see "
                        "docs/LOADGEN.md for the schema)")
    p.add_argument("--seed", type=int,
                   help="replay seed: same seed + profile = "
                        "byte-identical request sequence (default 1 "
                        "for built-ins; a --profile-file keeps its own "
                        "seed unless overridden)")
    p.add_argument("--duration", type=float,
                   help="rescale the built-in profile to this many "
                        "seconds")
    p.add_argument("--rps", type=float,
                   help="rescale the built-in profile's base rate")
    p.add_argument("--clients", type=int,
                   help="override the profile's client count")
    p.add_argument("--out", help="write the run artifact here (the "
                                 "summary still prints)")
    p.add_argument("--demo", action="store_true",
                   help="serve an in-process demo rig instead of "
                        "--address (enables the rig-only op kinds: "
                        "heal storms, precompute churn, model-delta "
                        "streams)")
    return parser


def _run_loadgen(args, auth) -> int:
    """The `cccli loadgen` subcommand: build/parse the profile, replay
    it (against --address, or an in-process --demo rig), print the run
    artifact (optionally to --out) plus a one-line summary."""
    from cruise_control_tpu.loadgen import (LoadHarness, builtin_profile,
                                            parse_profile,
                                            validate_artifact)
    if args.profile_file:
        profile = parse_profile(open(args.profile_file).read())
        if args.seed is not None:
            profile = parse_profile({**profile.to_json(),
                                     "seed": args.seed})
    else:
        profile = builtin_profile(
            args.profile, duration_s=args.duration, rps=args.rps,
            clients=args.clients,
            seed=args.seed if args.seed is not None else 1)
    demo = None
    try:
        if args.demo:
            from cruise_control_tpu.loadgen.rig import build_demo_rig
            print("# starting in-process demo rig...", file=sys.stderr)
            demo = build_demo_rig()
            base_url, rig = demo.base_url, demo.rig
        else:
            base_url, rig = args.address, None
        print(f"# replaying {profile.name!r} (seed {profile.seed}, "
              f"{profile.clients} clients, {profile.duration_s:.0f}s) "
              f"against {base_url}", file=sys.stderr)
        harness = LoadHarness(base_url, profile, rig=rig,
                              auth_header=auth,
                              max_retries=args.max_retries)
        artifact = harness.run()
    finally:
        if demo is not None:
            demo.shutdown()
    problems = validate_artifact(artifact)
    for p in problems:
        print(f"# artifact problem: {p}", file=sys.stderr)
    if args.out:
        from cruise_control_tpu.utils import persist
        persist.atomic_write(
            args.out, (json.dumps(artifact, indent=2, sort_keys=True)
                       + "\n").encode())
        print(f"# artifact written to {args.out}", file=sys.stderr)
    print(json.dumps(artifact, indent=2, sort_keys=True))
    return 1 if problems else 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    auth = None
    if args.user:
        auth = "Basic " + base64.b64encode(args.user.encode()).decode()
    client = CruiseControlClient(args.address, auth_header=auth,
                                 wait_default=not args.no_wait,
                                 max_retries_429=args.max_retries,
                                 cluster=args.cluster)

    cmd = args.command
    try:
        if cmd == "state":
            out = client.state(args.substates)
        elif cmd == "load":
            out = client.load()
        elif cmd == "partition_load":
            out = client.partition_load(args.resource, args.entries,
                                        args.topic)
        elif cmd == "proposals":
            out = client.proposals(args.goals, args.verbose,
                                   args.ignore_proposal_cache,
                                   portfolio_width=args.portfolio_width)
        elif cmd == "kafka_cluster_state":
            out = client.kafka_cluster_state()
        elif cmd == "user_tasks":
            out = client.user_tasks()
        elif cmd == "review_board":
            out = client.review_board()
        elif cmd == "fleet":
            out = client.fleet(verbose=args.verbose)
        elif cmd in ("rebalance", "add_broker", "remove_broker",
                     "demote_broker", "fix_offline_replicas"):
            params = {"dryrun": not args.execute,
                      "verbose": args.verbose}
            if getattr(args, "goals", None):
                params["goals"] = args.goals
            if args.reason:
                params["reason"] = args.reason
            if args.review_id is not None:
                params["review_id"] = args.review_id
            if cmd == "rebalance":
                if args.portfolio_width is not None:
                    params["portfolio_width"] = args.portfolio_width
                out = client.rebalance(**params)
            elif cmd == "fix_offline_replicas":
                out = client.fix_offline_replicas(**params)
            else:
                fn = {"add_broker": client.add_broker,
                      "remove_broker": client.remove_broker,
                      "demote_broker": client.demote_broker}[cmd]
                dryrun = params.pop("dryrun")
                out = fn(args.brokers, dryrun=dryrun, **params)
        elif cmd == "scenarios":
            if bool(args.spec_file) == bool(args.spec):
                raise SystemExit(
                    "scenarios needs exactly one of --spec-file/--spec")
            raw = (open(args.spec_file).read() if args.spec_file
                   else args.spec)
            payload = json.loads(raw)
            if isinstance(payload, list):     # bare scenario list
                payload = {"scenarios": payload}
            params = {}
            if args.reason:
                params["reason"] = args.reason
            if args.review_id is not None:
                params["review_id"] = args.review_id
            out = client.scenarios(
                payload.get("scenarios", []),
                goals=args.goals or payload.get("goals"),
                include_base=(not args.no_base
                              and payload.get("includeBase", True)),
                verbose=args.verbose, **params)
        elif cmd == "topic_configuration":
            out = client.topic_configuration(args.topic,
                                             args.replication_factor,
                                             dryrun=not args.execute)
        elif cmd == "stop_execution":
            out = client.stop_execution(force=args.force)
        elif cmd == "pause_sampling":
            out = client.pause_sampling(args.reason)
        elif cmd == "resume_sampling":
            out = client.resume_sampling(args.reason)
        elif cmd == "admin":
            params = {}
            if args.enable_self_healing_for:
                params["enable_self_healing_for"] = \
                    args.enable_self_healing_for
            if args.disable_self_healing_for:
                params["disable_self_healing_for"] = \
                    args.disable_self_healing_for
            out = client.admin(**params)
        elif cmd == "review":
            out = client.review(args.approve, args.discard, args.reason)
        elif cmd == "traces":
            out = client.traces(trace_id=args.trace_id,
                                outcome=args.outcome, limit=args.limit,
                                verbose=args.verbose,
                                since_ms=args.since,
                                min_duration_ms=args.min_duration_ms)
        elif cmd == "metrics":
            print(client.metrics_text(), end="")
            return 0
        elif cmd == "slo":
            out = client.slo_status()
        elif cmd == "portfolio":
            out = client.portfolio_status()
        elif cmd == "loadgen":
            return _run_loadgen(args, auth)
        else:  # pragma: no cover
            raise SystemExit(f"unhandled command {cmd}")
    except CruiseControlClientError as exc:
        print(json.dumps({"error": exc.message, "status": exc.status},
                         indent=2), file=sys.stderr)
        return 1
    print(json.dumps(out, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
