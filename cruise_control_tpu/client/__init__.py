"""Python client + CLI for the REST API (SURVEY.md §2.10)."""
from cruise_control_tpu.client.client import (CruiseControlClient,
                                              CruiseControlClientError)

__all__ = ["CruiseControlClient", "CruiseControlClientError"]
