"""Durable executor journal: a crash-safe WAL of execution state.

The executor is the one component that MUTATES the managed cluster, and
until this module its entire state (task manager, phase, throttles,
removal/demotion history) was process memory: a bounce mid-rebalance
left the cluster half-moved with throttles leaked and the anomaly
detector free to start a conflicting self-heal.  The reference avoided
exactly this by persisting ongoing-reassignment state in ZooKeeper
(reference Executor.java ongoing-execution znodes); here the equivalent
is an append-only, CRC-framed, fsync-on-commit JSONL write-ahead log
plus a small atomically-rewritten history file, both under a per-tenant
`executor.journal.dir`.

Write path (single-writer by construction: only the caller thread of
`execute_proposals` and the executor's runnable append, never
concurrently — the journal adds NO locking to the executor):

* `start`   — uuid, reason, full proposals, caps, strategy chain,
  removed/demoted brokers, throttle; rotates to a fresh segment and
  deletes settled older segments (the start record is self-contained).
* `task`    — every task state transition (keyed by the task's STABLE
  key, not the process-local id) + re-execution count.
* `phase`   — executor phase changes.
* `throttle` / `throttle-clear` — replication-throttle application and
  removal (the leak the recovery path must be able to undo).
* `finish`  — terminal record; its presence means nothing to recover.

Failure contract (the chaos-site satellite): a journal write/fsync
failure NEVER fails the rebalance — the journal marks itself broken,
counts the error (`executor-journal-errors`), fires `on_error` once
(the facade routes it through the anomaly plane) and the execution
continues journal-less, exactly as if `executor.journal.dir` were
unset.  Sites `executor.journal.write` / `executor.journal.fsync`
make disk-full/EIO scriptable (utils/faults.py).

Replay (`ExecutionJournal.replay`) reads every segment in order,
truncates the torn tail at the first bad record, and returns the last
execution's journaled state for executor/recovery.py to reconcile
against live cluster metadata — metadata is ground truth; the journal
only says what was *requested*.
"""
from __future__ import annotations

import dataclasses
import glob
import logging
import os
from typing import Callable, Dict, List, Optional, Sequence

from cruise_control_tpu.analyzer.proposals import (ExecutionProposal,
                                                   ReplicaPlacement)
from cruise_control_tpu.model.builder import PartitionId
from cruise_control_tpu.utils import faults, persist

LOG = logging.getLogger(__name__)

_SEGMENT_PREFIX = "journal-"
_SEGMENT_SUFFIX = ".jsonl"
_HISTORY_FILE = "history.json"
DEFAULT_SEGMENT_MAX_BYTES = 4 * 1024 * 1024


def proposal_record(p: ExecutionProposal) -> dict:
    """Full round-trippable serialization of one proposal (the REST
    `to_json` drops logdirs and sizes, which resume needs)."""
    return {
        "topic": p.partition.topic,
        "partition": p.partition.partition,
        "oldLeader": p.old_leader,
        "old": [[r.broker_id, r.logdir] for r in p.old_replicas],
        "new": [[r.broker_id, r.logdir] for r in p.new_replicas],
        "size": p.partition_size,
    }


def proposal_from_record(d: dict) -> ExecutionProposal:
    return ExecutionProposal(
        partition=PartitionId(d["topic"], d["partition"]),
        old_leader=d["oldLeader"],
        old_replicas=tuple(ReplicaPlacement(b, ld) for b, ld in d["old"]),
        new_replicas=tuple(ReplicaPlacement(b, ld) for b, ld in d["new"]),
        partition_size=d.get("size", 0.0))


@dataclasses.dataclass
class JournalReplay:
    """What the journal says about the LAST execution it recorded."""

    #: the last `start` record (None: journal empty / never executed)
    start: Optional[dict] = None
    #: stable task key -> last `task` record for that key
    tasks: Dict[str, dict] = dataclasses.field(default_factory=dict)
    #: last journaled executor phase
    phase: Optional[str] = None
    #: True when a `finish` record followed the last `start`
    finished: bool = False
    #: brokers with an applied-but-never-cleared replication throttle
    throttle_brokers: List[int] = dataclasses.field(default_factory=list)
    #: a torn tail / corrupt record truncated the replay somewhere
    truncated: bool = False
    #: total records replayed across segments
    records: int = 0
    segments: int = 0

    @property
    def in_flight(self) -> bool:
        """An execution was journaled and never finished."""
        return self.start is not None and not self.finished

    def proposals(self) -> List[ExecutionProposal]:
        if self.start is None:
            return []
        return [proposal_from_record(d)
                for d in self.start.get("proposals", [])]


class ExecutionJournal:
    """See module docstring.  One instance per executor/tenant; the
    directory IS the tenant scope (fleet/registry tenants each get
    `<executor.journal.dir>/<cluster-id>` via the config overlay)."""

    def __init__(self, directory: str,
                 segment_max_bytes: int = DEFAULT_SEGMENT_MAX_BYTES,
                 fsync: bool = True,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        import time as _time
        self.directory = directory
        self._segment_max_bytes = max(4096, int(segment_max_bytes))
        self._fsync = fsync
        self._time = time_fn or _time.time
        self._fh = None
        self._segment_path: Optional[str] = None
        self._segment_bytes = 0
        #: degraded: a write failed — journal-less from here on
        self.broken = False
        self.writes = 0
        self.bytes_written = 0
        self.errors = 0
        #: fired ONCE on the first write failure (facade wires the
        #: anomaly plane here); never raises into the executor
        self.on_error: Optional[Callable[[BaseException], None]] = None
        os.makedirs(directory, exist_ok=True)

    # ------------------------------------------------------------------
    # segment management
    # ------------------------------------------------------------------
    def _segment_paths(self) -> List[str]:
        return sorted(glob.glob(os.path.join(
            self.directory, f"{_SEGMENT_PREFIX}*{_SEGMENT_SUFFIX}")))

    def _next_segment_path(self) -> str:
        existing = self._segment_paths()
        if existing:
            last = os.path.basename(existing[-1])
            n = int(last[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)]) + 1
        else:
            n = 1
        return os.path.join(
            self.directory, f"{_SEGMENT_PREFIX}{n:06d}{_SEGMENT_SUFFIX}")

    def _open_segment(self, path: str) -> None:
        if self._fh is not None:
            self._fh.close()
        self._fh = persist.open_append(path)
        self._segment_path = path
        self._segment_bytes = os.path.getsize(path)
        # the new segment's DIRECTORY ENTRY must be durable too: a
        # record fsync makes the data durable, but after power loss a
        # file whose dir entry never committed does not exist — replay
        # would find only the previous execution's segments
        self._fsync_dir()

    def _fsync_dir(self) -> None:
        if not self._fsync:
            return
        try:
            persist.fsync_dir(self.directory)
        except OSError as exc:
            LOG.warning("journal: directory fsync failed: %s", exc)

    def _rotate(self, drop_older: bool) -> None:
        """Open a fresh segment; with `drop_older`, delete the settled
        previous segments AFTER the new one exists (a crash in between
        leaves both, and replay's last-start-wins handles it).  The
        directory is fsynced after both steps so neither the new
        segment nor the deletions can be lost to power failure."""
        older = self._segment_paths()
        self._open_segment(self._next_segment_path())
        if drop_older:
            for path in older:
                try:
                    os.unlink(path)
                except OSError as exc:
                    LOG.warning("journal: could not drop settled "
                                "segment %s: %s", path, exc)
            self._fsync_dir()

    # ------------------------------------------------------------------
    # the write path
    # ------------------------------------------------------------------
    def _write(self, record: dict) -> None:
        faults.inject("executor.journal.write")
        if self._fh is None or self._segment_bytes >= self._segment_max_bytes:
            if self._fh is None:
                self._open_segment(self._next_segment_path())
            else:
                self._rotate(drop_older=False)
        line = persist.json_frame(record)
        self._fh.write(line)
        self._fh.flush()
        if self._fsync:
            faults.inject("executor.journal.fsync")
            os.fsync(self._fh.fileno())
        self._segment_bytes += len(line)
        self.writes += 1
        self.bytes_written += len(line)

    def _commit(self, record: dict) -> None:
        """Append one record, degrading to journal-less on failure —
        a sick disk must never fail the rebalance it was auditing."""
        if self.broken:
            return
        try:
            self._write(record)
        except Exception as exc:  # noqa: BLE001 - degrade, never fail
            self.broken = True
            self.errors += 1
            LOG.error(
                "executor journal write failed (%s: %s); continuing "
                "JOURNAL-LESS — a crash from here on will not be "
                "recoverable", type(exc).__name__, exc)
            cb = self.on_error
            if cb is not None:
                try:
                    cb(exc)
                except Exception:  # noqa: BLE001 - reporting best-effort
                    LOG.exception("journal on_error callback failed")

    # ------------------------------------------------------------------
    # events
    # ------------------------------------------------------------------
    def log_start(self, uuid: str, reason: str,
                  proposals: Sequence[ExecutionProposal],
                  caps: dict, strategy_names: Sequence[str],
                  removed_brokers: Sequence[int],
                  demoted_brokers: Sequence[int],
                  throttle: Optional[float],
                  resumed: bool = False) -> None:
        if self.broken:
            return
        try:
            # a new start settles everything before it: fresh segment
            # first, then drop the old ones (replay survives a crash
            # between the two)
            self._rotate(drop_older=True)
        except Exception as exc:  # noqa: BLE001 - degrade, never fail
            self.broken = True
            self.errors += 1
            LOG.error("executor journal rotation failed (%s: %s); "
                      "continuing journal-less", type(exc).__name__, exc)
            cb = self.on_error
            if cb is not None:
                try:
                    cb(exc)
                except Exception:  # noqa: BLE001
                    LOG.exception("journal on_error callback failed")
            return
        self._commit({
            "t": "start", "uuid": uuid, "reason": reason,
            "ts": self._time() * 1000.0,
            "proposals": [proposal_record(p) for p in proposals],
            "caps": dict(caps),
            "strategy": list(strategy_names),
            "removed": sorted(removed_brokers),
            "demoted": sorted(demoted_brokers),
            "throttle": throttle,
            "resumed": resumed,
        })

    def log_task(self, uuid: Optional[str], key: str, state: str,
                 now_ms: float, reexecution_count: int = 0) -> None:
        self._commit({"t": "task", "uuid": uuid, "key": key,
                      "state": state, "ts": now_ms,
                      "reexec": reexecution_count})

    def log_phase(self, uuid: Optional[str], phase: str) -> None:
        self._commit({"t": "phase", "uuid": uuid, "phase": phase,
                      "ts": self._time() * 1000.0})

    def log_throttle(self, uuid: Optional[str], brokers: Sequence[int],
                     rate: float) -> None:
        self._commit({"t": "throttle", "uuid": uuid,
                      "brokers": list(brokers), "rate": rate,
                      "ts": self._time() * 1000.0})

    def log_throttle_cleared(self, uuid: Optional[str],
                             brokers: Sequence[int]) -> None:
        self._commit({"t": "throttle-clear", "uuid": uuid,
                      "brokers": list(brokers),
                      "ts": self._time() * 1000.0})

    def log_finish(self, uuid: Optional[str], succeeded: bool,
                   message: str) -> None:
        self._commit({"t": "finish", "uuid": uuid,
                      "succeeded": succeeded, "message": message,
                      "ts": self._time() * 1000.0})

    # ------------------------------------------------------------------
    # removal/demotion history (atomically rewritten, not appended:
    # it is small and latest-wins)
    # ------------------------------------------------------------------
    def save_history(self, removed: Dict[int, float],
                     demoted: Dict[int, float]) -> None:
        try:
            persist.atomic_write_json(
                os.path.join(self.directory, _HISTORY_FILE),
                {"removed": {str(k): v for k, v in removed.items()},
                 "demoted": {str(k): v for k, v in demoted.items()}},
                fsync=self._fsync)
        except Exception as exc:  # noqa: BLE001 - degrade, never fail
            self.errors += 1
            LOG.warning("executor history write failed (%s: %s); "
                        "removal/demotion history will not survive a "
                        "restart", type(exc).__name__, exc)

    def load_history(self) -> tuple:
        import json
        path = os.path.join(self.directory, _HISTORY_FILE)
        try:
            with open(path) as fh:
                doc = json.load(fh)
            return ({int(k): float(v)
                     for k, v in (doc.get("removed") or {}).items()},
                    {int(k): float(v)
                     for k, v in (doc.get("demoted") or {}).items()})
        except FileNotFoundError:
            return {}, {}
        except (OSError, ValueError) as exc:
            LOG.warning("executor history unreadable (%s); starting "
                        "with empty removal/demotion history", exc)
            return {}, {}

    # ------------------------------------------------------------------
    # replay
    # ------------------------------------------------------------------
    def replay(self) -> JournalReplay:
        """Read-only scan of every segment in order (call BEFORE this
        process writes).  Last `start` wins; task records are keyed by
        stable key with the last record per key retained; a torn tail
        truncates the segment it appears in at the first bad record."""
        out = JournalReplay()
        paths = self._segment_paths()
        out.segments = len(paths)
        throttle_brokers: List[int] = []
        for path in paths:
            records, truncated = persist.read_crc_json(path)
            if truncated:
                out.truncated = True
                LOG.warning(
                    "journal segment %s has a torn/corrupt tail; "
                    "replay truncated at record %d", path, len(records))
            for rec in records:
                out.records += 1
                t = rec.get("t")
                if t == "start":
                    out.start = rec
                    out.tasks = {}
                    out.phase = None
                    out.finished = False
                    throttle_brokers = []
                elif out.start is None:
                    continue      # orphan records before any start
                elif rec.get("uuid") != out.start.get("uuid"):
                    continue
                elif t == "task":
                    out.tasks[rec["key"]] = rec
                elif t == "phase":
                    out.phase = rec.get("phase")
                elif t == "throttle":
                    throttle_brokers = list(rec.get("brokers", []))
                elif t == "throttle-clear":
                    throttle_brokers = []
                elif t == "finish":
                    # deliberately does NOT clear throttle_brokers: a
                    # finished execution whose throttle-clear call
                    # failed still leaks throttles, and recovery must
                    # see them
                    out.finished = True
        out.throttle_brokers = throttle_brokers
        return out

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError as exc:
                LOG.warning("journal close failed: %s", exc)
            self._fh = None

    def to_json(self) -> dict:
        return {
            "directory": self.directory,
            "broken": self.broken,
            "writes": self.writes,
            "bytesWritten": self.bytes_written,
            "errors": self.errors,
        }
