"""The execution engine.

Drives accepted proposals against the cluster in the reference's three
phases — inter-broker replica moves, intra-broker (logdir) moves, leadership
moves — with per-phase batching loops that poll cluster metadata on a check
interval, mark tasks completed/dead, re-execute stuck reassignments, and
apply replication throttles around moves (reference CC/executor/
Executor.java:74-1477, phase dispatch at :791-873, polling at :1169-1334,
re-execution at :1432-1470).

Host-side and I/O-bound by design: actual data movement happens inside the
managed cluster; this engine only requests and observes it.  Time and sleep
are injectable so the loop runs identically against wall-clock demos and
virtual-time simulated clusters.
"""
from __future__ import annotations

import logging
import threading
import time as _time
import uuid as _uuid
from typing import Callable, Dict, List, Optional, Sequence, Set

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.cluster.admin import ClusterAdminClient
from cruise_control_tpu.cluster.types import TopicPartition
from cruise_control_tpu.executor import recovery as recovery_mod
from cruise_control_tpu.executor.journal import ExecutionJournal
from cruise_control_tpu.executor.state import ExecutorPhase, ExecutorState
from cruise_control_tpu.executor.strategy import (ReplicaMovementStrategy,
                                                  strategy_from_names)
from cruise_control_tpu.executor.task import (ExecutionTask, TaskState,
                                              TaskType)
from cruise_control_tpu.executor.task_manager import ExecutionTaskManager
from cruise_control_tpu.obs import trace as obs_trace
from cruise_control_tpu.utils import faults

LOG = logging.getLogger(__name__)
#: operations audit log — one INFO line per started execution, emitted here
#: so every path (facade, self-healing, topic-RF change) is covered
#: (reference Executor.java:76,775-781 operationLogger)
OPERATION_LOG = logging.getLogger("operationLogger")


class ExecutorNotifier:
    """SPI notified when an execution finishes (reference
    ExecutorNotifier.java).  The default implementation logs the
    completion (the executor.notifier.class default)."""

    def on_execution_finished(self, uuid: str, succeeded: bool,
                              message: str) -> None:
        LOG.info("execution %s finished (succeeded=%s): %s", uuid,
                 succeeded, message)


class ExecutionStoppedException(RuntimeError):
    pass


class Executor:
    """Thread-safe, single-execution-at-a-time engine."""

    def __init__(self, admin: ClusterAdminClient,
                 load_monitor=None,
                 notifier: Optional[ExecutorNotifier] = None,
                 concurrent_inter_broker_moves_per_broker: int = 5,
                 concurrent_intra_broker_moves_per_broker: int = 2,
                 concurrent_leader_movements: int = 1000,
                 progress_check_interval_s: float = 10.0,
                 max_task_execution_idle_s: float = 190.0,
                 max_task_lifetime_s: float = 6 * 3600.0,
                 task_alerting_threshold_s: float = 90.0,
                 inter_rate_alert_threshold_mb_s: float = 0.1,
                 intra_rate_alert_threshold_mb_s: float = 0.2,
                 logdir_response_timeout_s: float = 10.0,
                 leader_movement_timeout_s: float = 180.0,
                 replication_throttle_bytes_per_s: Optional[float] = None,
                 removal_history_retention_s: float = 12 * 3600.0,
                 demotion_history_retention_s: Optional[float] = None,
                 max_cluster_movements: Optional[int] = None,
                 default_strategy: Optional[ReplicaMovementStrategy] = None,
                 max_consecutive_poll_failures: int = 10,
                 journal: Optional[ExecutionJournal] = None,
                 time_fn: Optional[Callable[[], float]] = None,
                 sleep_fn: Optional[Callable[[float], None]] = None) -> None:
        self._admin = admin
        self._load_monitor = load_monitor
        self._notifier = notifier
        self._inter_cap = concurrent_inter_broker_moves_per_broker
        self._intra_cap = concurrent_intra_broker_moves_per_broker
        self._leader_cap = concurrent_leader_movements
        self._check_interval = progress_check_interval_s
        self._max_idle = max_task_execution_idle_s
        #: absolute kill switch: any task alive longer than this is DEAD
        #: (reference max.execution.task.lifetime.ms)
        self._max_lifetime = max_task_lifetime_s
        #: warn (and notify) once a task runs longer than this (reference
        #: task.execution.alerting.threshold.ms)
        self._alert_threshold = task_alerting_threshold_s
        self._alerted_tasks: set = set()
        #: movement-rate alerting floors in MB/s (reference
        #: {inter,intra}.broker.replica.movement.rate.alerting.threshold):
        #: a task slower than its phase's floor alerts even before the
        #: age-based threshold
        self._inter_rate_alert_mb_s = inter_rate_alert_threshold_mb_s
        self._intra_rate_alert_mb_s = intra_rate_alert_threshold_mb_s
        #: timeout for logdir describe/alter calls (reference
        #: logdir.response.timeout.ms); honest-signaling: the stdlib admin
        #: SPI is synchronous, so this caps the WARNING we raise when a
        #: call overruns, it cannot abort the call
        self._logdir_timeout_s = logdir_response_timeout_s
        #: refuse executions whose task count exceeds this (reference
        #: max.num.cluster.movements guards memory/controller pressure)
        self._max_cluster_movements = max_cluster_movements
        self._default_strategy = default_strategy
        self._leader_timeout = leader_movement_timeout_s
        self._throttle_rate = replication_throttle_bytes_per_s
        self._history_retention = removal_history_retention_s
        self._demotion_retention = (demotion_history_retention_s
                                    if demotion_history_retention_s
                                    is not None
                                    else removal_history_retention_s)
        self._time = time_fn or _time.time
        self._sleep = sleep_fn or _time.sleep

        self._lock = threading.RLock()
        #: transient admin-client failures tolerated during progress
        #: polls (the poll retries next interval instead of failing the
        #: whole execution; submission paths stay fail-fast)
        self.num_poll_failures_tolerated = 0
        #: CONSECUTIVE tolerated poll failures before the execution
        #: fails anyway: tolerance is for transient blips — a
        #: permanently broken admin client must still fail the execution
        #: (pre-tolerance behavior) instead of wedging it forever with
        #: has_ongoing_execution pinned true (config key
        #: executor.max.consecutive.poll.failures; =1 is the fail-fast
        #: edge: the SECOND consecutive failure fails the run)
        self._max_consecutive_poll_failures = max(
            1, int(max_consecutive_poll_failures))
        self._consecutive_poll_failures = 0
        self._manager: Optional[ExecutionTaskManager] = None
        self._phase = ExecutorPhase.NO_TASK_IN_PROGRESS
        self._stop_requested = False
        self._force_stop = False
        self._uuid: Optional[str] = None
        self._reason: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        #: broker id -> removal/demotion time (reference Executor.java:309-366)
        self._removed_brokers: Dict[int, float] = {}
        self._demoted_brokers: Dict[int, float] = {}
        #: durable executor journal (executor/journal.py): None = the
        #: pre-journal in-memory behavior, byte for byte.  With one,
        #: every execution is a resumable WAL'd operation and the
        #: removal/demotion history survives restarts.
        self._journal = journal
        #: adopted in-flight tasks a recovery seeded for the phase
        #: loops to poll (set by _start_recovered, consumed by _run)
        self._resume_seed: Optional[Dict[TaskType, List[ExecutionTask]]] \
            = None
        #: True from replay until reconciliation settles (resume
        #: started or abort cleaned) — the anomaly detector's
        #: fix-in-progress gate includes it so a self-heal can never
        #: race an unreconciled half-moved cluster
        self._recovery_in_progress = False
        #: last recovery outcome (recovery.RecoveryReport json)
        self.last_recovery: Optional[dict] = None
        if journal is not None:
            removed, demoted = journal.load_history()
            self._removed_brokers.update(removed)
            self._demoted_brokers.update(demoted)

    def _admin_call(self, op: str, *args, **kwargs):
        """Every admin-client interaction funnels through here so the
        fault harness (utils/faults.py, sites `executor.admin.<op>`) can
        script transient cluster failures against the exact call the
        executor makes."""
        faults.inject(f"executor.admin.{op}")
        return getattr(self._admin, op)(*args, **kwargs)

    # ------------------------------------------------------------------
    # public surface
    # ------------------------------------------------------------------
    def execute_proposals(self, proposals: Sequence[ExecutionProposal],
                          reason: str = "",
                          uuid: Optional[str] = None,
                          removed_brokers: Sequence[int] = (),
                          demoted_brokers: Sequence[int] = (),
                          strategy: Optional[ReplicaMovementStrategy] = None,
                          concurrent_inter_broker_moves: Optional[int] = None,
                          concurrent_leader_movements: Optional[int] = None,
                          replication_throttle: Optional[float] = None,
                          wait: bool = False) -> str:
        """Register and start executing proposals.  Returns the execution
        uuid.  Raises if an execution is already in progress (reference
        sanityCheckExecuteProposals)."""
        for name, value in (("concurrent_inter_broker_moves",
                             concurrent_inter_broker_moves),
                            ("concurrent_leader_movements",
                             concurrent_leader_movements)):
            if value is not None and value < 1:
                raise ValueError(f"{name} must be >= 1, got {value}")
        if replication_throttle is not None and replication_throttle <= 0:
            raise ValueError("replication_throttle must be positive")
        with self._lock:
            if self._phase != ExecutorPhase.NO_TASK_IN_PROGRESS:
                raise RuntimeError(
                    f"cannot start execution in state {self._phase}")
            self._phase = ExecutorPhase.STARTING_EXECUTION
            self._stop_requested = False
            self._force_stop = False
            self._uuid = uuid or str(_uuid.uuid4())
            self._reason = reason
            self._alerted_tasks.clear()
            self._consecutive_poll_failures = 0
            now = self._time()
            for b in removed_brokers:
                self._removed_brokers[b] = now
            for b in demoted_brokers:
                self._demoted_brokers[b] = now
            inter_cap = (concurrent_inter_broker_moves
                         if concurrent_inter_broker_moves is not None
                         else self._inter_cap)
            leader_cap = (concurrent_leader_movements
                          if concurrent_leader_movements is not None
                          else self._leader_cap)
            strategy_used = strategy or self._default_strategy
            mgr = ExecutionTaskManager(
                inter_cap, self._intra_cap, leader_cap, strategy_used)
            snapshot = self._admin_call("describe_cluster")
            mgr.load_proposals(proposals,
                               sorted(snapshot.all_broker_ids))
            if (self._max_cluster_movements is not None
                    and mgr.counts().total > self._max_cluster_movements):
                self._phase = ExecutorPhase.NO_TASK_IN_PROGRESS
                raise ValueError(
                    f"execution of {mgr.counts().total} tasks exceeds "
                    f"max.num.cluster.movements="
                    f"{self._max_cluster_movements}")
            self._manager = mgr
            throttle = (replication_throttle
                        if replication_throttle is not None
                        else self._throttle_rate)
            run_uuid = self._uuid
        # outside the lock: counts() walks every task and a blocking log
        # handler must not stall state queries / stop_execution
        OPERATION_LOG.info(
            "execution %s started: %d proposals (%d inter-broker, "
            "%d intra-broker, %d leadership tasks), reason: %s",
            run_uuid, len(proposals),
            mgr.counts(TaskType.INTER_BROKER_REPLICA_ACTION).total,
            mgr.counts(TaskType.INTRA_BROKER_REPLICA_ACTION).total,
            mgr.counts(TaskType.LEADER_ACTION).total,
            reason or "(unspecified)")
        # write-ahead: the start record (full proposals + caps +
        # strategy + throttle) commits BEFORE the runnable touches the
        # cluster, so a crash at any later point is recoverable
        if self._journal is not None:
            self._journal.log_start(
                uuid=run_uuid, reason=reason, proposals=proposals,
                caps={"inter": inter_cap, "intra": self._intra_cap,
                      "leader": leader_cap},
                strategy_names=(strategy_used.chain_names()
                                if strategy_used is not None else []),
                removed_brokers=removed_brokers,
                demoted_brokers=demoted_brokers,
                throttle=throttle)
            self._save_history()
        self._thread = threading.Thread(
            target=self._run, args=(throttle,),
            name=f"proposal-execution-{run_uuid[:8]}", daemon=True)
        self._thread.start()
        if wait:
            self._thread.join()
        return run_uuid

    def stop_execution(self, force: bool = False) -> None:
        """Request graceful (or forced — cancel in-flight reassignments)
        stop (reference Executor.stopExecution / force-stop znode deletion
        :1153-1163)."""
        with self._lock:
            if self._phase == ExecutorPhase.NO_TASK_IN_PROGRESS:
                return
            self._stop_requested = True
            self._force_stop = force
            self._phase = ExecutorPhase.STOPPING_EXECUTION

    def await_completion(self, timeout: Optional[float] = None) -> bool:
        t = self._thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    @property
    def state(self) -> ExecutorState:
        with self._lock:
            if (self._phase == ExecutorPhase.NO_TASK_IN_PROGRESS
                    or self._manager is None):
                return ExecutorState.idle(recovery=self.recovery_json())
            return ExecutorState.snapshot(self._phase, self._uuid,
                                          self._reason, self._manager,
                                          recovery=self.recovery_json())

    @property
    def has_ongoing_execution(self) -> bool:
        with self._lock:
            return self._phase != ExecutorPhase.NO_TASK_IN_PROGRESS

    @property
    def recovery_in_progress(self) -> bool:
        """True while a journal replay is being reconciled — callers
        gating on has_ongoing_execution (the anomaly detector's
        one-fix-at-a-time rule) must treat this exactly the same: the
        cluster may be half-moved until reconciliation settles."""
        return self._recovery_in_progress

    def recovery_json(self) -> Optional[dict]:
        """The `recovery` block of ExecutorState: journal health + the
        last reconcile-and-resume outcome.  None (block omitted) when
        journaling is off and nothing was ever recovered — journal-less
        deployments see the exact pre-journal STATE body."""
        if self._journal is None and self.last_recovery is None \
                and not self._recovery_in_progress:
            return None
        out: dict = {
            "journalEnabled": self._journal is not None,
            "recoveryInProgress": self._recovery_in_progress,
        }
        if self._journal is not None:
            out["journal"] = self._journal.to_json()
        if self.last_recovery is not None:
            out["lastRecovery"] = self.last_recovery
        return out

    @property
    def journal(self) -> Optional[ExecutionJournal]:
        return self._journal

    def recently_removed_brokers(self) -> Set[int]:
        return self._recent(self._removed_brokers)

    def recently_demoted_brokers(self) -> Set[int]:
        return self._recent(self._demoted_brokers,
                            self._demotion_retention)

    def drop_recently_removed_brokers(self, brokers: Sequence[int]) -> None:
        with self._lock:
            for b in brokers:
                self._removed_brokers.pop(b, None)
        self._save_history()

    def drop_recently_demoted_brokers(self, brokers: Sequence[int]) -> None:
        with self._lock:
            for b in brokers:
                self._demoted_brokers.pop(b, None)
        self._save_history()

    def _save_history(self) -> None:
        """Persist the removal/demotion tables next to the journal so
        exclusion windows survive a process bounce (the reference kept
        these in ZooKeeper for the same reason)."""
        if self._journal is None:
            return
        with self._lock:
            removed = dict(self._removed_brokers)
            demoted = dict(self._demoted_brokers)
        self._journal.save_history(removed, demoted)

    def _recent(self, table: Dict[int, float],
                retention_s: Optional[float] = None) -> Set[int]:
        with self._lock:
            cutoff = self._time() - (retention_s
                                     if retention_s is not None
                                     else self._history_retention)
            for b in [b for b, t in table.items() if t < cutoff]:
                del table[b]
            return set(table)

    # ------------------------------------------------------------------
    # the execution runnable (reference ProposalExecutionRunnable)
    # ------------------------------------------------------------------
    def _run(self, throttle: Optional[float]) -> None:
        mgr = self._manager
        assert mgr is not None
        succeeded = True
        message = "execution completed"
        throttled_brokers: List[int] = []
        # adopted in-flight tasks from a crash recovery: the phase
        # loops start polling them instead of (re-)submitting
        seed = self._resume_seed or {}
        self._resume_seed = None
        try:
            if self._load_monitor is not None:
                self._load_monitor.pause_metric_sampling(
                    "executing proposals")
            if throttle is not None:
                snapshot = self._admin_call("describe_cluster")
                throttled_brokers = sorted(snapshot.alive_broker_ids)
                self._admin_call("set_replication_throttle",
                                 throttled_brokers, throttle)
                self._journal_throttle(throttled_brokers, throttle)
            self._set_phase(
                ExecutorPhase.INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS)
            self._inter_broker_move_replicas(
                mgr, seed.get(TaskType.INTER_BROKER_REPLICA_ACTION))
            self._set_phase(
                ExecutorPhase.INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS)
            self._intra_broker_move_replicas(
                mgr, seed.get(TaskType.INTRA_BROKER_REPLICA_ACTION))
            self._set_phase(ExecutorPhase.LEADER_MOVEMENT_TASK_IN_PROGRESS)
            self._move_leaderships(mgr)
        except ExecutionStoppedException:
            succeeded = False
            message = "execution stopped by user"
        except Exception as exc:  # noqa: BLE001 - report any failure
            LOG.exception("execution failed")
            succeeded = False
            message = f"execution failed: {exc}"
        finally:
            if throttled_brokers:
                try:
                    self._admin_call("clear_replication_throttle",
                                     throttled_brokers)
                    self._journal_throttle_cleared(throttled_brokers)
                except Exception:  # noqa: BLE001
                    LOG.exception("failed to clear throttles")
            if self._load_monitor is not None:
                self._load_monitor.resume_metric_sampling(
                    "execution finished")
            with self._lock:
                uuid = self._uuid
            # the finish record commits BEFORE the phase flips to
            # NO_TASK: a crash in between replays as an already-settled
            # execution (nothing to recover), never as in-flight
            if self._journal is not None:
                self._journal.log_finish(uuid, succeeded, message)
            with self._lock:
                self._phase = ExecutorPhase.NO_TASK_IN_PROGRESS
            if self._notifier is not None and uuid is not None:
                self._notifier.on_execution_finished(uuid, succeeded, message)

    def _set_phase(self, phase: ExecutorPhase) -> None:
        with self._lock:
            if self._stop_requested:
                raise ExecutionStoppedException()
            self._phase = phase
        if self._journal is not None:
            self._journal.log_phase(self._uuid, phase.value)

    # ------------------------------------------------------------------
    # journal hooks (no-ops without a journal; called only from the
    # single-writer runnable / the execute_proposals caller thread, so
    # they add no locking to the executor)
    # ------------------------------------------------------------------
    def _journal_task(self, task: ExecutionTask, now_ms: float) -> None:
        if self._journal is not None:
            self._journal.log_task(self._uuid, task.stable_key,
                                   task.state.value, now_ms,
                                   task.reexecution_count)

    def _journal_tasks(self, tasks: Sequence[ExecutionTask],
                       now_ms: float) -> None:
        for t in tasks:
            self._journal_task(t, now_ms)

    def _journal_throttle(self, brokers: Sequence[int],
                          rate: float) -> None:
        if self._journal is not None:
            self._journal.log_throttle(self._uuid, brokers, rate)

    def _journal_throttle_cleared(self, brokers: Sequence[int]) -> None:
        if self._journal is not None:
            self._journal.log_throttle_cleared(self._uuid, brokers)

    def _finish_task(self, mgr: ExecutionTaskManager, task: ExecutionTask,
                     state: TaskState, now_ms: float) -> None:
        """finish_task + journal in one step (every terminal
        transition must reach the WAL)."""
        mgr.finish_task(task, state, now_ms)
        self._journal_task(task, now_ms)

    def _check_stop(self, mgr: ExecutionTaskManager,
                    in_flight: List[ExecutionTask]) -> None:
        with self._lock:
            if not self._stop_requested:
                return
            force = self._force_stop
        now_ms = self._time() * 1000.0
        if force:
            # cancel in-flight reassignments outright
            cancel = {TopicPartition(t.proposal.partition.topic,
                                     t.proposal.partition.partition): None
                      for t in in_flight
                      if t.task_type == TaskType.INTER_BROKER_REPLICA_ACTION}
            if cancel:
                self._admin_call("alter_partition_reassignments", cancel)
            for t in list(in_flight):
                mgr.mark_aborting(t, now_ms)
                self._finish_task(mgr, t, TaskState.ABORTED, now_ms)
                in_flight.remove(t)
        else:
            for t in in_flight:
                mgr.mark_aborting(t, now_ms)
                self._journal_task(t, now_ms)
        raise ExecutionStoppedException()

    # ------------------------------------------------------------------
    # phase 1: inter-broker replica movement
    # ------------------------------------------------------------------
    def _inter_broker_move_replicas(
            self, mgr: ExecutionTaskManager,
            adopted: Optional[List[ExecutionTask]] = None) -> None:
        #: `adopted`: in-flight reassignments a crash recovery found
        #: still running in the cluster — polled to completion exactly
        #: like own submissions, NEVER re-submitted
        in_flight: List[ExecutionTask] = list(adopted or [])
        while True:
            now_ms = self._time() * 1000.0
            new_tasks = mgr.next_inter_broker_tasks(now_ms)
            # write-ahead: IN_PROGRESS records commit before the
            # submission reaches the cluster (a crash in between reads
            # as requested-but-not-submitted; reconciliation re-submits
            # safely because the cluster never saw it)
            self._journal_tasks(new_tasks, now_ms)
            if new_tasks:
                alive = self._admin_call("describe_cluster").alive_broker_ids
                targets = {}
                for t in new_tasks:
                    if any(b not in alive
                           for b in t.proposal.replicas_to_add):
                        # destination already dead — never submit
                        self._finish_task(mgr, t, TaskState.DEAD, now_ms)
                        continue
                    tp = TopicPartition(t.proposal.partition.topic,
                                        t.proposal.partition.partition)
                    targets[tp] = [r.broker_id
                                   for r in t.proposal.new_replicas]
                    in_flight.append(t)
                if targets:
                    self._admin_call("alter_partition_reassignments", targets)
            if not in_flight and not new_tasks:
                counts = mgr.counts(TaskType.INTER_BROKER_REPLICA_ACTION)
                if counts.pending == 0:
                    return
            try:
                self._check_stop(mgr, in_flight)
            except ExecutionStoppedException:
                if in_flight:
                    # graceful stop: wait for in-flight tasks to finish
                    self._drain_inter_broker(mgr, in_flight)
                raise
            self._sleep(self._check_interval)
            self._poll_inter_broker(mgr, in_flight)

    def _drain_inter_broker(self, mgr: ExecutionTaskManager,
                            in_flight: List[ExecutionTask]) -> None:
        while in_flight:
            self._sleep(self._check_interval)
            self._poll_inter_broker(mgr, in_flight)
            with self._lock:
                if self._force_stop:
                    now_ms = self._time() * 1000.0
                    cancel = {
                        TopicPartition(t.proposal.partition.topic,
                                       t.proposal.partition.partition): None
                        for t in in_flight}
                    if cancel:
                        self._admin_call("alter_partition_reassignments", cancel)
                    for t in list(in_flight):
                        self._finish_task(mgr, t, TaskState.ABORTED, now_ms)
                    in_flight.clear()

    def _tolerate_poll_failure(self, phase: str, exc: Exception) -> None:
        """A progress POLL hit a transient admin/cluster failure: the
        in-flight work is still running inside the cluster, so failing
        the whole execution would abandon it for an observation error —
        count it, log it, and observe again next interval.  (Submission
        paths stay fail-fast: not requesting work is recoverable by the
        caller, silently dropping requested work is not.)  Bounded:
        after `_max_consecutive_poll_failures` in a row the failure is
        re-raised and the execution fails — a permanently dead admin
        client must not wedge has_ongoing_execution forever."""
        self.num_poll_failures_tolerated += 1
        self._consecutive_poll_failures += 1
        if self._consecutive_poll_failures \
                > self._max_consecutive_poll_failures:
            LOG.error(
                "%s progress poll failed %d consecutive times; the admin "
                "client looks permanently broken — failing the execution",
                phase, self._consecutive_poll_failures)
            raise exc
        LOG.warning(
            "%s progress poll failed (%s: %s); retrying next interval "
            "(%d/%d consecutive, %d tolerated this process)", phase,
            type(exc).__name__, exc, self._consecutive_poll_failures,
            self._max_consecutive_poll_failures,
            self.num_poll_failures_tolerated)

    def _poll_inter_broker(self, mgr: ExecutionTaskManager,
                           in_flight: List[ExecutionTask]) -> None:
        """One metadata poll: classify each in-flight reassignment as done,
        dead, lost (re-execute), or still moving (reference
        waitForExecutionTaskToFinish + maybeReexecuteTasks — re-execution
        happens only when the cluster no longer knows about the
        reassignment, never on a wall-clock timer, so slow transfers are
        simply waited out).  Transient admin failures skip the poll
        (retried next interval) instead of failing the execution."""
        try:
            self._poll_inter_broker_once(mgr, in_flight)
            self._consecutive_poll_failures = 0
        except Exception as exc:  # noqa: BLE001 - poll is observational
            self._tolerate_poll_failure("inter-broker", exc)

    def _poll_inter_broker_once(self, mgr: ExecutionTaskManager,
                                in_flight: List[ExecutionTask]) -> None:
        snapshot = self._admin_call("describe_cluster")
        reassigning = {r.tp for r in
                       self._admin_call("list_partition_reassignments")}
        alive = snapshot.alive_broker_ids
        now_ms = self._time() * 1000.0
        for task in list(in_flight):
            p = task.proposal
            tp = TopicPartition(p.partition.topic, p.partition.partition)
            info = snapshot.partition(tp)
            new_brokers = [r.broker_id for r in p.new_replicas]
            if info is None:
                # partition deleted out from under us
                self._finish_task(mgr, task, TaskState.DEAD, now_ms)
                in_flight.remove(task)
                continue
            if tp not in reassigning and set(info.replicas) == set(new_brokers):
                state = (TaskState.ABORTED
                         if task.state == TaskState.ABORTING
                         else TaskState.COMPLETED)
                self._finish_task(mgr, task, state, now_ms)
                in_flight.remove(task)
            elif any(b not in alive for b in p.replicas_to_add):
                # a destination broker died: task cannot finish
                self._admin_call("alter_partition_reassignments", {tp: None})
                self._finish_task(mgr, task, TaskState.DEAD, now_ms)
                in_flight.remove(task)
            elif tp not in reassigning:
                # the cluster lost the reassignment (e.g. controller
                # failover): re-submit it
                task.reexecution_count += 1
                self._journal_task(task, now_ms)
                self._admin_call("alter_partition_reassignments",
                                 {tp: new_brokers})
            else:
                age_s = (now_ms - task.start_time_ms) / 1e3
                if age_s > self._max_lifetime:
                    # absolute lifetime exceeded (reference
                    # max.execution.task.lifetime.ms): cancel + mark dead
                    self._admin_call("alter_partition_reassignments", {tp: None})
                    self._finish_task(mgr, task, TaskState.DEAD, now_ms)
                    in_flight.remove(task)
                else:
                    mb = task.proposal.inter_broker_data_to_move / 1e6
                    rate = mb / max(age_s, 1e-9)
                    slow = (age_s > self._alert_threshold
                            or (age_s > self._check_interval
                                and rate < self._inter_rate_alert_mb_s
                                and mb > 0.0))
                    if slow and task.task_id not in self._alerted_tasks:
                        self._alerted_tasks.add(task.task_id)
                        LOG.warning(
                            "task %s (%s) running for %.0fs at %.2f MB/s "
                            "(alert thresholds: %.0fs / %.2f MB/s)",
                            task.task_id, tp, age_s, rate,
                            self._alert_threshold,
                            self._inter_rate_alert_mb_s)

    # ------------------------------------------------------------------
    # phase 2: intra-broker (logdir) movement
    # ------------------------------------------------------------------
    def _intra_broker_move_replicas(
            self, mgr: ExecutionTaskManager,
            adopted: Optional[List[ExecutionTask]] = None) -> None:
        in_flight: List[ExecutionTask] = list(adopted or [])
        while True:
            now_ms = self._time() * 1000.0
            new_tasks = mgr.next_intra_broker_tasks(now_ms)
            self._journal_tasks(new_tasks, now_ms)
            if new_tasks:
                moves: Dict[TopicPartition, Dict[int, str]] = {}
                for t in new_tasks:
                    tp = TopicPartition(t.proposal.partition.topic,
                                        t.proposal.partition.partition)
                    old_dirs = {r.broker_id: r.logdir
                                for r in t.proposal.old_replicas}
                    for r in t.proposal.new_replicas:
                        if (r.logdir is not None
                                and old_dirs.get(r.broker_id) is not None
                                and old_dirs[r.broker_id] != r.logdir):
                            moves.setdefault(tp, {})[r.broker_id] = r.logdir
                if moves:
                    _t0 = self._time()
                    self._admin_call("alter_replica_log_dirs", moves)
                    if self._time() - _t0 > self._logdir_timeout_s:
                        LOG.warning(
                            "alter_replica_log_dirs took %.1fs (> "
                            "logdir.response.timeout.ms)",
                            self._time() - _t0)
                in_flight.extend(new_tasks)
            if not in_flight and not new_tasks:
                if mgr.counts(TaskType.INTRA_BROKER_REPLICA_ACTION).pending \
                        == 0:
                    return
            self._check_stop(mgr, in_flight)
            self._sleep(self._check_interval)
            # poll: logdir placement matches the proposal
            try:
                snapshot = self._admin_call("describe_cluster")
                self._consecutive_poll_failures = 0
            except Exception as exc:  # noqa: BLE001 - observational
                self._tolerate_poll_failure("intra-broker", exc)
                continue
            alive = snapshot.alive_broker_ids
            now_ms = self._time() * 1000.0
            for task in list(in_flight):
                p = task.proposal
                tp = TopicPartition(p.partition.topic, p.partition.partition)
                info = snapshot.partition(tp)
                want = {r.broker_id: r.logdir for r in p.new_replicas
                        if r.logdir is not None}
                if info is None or any(b not in alive for b in want):
                    # partition deleted or the hosting broker died
                    self._finish_task(mgr, task, TaskState.DEAD, now_ms)
                    in_flight.remove(task)
                    continue
                have = dict(info.logdir_by_broker)
                if all(have.get(b) == d for b, d in want.items()):
                    self._finish_task(mgr, task, TaskState.COMPLETED,
                                      now_ms)
                    in_flight.remove(task)
                elif (now_ms - task.start_time_ms
                      > self._max_idle * 1000.0):
                    # logdir move stalled beyond the idle budget
                    self._finish_task(mgr, task, TaskState.DEAD, now_ms)
                    in_flight.remove(task)
                else:
                    age_s = (now_ms - task.start_time_ms) / 1e3
                    mb = p.intra_broker_data_to_move / 1e6
                    if (age_s > self._check_interval and mb > 0.0
                            and mb / age_s < self._intra_rate_alert_mb_s
                            and task.task_id not in self._alerted_tasks):
                        self._alerted_tasks.add(task.task_id)
                        LOG.warning(
                            "intra-broker task %s (%s) at %.2f MB/s, "
                            "below the %.2f MB/s alerting floor",
                            task.task_id, tp, mb / age_s,
                            self._intra_rate_alert_mb_s)

    # ------------------------------------------------------------------
    # phase 3: leadership movement
    # ------------------------------------------------------------------
    def _move_leaderships(self, mgr: ExecutionTaskManager) -> None:
        while True:
            now_ms = self._time() * 1000.0
            batch = mgr.next_leadership_tasks(now_ms)
            self._journal_tasks(batch, now_ms)
            if not batch:
                if mgr.counts(TaskType.LEADER_ACTION).pending == 0:
                    return
                self._sleep(self._check_interval)
                continue
            self._check_stop(mgr, batch)
            # reorder each partition's replica list so the desired leader is
            # the preferred replica (an in-place same-set reassignment), then
            # trigger preferred-leader election — the modern equivalent of
            # the reference's ZK PLE path (ExecutorUtils.scala:95-101)
            snapshot = self._admin_call("describe_cluster")
            alive = snapshot.alive_broker_ids
            tps = []
            reorders = {}
            for t in list(batch):
                p = t.proposal
                tp = TopicPartition(p.partition.topic, p.partition.partition)
                info = snapshot.partition(tp)
                want = [r.broker_id for r in p.new_replicas]
                if (info is None or p.new_leader not in alive
                        or set(info.replicas) != set(want)):
                    # leader is dead or its replica never arrived (e.g. the
                    # inter-broker task died): leadership cannot move
                    self._finish_task(mgr, t, TaskState.DEAD, now_ms)
                    batch.remove(t)
                    continue
                tps.append(tp)
                reorders[tp] = want
            if reorders:
                try:
                    self._admin_call("alter_partition_reassignments",
                                     reorders)
                    self._admin_call("elect_preferred_leaders", tps)
                except Exception as exc:  # noqa: BLE001 - deadline decides
                    # the election request failed (transient admin/
                    # controller trouble): leadership may still land if
                    # part of the request went through — poll until the
                    # leader-movement timeout marks the stragglers DEAD
                    self._tolerate_poll_failure("leadership-submit", exc)
            deadline_ms = (self._time() + self._leader_timeout) * 1000.0
            pending = list(batch)
            while pending:
                with self._lock:
                    stop = self._stop_requested
                if stop:
                    # leadership movements are instantaneous requests; on
                    # stop just abandon what hasn't landed yet
                    now_ms = self._time() * 1000.0
                    for task in pending:
                        mgr.mark_aborting(task, now_ms)
                        self._finish_task(mgr, task, TaskState.ABORTED,
                                          now_ms)
                    raise ExecutionStoppedException()
                self._sleep(min(self._check_interval,
                                self._leader_timeout / 10.0))
                now_ms = self._time() * 1000.0
                try:
                    snapshot = self._admin_call("describe_cluster")
                    self._consecutive_poll_failures = 0
                except Exception as exc:  # noqa: BLE001 - observational
                    self._tolerate_poll_failure("leadership", exc)
                    if now_ms > deadline_ms:
                        for task in pending:
                            self._finish_task(mgr, task, TaskState.DEAD,
                                              now_ms)
                        pending.clear()
                    continue
                alive = snapshot.alive_broker_ids
                for task in list(pending):
                    p = task.proposal
                    tp = TopicPartition(p.partition.topic,
                                        p.partition.partition)
                    info = snapshot.partition(tp)
                    if info is None or p.new_leader not in alive:
                        self._finish_task(mgr, task, TaskState.DEAD,
                                          now_ms)
                        pending.remove(task)
                    elif info.leader == p.new_leader:
                        self._finish_task(mgr, task, TaskState.COMPLETED,
                                          now_ms)
                        pending.remove(task)
                if now_ms > deadline_ms:
                    for task in pending:
                        self._finish_task(mgr, task, TaskState.DEAD,
                                          now_ms)
                    pending.clear()

    # ------------------------------------------------------------------
    # crash recovery: replay -> reconcile -> resume | abort-and-clean
    # (executor/journal.py + executor/recovery.py; the unclean-shutdown
    # counterpart of the PR-12 graceful drain)
    # ------------------------------------------------------------------
    def recover(self, mode: str = "resume",
                wait: bool = False) -> Optional[dict]:
        """Replay the journal and settle whatever the crashed process
        left behind.  Returns the RecoveryReport json (also kept as
        `last_recovery`), or None when there is nothing to recover.

        `mode="resume"` restarts the interrupted execution under its
        ORIGINAL uuid/caps/strategy/throttle, with moves the cluster
        already finished sealed as completed and moves still running
        adopted (polled, never re-submitted).  `mode="abort"` cancels
        the in-flight reassignments and settles the journal, leaving
        `has_ongoing_execution` false.  Both modes clear orphaned
        replication throttles FIRST.  While reconciliation runs,
        `recovery_in_progress` is True — the anomaly detector must not
        start a self-heal over a half-moved cluster."""
        if mode not in ("resume", "abort"):
            raise ValueError(
                f"executor.recovery.mode must be resume|abort, "
                f"got {mode!r}")
        if self._journal is None:
            return None
        with self._lock:
            if self._phase != ExecutorPhase.NO_TASK_IN_PROGRESS:
                raise RuntimeError(
                    "cannot recover while an execution is in progress")
            self._recovery_in_progress = True
        try:
            with obs_trace.span("recovery.replay") as sp:
                replay = self._journal.replay()
                if sp is not None:
                    sp.set_tag("records", replay.records)
                    sp.set_tag("truncated", replay.truncated)
            # orphaned throttles are cleared even for executions whose
            # finish record landed but whose clear call failed
            cleared = self._clear_orphaned_throttles(
                replay.throttle_brokers,
                replay.start.get("uuid") if replay.start else None)
            if not replay.in_flight:
                if cleared:
                    LOG.info("recovery: cleared %d orphaned "
                             "replication throttles from a settled "
                             "execution", len(cleared))
                return None
            with obs_trace.span("recovery.reconcile") as sp:
                snapshot = self._admin_call("describe_cluster")
                reassigning = [
                    r.tp for r in
                    self._admin_call("list_partition_reassignments")]
                plan = recovery_mod.reconcile(replay, snapshot,
                                              reassigning)
                if sp is not None and plan is not None:
                    sp.set_tag("adopted", plan.count(recovery_mod.ADOPT))
                    sp.set_tag("pending",
                               plan.count(recovery_mod.PENDING))
            if plan is None:
                return None
            LOG.warning("recovery: %s — mode=%s",
                        recovery_mod.plan_summary(plan), mode)
            now_ms = self._time() * 1000.0
            if mode == "abort":
                with obs_trace.span("recovery.abort"):
                    cancelled = self._abort_recovered(plan)
                report = recovery_mod.report_from_plan(
                    plan, mode, resumed=False, cancelled=cancelled,
                    now_ms=now_ms)
            else:
                with obs_trace.span("recovery.resume"):
                    self._start_recovered(plan)
                report = recovery_mod.report_from_plan(
                    plan, mode, resumed=True, cancelled=0,
                    now_ms=now_ms)
            report.cleared_throttle_brokers = cleared
            self.last_recovery = report.to_json()
        finally:
            self._recovery_in_progress = False
        if wait and mode == "resume":
            self.await_completion()
        return self.last_recovery

    def _clear_orphaned_throttles(self, brokers: List[int],
                                  uuid: Optional[str]) -> List[int]:
        if not brokers:
            return []
        try:
            self._admin_call("clear_replication_throttle", brokers)
            if self._journal is not None:
                # the clear must carry the REPLAYED execution's uuid
                # (self._uuid is None in a fresh process): replay
                # filters records by the active start's uuid, and an
                # unattributed clear would be dropped — every later
                # restart would re-clear, stripping throttles someone
                # else applied in the meantime
                self._journal.log_throttle_cleared(uuid, brokers)
            return list(brokers)
        except Exception:  # noqa: BLE001 - best effort; the resumed
            # execution re-applies and re-clears its own throttle anyway
            LOG.exception("recovery: clearing orphaned throttles on "
                          "%s failed", brokers)
            return []

    def _start_recovered(self, plan) -> str:
        """Resume the interrupted execution under its original uuid:
        reload the journaled proposals through the same deterministic
        planner, seal reconciled terminal states, adopt in-flight
        moves, and start the runnable — the phase loops then treat the
        adopted tasks exactly like own submissions."""
        now_ms = self._time() * 1000.0
        with self._lock:
            if self._phase != ExecutorPhase.NO_TASK_IN_PROGRESS:
                raise RuntimeError(
                    f"cannot resume in state {self._phase}")
            self._phase = ExecutorPhase.STARTING_EXECUTION
            self._stop_requested = False
            self._force_stop = False
            self._uuid = plan.uuid
            self._reason = (plan.reason or "recovered execution")
            self._alerted_tasks.clear()
            self._consecutive_poll_failures = 0
            now = self._time()
            for b in plan.removed_brokers:
                self._removed_brokers.setdefault(b, now)
            for b in plan.demoted_brokers:
                self._demoted_brokers.setdefault(b, now)
            caps = plan.caps
            mgr = ExecutionTaskManager(
                int(caps.get("inter", self._inter_cap)),
                int(caps.get("intra", self._intra_cap)),
                int(caps.get("leader", self._leader_cap)),
                (strategy_from_names(plan.strategy_names)
                 if plan.strategy_names else self._default_strategy))
            snapshot = self._admin_call("describe_cluster")
            mgr.load_proposals(plan.proposals,
                               sorted(snapshot.all_broker_ids))
            adopted = mgr.apply_recovery(plan.resolutions, now_ms)
            self._manager = mgr
            self._resume_seed = adopted
            run_uuid = self._uuid
        OPERATION_LOG.info(
            "execution %s RESUMED after process restart: %d tasks "
            "(%d already terminal, %d adopted in flight, %d pending), "
            "crashed in phase %s, reason: %s",
            run_uuid, len(plan.tasks),
            plan.count(recovery_mod.TERMINAL),
            plan.count(recovery_mod.ADOPT),
            plan.count(recovery_mod.PENDING),
            plan.phase_at_crash or "(unknown)",
            plan.reason or "(unspecified)")
        if self._journal is not None:
            # re-journal the execution self-contained in a fresh
            # segment: start (resumed=true) + every non-pending
            # RESOLUTION (not the fresh planner tasks, which are still
            # PENDING — a second crash must replay the sealed/adopted
            # states, and adopted tasks must keep their ORIGINAL start
            # time so the max-lifetime clock survives the bounce)
            self._journal.log_start(
                uuid=run_uuid, reason=plan.reason,
                proposals=plan.proposals, caps=plan.caps,
                strategy_names=plan.strategy_names,
                removed_brokers=plan.removed_brokers,
                demoted_brokers=plan.demoted_brokers,
                throttle=plan.throttle, resumed=True)
            for task in plan.tasks:
                res = plan.resolutions[task.stable_key]
                if res.action == recovery_mod.TERMINAL:
                    self._journal.log_task(run_uuid, task.stable_key,
                                           res.state, now_ms,
                                           res.reexecution_count)
                elif res.action == recovery_mod.ADOPT:
                    self._journal.log_task(
                        run_uuid, task.stable_key,
                        TaskState.IN_PROGRESS.value,
                        res.start_ms if res.start_ms > 0 else now_ms,
                        res.reexecution_count)
            self._save_history()
        self._thread = threading.Thread(
            target=self._run, args=(plan.throttle,),
            name=f"proposal-execution-{run_uuid[:8]}", daemon=True)
        self._thread.start()
        return run_uuid

    def _abort_recovered(self, plan) -> int:
        """Abort-and-clean: cancel adopted in-flight reassignments,
        seal every non-terminal task as aborted in the journal, and
        settle the journal with a finish record — the cluster keeps
        whatever moves already completed (metadata is truth; unwinding
        them would be a second rebalance, the operator's call)."""
        now_ms = self._time() * 1000.0
        cancel = {}
        for task in plan.adopted_tasks(
                TaskType.INTER_BROKER_REPLICA_ACTION):
            p = task.proposal
            cancel[TopicPartition(p.partition.topic,
                                  p.partition.partition)] = None
        if cancel:
            self._admin_call("alter_partition_reassignments", cancel)
        if self._journal is not None:
            for task in plan.tasks:
                res = plan.resolutions[task.stable_key]
                if res.action == recovery_mod.TERMINAL:
                    self._journal.log_task(plan.uuid, task.stable_key,
                                           res.state, now_ms,
                                           res.reexecution_count)
                else:
                    self._journal.log_task(plan.uuid, task.stable_key,
                                           TaskState.ABORTED.value,
                                           now_ms,
                                           res.reexecution_count)
            self._journal.log_finish(
                plan.uuid, False,
                f"aborted by crash recovery "
                f"({len(cancel)} in-flight reassignments cancelled)")
            self._save_history()
        OPERATION_LOG.info(
            "execution %s ABORTED by crash recovery: %d in-flight "
            "reassignments cancelled, %d tasks were already terminal",
            plan.uuid, len(cancel), plan.count(recovery_mod.TERMINAL))
        return len(cancel)
