"""Executor plane: drives rebalance proposals against the cluster.

Host-side, I/O-bound async engine (SURVEY.md §2.5); the reference's
CC/executor/ package re-designed over the ClusterAdminClient SPI.
"""
from cruise_control_tpu.executor.executor import (Executor, ExecutorNotifier)
from cruise_control_tpu.executor.journal import (ExecutionJournal,
                                                 JournalReplay)
from cruise_control_tpu.executor.planner import ExecutionTaskPlanner
from cruise_control_tpu.executor.recovery import (ReconcilePlan,
                                                  RecoveryReport, reconcile)
from cruise_control_tpu.executor.state import ExecutorPhase, ExecutorState
from cruise_control_tpu.executor.strategy import (
    BaseReplicaMovementStrategy, PostponeUrpReplicaMovementStrategy,
    PrioritizeLargeReplicaMovementStrategy,
    PrioritizeSmallReplicaMovementStrategy, ReplicaMovementStrategy,
    strategy_from_names)
from cruise_control_tpu.executor.task import (ExecutionTask, TaskState,
                                              TaskType)
from cruise_control_tpu.executor.task_manager import (ExecutionCounts,
                                                      ExecutionTaskManager)

__all__ = [
    "Executor", "ExecutorNotifier", "ExecutorPhase", "ExecutorState",
    "ExecutionJournal", "JournalReplay", "ReconcilePlan",
    "RecoveryReport", "reconcile",
    "ExecutionTask", "ExecutionTaskManager", "ExecutionTaskPlanner",
    "ExecutionCounts", "TaskState", "TaskType",
    "ReplicaMovementStrategy", "BaseReplicaMovementStrategy",
    "PrioritizeSmallReplicaMovementStrategy",
    "PrioritizeLargeReplicaMovementStrategy",
    "PostponeUrpReplicaMovementStrategy", "strategy_from_names",
]
