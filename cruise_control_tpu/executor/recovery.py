"""Reconcile-and-resume: turn a replayed executor journal into action.

The journal (executor/journal.py) records what the executor *requested*;
the cluster records what actually *happened* — and after a crash the two
disagree in every interesting way: moves the cluster finished while the
process was down, moves Kafka is still executing, moves that were
journaled but never submitted.  Reconciliation treats live cluster
metadata as ground truth (the reference's maybeReexecuteTasks
discipline applied at startup) and classifies every journaled task:

* **terminal** — the journal already recorded COMPLETED/ABORTED/DEAD,
  or the cluster state proves the move landed (placement == target and
  no ongoing reassignment), or the partition vanished (DEAD);
* **adopt**   — the cluster still lists the reassignment: the move is
  running RIGHT NOW; the resumed execution polls it to completion and
  must never re-submit it (that is the no-task-executed-twice pin);
* **pending** — neither: whatever was requested never reached the
  cluster (or the cluster lost it), so the task executes normally.

`executor.recovery.mode` then decides what to do with the plan:
``resume`` (default) restarts the SAME execution — original uuid, caps,
strategy, throttle — with terminal tasks sealed and adopted tasks
polled; ``abort`` cancels the adopted reassignments, clears throttles
and settles the journal, leaving `has_ongoing_execution` false with
removal/demotion history restored.  In BOTH modes orphaned replication
throttles are removed first.
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional, Sequence, Tuple

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.cluster.types import TopicPartition
from cruise_control_tpu.executor.journal import JournalReplay
from cruise_control_tpu.executor.planner import ExecutionTaskPlanner
from cruise_control_tpu.executor.task import (ExecutionTask, TaskState,
                                              TaskType)

LOG = logging.getLogger(__name__)

#: reconciliation verdict per task
TERMINAL = "terminal"
ADOPT = "adopt"
PENDING = "pending"


@dataclasses.dataclass
class TaskResolution:
    key: str
    action: str                      # TERMINAL | ADOPT | PENDING
    state: Optional[str] = None      # terminal TaskState name
    start_ms: float = -1.0           # adopted: original start time
    reexecution_count: int = 0


@dataclasses.dataclass
class ReconcilePlan:
    """Everything `Executor` needs to resume or abort one recovered
    execution."""

    uuid: str
    reason: str
    proposals: List[ExecutionProposal]
    caps: dict
    strategy_names: List[str]
    throttle: Optional[float]
    removed_brokers: List[int]
    demoted_brokers: List[int]
    resolutions: Dict[str, TaskResolution]
    #: planner-decomposed tasks (fresh objects, stable keys assigned)
    tasks: List[ExecutionTask]
    clear_throttle_brokers: List[int]
    phase_at_crash: Optional[str]
    journal_truncated: bool = False

    def count(self, action: str) -> int:
        return sum(1 for r in self.resolutions.values()
                   if r.action == action)

    def adopted_tasks(self, task_type: TaskType) -> List[ExecutionTask]:
        return [t for t in self.tasks
                if t.task_type is task_type
                and self.resolutions[t.stable_key].action == ADOPT]

    def to_json(self) -> dict:
        return {
            "uuid": self.uuid,
            "phaseAtCrash": self.phase_at_crash,
            "tasksTotal": len(self.tasks),
            "tasksTerminal": self.count(TERMINAL),
            "tasksAdopted": self.count(ADOPT),
            "tasksPending": self.count(PENDING),
            "clearThrottleBrokers": list(self.clear_throttle_brokers),
            "journalTruncated": self.journal_truncated,
        }


def reconcile(replay: JournalReplay, snapshot,
              reassigning_tps: Sequence[TopicPartition]
              ) -> Optional[ReconcilePlan]:
    """Build the recovery plan for the replayed journal against one
    consistent metadata observation (`snapshot` +
    `reassigning_tps` fetched by the caller through its admin client).
    Returns None when the journal holds no unfinished execution."""
    if not replay.in_flight:
        return None
    start = replay.start
    proposals = replay.proposals()
    # the SAME deterministic decomposition the original process ran:
    # stable keys line up because the planner derives them from the
    # proposal content, not from process-local counters
    planner = ExecutionTaskPlanner()
    planner.add_proposals(proposals)
    tasks = planner.all_tasks()
    reassigning = set(reassigning_tps)
    resolutions: Dict[str, TaskResolution] = {}
    for task in tasks:
        resolutions[task.stable_key] = _resolve(
            task, replay.tasks.get(task.stable_key), snapshot,
            reassigning)
    return ReconcilePlan(
        uuid=start["uuid"],
        reason=start.get("reason") or "",
        proposals=proposals,
        caps=dict(start.get("caps") or {}),
        strategy_names=list(start.get("strategy") or []),
        throttle=start.get("throttle"),
        removed_brokers=list(start.get("removed") or []),
        demoted_brokers=list(start.get("demoted") or []),
        resolutions=resolutions,
        tasks=tasks,
        clear_throttle_brokers=list(replay.throttle_brokers),
        phase_at_crash=replay.phase,
        journal_truncated=replay.truncated,
    )


def _resolve(task: ExecutionTask, recorded: Optional[dict], snapshot,
             reassigning: set) -> TaskResolution:
    """Classify one task: journal says what was requested, the cluster
    says what happened — the cluster wins."""
    key = task.stable_key
    reexec = int(recorded.get("reexec", 0)) if recorded else 0
    rec_state = recorded.get("state") if recorded else None
    if rec_state in (TaskState.COMPLETED.value, TaskState.ABORTED.value,
                     TaskState.DEAD.value):
        return TaskResolution(key, TERMINAL, state=rec_state,
                              reexecution_count=reexec)
    p = task.proposal
    tp = TopicPartition(p.partition.topic, p.partition.partition)
    info = snapshot.partition(tp)
    if info is None:
        # partition deleted while we were down
        return TaskResolution(key, TERMINAL, state=TaskState.DEAD.value,
                              reexecution_count=reexec)
    start_ms = float(recorded.get("ts", -1.0)) if recorded else -1.0
    if task.task_type is TaskType.INTER_BROKER_REPLICA_ACTION:
        want = {r.broker_id for r in p.new_replicas}
        if tp in reassigning:
            # Kafka is executing it right now: poll, never re-submit
            return TaskResolution(key, ADOPT, start_ms=start_ms,
                                  reexecution_count=reexec)
        if set(info.replicas) == want:
            return TaskResolution(key, TERMINAL,
                                  state=TaskState.COMPLETED.value,
                                  reexecution_count=reexec)
        return TaskResolution(key, PENDING, reexecution_count=reexec)
    if task.task_type is TaskType.INTRA_BROKER_REPLICA_ACTION:
        want = {r.broker_id: r.logdir for r in p.new_replicas
                if r.logdir is not None}
        have = dict(info.logdir_by_broker)
        if want and all(have.get(b) == d for b, d in want.items()):
            return TaskResolution(key, TERMINAL,
                                  state=TaskState.COMPLETED.value,
                                  reexecution_count=reexec)
        # logdir moves have no in-flight listing to prove the alter
        # ever reached the cluster (unlike reassignments), and
        # re-requesting a move to the same destination dir is
        # idempotent — so an unlanded move is always re-submitted;
        # adopting a possibly-never-submitted one would stall until
        # the idle timeout killed it
        return TaskResolution(key, PENDING, reexecution_count=reexec)
    # LEADER_ACTION: elections are near-instant requests — done if the
    # leader matches, otherwise re-request (idempotent)
    if info.leader == p.new_leader:
        return TaskResolution(key, TERMINAL,
                              state=TaskState.COMPLETED.value,
                              reexecution_count=reexec)
    return TaskResolution(key, PENDING, reexecution_count=reexec)


@dataclasses.dataclass
class RecoveryReport:
    """What a recovery pass did — surfaced through the
    EXECUTION_RECOVERY anomaly, the flight recorder and the
    ExecutorState `recovery` block."""

    mode: str
    uuid: str
    resumed: bool
    tasks_total: int = 0
    tasks_terminal: int = 0
    tasks_adopted: int = 0
    tasks_pending: int = 0
    cleared_throttle_brokers: List[int] = dataclasses.field(
        default_factory=list)
    cancelled_reassignments: int = 0
    journal_truncated: bool = False
    phase_at_crash: Optional[str] = None
    recovered_at_ms: float = 0.0

    def to_json(self) -> dict:
        return {
            "mode": self.mode,
            "uuid": self.uuid,
            "resumed": self.resumed,
            "tasksTotal": self.tasks_total,
            "tasksTerminal": self.tasks_terminal,
            "tasksAdopted": self.tasks_adopted,
            "tasksPending": self.tasks_pending,
            "clearedThrottleBrokers": list(self.cleared_throttle_brokers),
            "cancelledReassignments": self.cancelled_reassignments,
            "journalTruncated": self.journal_truncated,
            "phaseAtCrash": self.phase_at_crash,
            "recoveredAtMs": self.recovered_at_ms,
        }


def report_from_plan(plan: ReconcilePlan, mode: str, resumed: bool,
                     cancelled: int, now_ms: float) -> RecoveryReport:
    return RecoveryReport(
        mode=mode, uuid=plan.uuid, resumed=resumed,
        tasks_total=len(plan.tasks),
        tasks_terminal=plan.count(TERMINAL),
        tasks_adopted=plan.count(ADOPT),
        tasks_pending=plan.count(PENDING),
        cleared_throttle_brokers=list(plan.clear_throttle_brokers),
        cancelled_reassignments=cancelled,
        journal_truncated=plan.journal_truncated,
        phase_at_crash=plan.phase_at_crash,
        recovered_at_ms=now_ms)


def plan_summary(plan: Optional[ReconcilePlan]) -> str:
    if plan is None:
        return "nothing to recover"
    return (f"execution {plan.uuid}: {len(plan.tasks)} tasks "
            f"({plan.count(TERMINAL)} terminal, {plan.count(ADOPT)} "
            f"adopted in-flight, {plan.count(PENDING)} pending), "
            f"crashed in phase {plan.phase_at_crash or 'unknown'}")


def stable_keys(tasks: Sequence[ExecutionTask]) -> Tuple[str, ...]:
    return tuple(t.stable_key for t in tasks)
