"""Execution task planner.

Turns each ExecutionProposal into at most one leadership task, at most one
inter-broker movement task, and any number of intra-broker (logdir) movement
tasks, then serves them per broker in strategy order — the behavior of the
reference's ExecutionTaskPlanner (reference CC/executor/
ExecutionTaskPlanner.java:68-446).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor.strategy import (BaseReplicaMovementStrategy,
                                                  ReplicaMovementStrategy)
from cruise_control_tpu.executor.task import (ExecutionTask, TaskState,
                                              TaskType)


class ExecutionTaskPlanner:
    """Stateful planner: load proposals once, pop executable tasks as
    concurrency slots open."""

    def __init__(self,
                 strategy: Optional[ReplicaMovementStrategy] = None) -> None:
        self._strategy = strategy or BaseReplicaMovementStrategy()
        self._leadership_tasks: List[ExecutionTask] = []
        self._inter_broker_tasks: List[ExecutionTask] = []
        self._intra_broker_tasks: List[ExecutionTask] = []

    # ------------------------------------------------------------------
    # planning
    # ------------------------------------------------------------------
    def add_proposals(self, proposals: Sequence[ExecutionProposal]) -> None:
        """Decompose proposals into typed tasks
        (ExecutionTaskPlanner.addExecutionProposal).  Stable keys are
        assigned here, from proposal content — the decomposition is
        deterministic, so a restarted process replaying the journaled
        proposals derives the SAME keys (executor/journal.py)."""
        for p in proposals:
            tp = f"{p.partition.topic}:{p.partition.partition}"
            if p.has_replica_action:
                self._inter_broker_tasks.append(ExecutionTask(
                    ExecutionTask.next_id(), p,
                    TaskType.INTER_BROKER_REPLICA_ACTION,
                    stable_key=f"INTER:{tp}"))
            if p.has_leader_action:
                # runs in phase 3, after any replica movement has landed the
                # new leader's replica (Executor.java execute() phase order)
                self._leadership_tasks.append(ExecutionTask(
                    ExecutionTask.next_id(), p, TaskType.LEADER_ACTION,
                    stable_key=f"LEADER:{tp}"))
            for intra in self._intra_broker_moves(p):
                self._intra_broker_tasks.append(intra)
        self._inter_broker_tasks = self._strategy.sorted_tasks(
            self._inter_broker_tasks)

    @staticmethod
    def _intra_broker_moves(p: ExecutionProposal) -> List[ExecutionTask]:
        """Same-broker logdir changes (reference planner's
        maybeAddIntraBrokerReplicaMovementTasks)."""
        old_by_broker = {r.broker_id: r.logdir for r in p.old_replicas}
        tasks = []
        for r in p.new_replicas:
            old_dir = old_by_broker.get(r.broker_id)
            if (r.broker_id in old_by_broker and r.logdir is not None
                    and old_dir is not None and r.logdir != old_dir):
                tasks.append(ExecutionTask(
                    ExecutionTask.next_id(), p,
                    TaskType.INTRA_BROKER_REPLICA_ACTION,
                    stable_key=(f"INTRA:{p.partition.topic}:"
                                f"{p.partition.partition}:{len(tasks)}")))
        return tasks

    # ------------------------------------------------------------------
    # serving
    # ------------------------------------------------------------------
    @property
    def remaining_leadership_tasks(self) -> List[ExecutionTask]:
        return [t for t in self._leadership_tasks
                if t.state == TaskState.PENDING]

    @property
    def remaining_inter_broker_tasks(self) -> List[ExecutionTask]:
        return [t for t in self._inter_broker_tasks
                if t.state == TaskState.PENDING]

    @property
    def remaining_intra_broker_tasks(self) -> List[ExecutionTask]:
        return [t for t in self._intra_broker_tasks
                if t.state == TaskState.PENDING]

    def pop_inter_broker_tasks(
            self, slots_by_broker: Dict[int, int]) -> List[ExecutionTask]:
        """Next batch of inter-broker moves honoring per-broker concurrency
        slots.  A task consumes a slot on EVERY participating broker (both
        adding and removing sides), matching the reference's per-broker
        in-flight accounting (ExecutionTaskPlanner.getInterBrokerReplica
        MovementTasks)."""
        picked: List[ExecutionTask] = []
        slots = dict(slots_by_broker)
        for task in self.remaining_inter_broker_tasks:
            brokers = task.participants()
            if all(slots.get(b, 0) > 0 for b in brokers):
                for b in brokers:
                    slots[b] = slots.get(b, 0) - 1
                picked.append(task)
        return picked

    def pop_intra_broker_tasks(
            self, slots_by_broker: Dict[int, int]) -> List[ExecutionTask]:
        picked: List[ExecutionTask] = []
        slots = dict(slots_by_broker)
        for task in self.remaining_intra_broker_tasks:
            brokers = task.intra_brokers()
            if all(slots.get(b, 0) > 0 for b in brokers):
                for b in brokers:
                    slots[b] = slots.get(b, 0) - 1
                picked.append(task)
        return picked

    def pop_leadership_tasks(self, max_tasks: int) -> List[ExecutionTask]:
        return self.remaining_leadership_tasks[:max_tasks]

    # ------------------------------------------------------------------
    def all_tasks(self) -> List[ExecutionTask]:
        return (self._inter_broker_tasks + self._intra_broker_tasks
                + self._leadership_tasks)

    def clear(self) -> None:
        self._leadership_tasks.clear()
        self._inter_broker_tasks.clear()
        self._intra_broker_tasks.clear()
