"""Replica-movement ordering strategies.

SPI mirroring the reference's ReplicaMovementStrategy chain
(reference CC/executor/strategy/*.java, ~180 LoC): a strategy yields a
comparator over inter-broker movement tasks and may be chained with a
fallback that breaks ties.  The terminal tie-break is always task id
(proposal order), the reference's BaseReplicaMovementStrategy.
"""
from __future__ import annotations

import abc
from typing import Callable, List, Optional, Sequence, Set

from cruise_control_tpu.cluster.types import TopicPartition
from cruise_control_tpu.executor.task import ExecutionTask

#: sort key: smaller sorts first
SortKey = Callable[[ExecutionTask], tuple]


class ReplicaMovementStrategy(abc.ABC):
    """Orders inter-broker replica movement tasks for execution."""

    def __init__(self) -> None:
        self._next: Optional[ReplicaMovementStrategy] = None

    def chain(self, nxt: "ReplicaMovementStrategy") -> "ReplicaMovementStrategy":
        """Append a tie-breaking strategy (reference
        AbstractReplicaMovementStrategy.chain)."""
        tail = self
        while tail._next is not None:
            tail = tail._next
        tail._next = nxt
        return self

    @abc.abstractmethod
    def _key(self, task: ExecutionTask) -> float:
        """Per-task priority scalar; smaller executes earlier."""

    def sort_key(self) -> SortKey:
        chain: List[ReplicaMovementStrategy] = []
        node: Optional[ReplicaMovementStrategy] = self
        while node is not None:
            chain.append(node)
            node = node._next

        def key(task: ExecutionTask) -> tuple:
            return tuple(s._key(task) for s in chain) + (task.task_id,)
        return key

    def sorted_tasks(self, tasks: Sequence[ExecutionTask]
                     ) -> List[ExecutionTask]:
        return sorted(tasks, key=self.sort_key())

    def name(self) -> str:
        return type(self).__name__

    def chain_names(self) -> List[str]:
        """Every strategy name in chain order — the round-trippable
        form the executor journal records so a resumed execution
        rebuilds the SAME ordering via `strategy_from_names`."""
        out: List[str] = []
        node: Optional[ReplicaMovementStrategy] = self
        while node is not None:
            out.append(node.name())
            node = node._next
        return out


class BaseReplicaMovementStrategy(ReplicaMovementStrategy):
    """Proposal order (task-id ascending) — the default."""

    def _key(self, task: ExecutionTask) -> float:
        return task.task_id


class PrioritizeSmallReplicaMovementStrategy(ReplicaMovementStrategy):
    """Smallest partitions first — drains many cheap moves early."""

    def _key(self, task: ExecutionTask) -> float:
        return task.proposal.partition_size


class PrioritizeLargeReplicaMovementStrategy(ReplicaMovementStrategy):
    """Largest partitions first — starts long transfers immediately."""

    def _key(self, task: ExecutionTask) -> float:
        return -task.proposal.partition_size


class PostponeUrpReplicaMovementStrategy(ReplicaMovementStrategy):
    """Partitions with no under-replicated/offline replicas move first
    (reference PostponeUrpReplicaMovementStrategy)."""

    def __init__(self, urp_partitions: Optional[Set[TopicPartition]] = None):
        super().__init__()
        self._urp = urp_partitions or set()

    def set_urp(self, urp_partitions: Set[TopicPartition]) -> None:
        self._urp = set(urp_partitions)

    def _key(self, task: ExecutionTask) -> float:
        p = task.proposal.partition
        tp = TopicPartition(p.topic, p.partition)
        return 1.0 if tp in self._urp else 0.0


STRATEGIES = {
    "BaseReplicaMovementStrategy": BaseReplicaMovementStrategy,
    "PrioritizeSmallReplicaMovementStrategy":
        PrioritizeSmallReplicaMovementStrategy,
    "PrioritizeLargeReplicaMovementStrategy":
        PrioritizeLargeReplicaMovementStrategy,
    "PostponeUrpReplicaMovementStrategy": PostponeUrpReplicaMovementStrategy,
}


def strategy_from_names(names: Sequence[str]) -> ReplicaMovementStrategy:
    """Build a chained strategy from config names; always terminates with
    the base strategy so ordering is total."""
    root: Optional[ReplicaMovementStrategy] = None
    for n in names:
        cls = STRATEGIES.get(n)
        if cls is None:
            raise ValueError(f"unknown replica movement strategy {n!r}")
        s = cls()
        root = s if root is None else root.chain(s)
    base = BaseReplicaMovementStrategy()
    return base if root is None else root.chain(base)
