"""REST-visible executor state.

Reference ExecutorState.java:1-504 — one of NO_TASK_IN_PROGRESS,
STARTING_EXECUTION, three per-phase IN_PROGRESS states, and
STOPPING_EXECUTION, plus progress counters per task type.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Dict, Optional

from cruise_control_tpu.executor.task_manager import (ExecutionCounts,
                                                      ExecutionTaskManager)
from cruise_control_tpu.executor.task import TaskType


class ExecutorPhase(enum.Enum):
    NO_TASK_IN_PROGRESS = "NO_TASK_IN_PROGRESS"
    STARTING_EXECUTION = "STARTING_EXECUTION"
    INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = (
        "INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS")
    INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = (
        "INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS")
    LEADER_MOVEMENT_TASK_IN_PROGRESS = "LEADER_MOVEMENT_TASK_IN_PROGRESS"
    STOPPING_EXECUTION = "STOPPING_EXECUTION"


@dataclasses.dataclass(frozen=True)
class ExecutorState:
    """Immutable snapshot for the STATE endpoint."""

    phase: ExecutorPhase
    uuid: Optional[str] = None
    reason: Optional[str] = None
    inter_broker: Optional[ExecutionCounts] = None
    intra_broker: Optional[ExecutionCounts] = None
    leadership: Optional[ExecutionCounts] = None
    data_to_move_mb: float = 0.0
    data_moved_mb: float = 0.0
    #: crash-recovery telemetry (executor/journal.py + recovery.py):
    #: journal health and the last reconcile-and-resume outcome
    recovery: Optional[Dict] = None

    @staticmethod
    def idle(recovery: Optional[Dict] = None) -> "ExecutorState":
        return ExecutorState(ExecutorPhase.NO_TASK_IN_PROGRESS,
                             recovery=recovery)

    @staticmethod
    def snapshot(phase: ExecutorPhase, uuid: Optional[str],
                 reason: Optional[str],
                 manager: ExecutionTaskManager,
                 recovery: Optional[Dict] = None) -> "ExecutorState":
        return ExecutorState(
            phase=phase, uuid=uuid, reason=reason,
            inter_broker=manager.counts(TaskType.INTER_BROKER_REPLICA_ACTION),
            intra_broker=manager.counts(TaskType.INTRA_BROKER_REPLICA_ACTION),
            leadership=manager.counts(TaskType.LEADER_ACTION),
            data_to_move_mb=manager.inter_broker_data_to_move / 1e6,
            data_moved_mb=manager.inter_broker_data_moved / 1e6,
            recovery=recovery,
        )

    def to_json(self) -> Dict:
        out: Dict = {"state": self.phase.value}
        if self.recovery is not None:
            out["recovery"] = self.recovery
        if self.phase == ExecutorPhase.NO_TASK_IN_PROGRESS:
            return out
        out["triggeredUserTaskId"] = self.uuid
        out["reason"] = self.reason
        for name, counts in (("interBrokerReplicaMovement", self.inter_broker),
                             ("intraBrokerReplicaMovement", self.intra_broker),
                             ("leadershipMovement", self.leadership)):
            if counts is not None:
                out[name] = {
                    "total": counts.total, "pending": counts.pending,
                    "inProgress": counts.in_progress,
                    "aborting": counts.aborting, "aborted": counts.aborted,
                    "dead": counts.dead, "completed": counts.completed,
                }
        out["finishedDataMovementMB"] = self.data_moved_mb
        out["totalDataToMoveMB"] = self.data_to_move_mb
        return out
