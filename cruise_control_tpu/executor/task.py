"""Execution tasks — the unit of work the executor drives to completion.

Mirrors the reference's task model (reference CC/executor/ExecutionTask.java:
1-321): a task wraps one ExecutionProposal with an action type and walks the
state machine PENDING -> IN_PROGRESS -> {COMPLETED, ABORTING -> ABORTED,
DEAD}.  Tasks are host-side objects: execution is I/O-bound against the
cluster's control plane, so nothing here touches the device.
"""
from __future__ import annotations

import dataclasses
import enum
import itertools

from cruise_control_tpu.analyzer.proposals import ExecutionProposal


class TaskType(enum.Enum):
    """Reference ExecutionTask.TaskType."""

    INTER_BROKER_REPLICA_ACTION = "INTER_BROKER_REPLICA_ACTION"
    INTRA_BROKER_REPLICA_ACTION = "INTRA_BROKER_REPLICA_ACTION"
    LEADER_ACTION = "LEADER_ACTION"


class TaskState(enum.Enum):
    """Reference ExecutionTask.State (ExecutionTask.java:31-44)."""

    PENDING = "PENDING"
    IN_PROGRESS = "IN_PROGRESS"
    ABORTING = "ABORTING"
    ABORTED = "ABORTED"
    DEAD = "DEAD"
    COMPLETED = "COMPLETED"


#: legal state-machine transitions (ExecutionTask.java VALID_TRANSFER map)
_VALID = {
    TaskState.PENDING: {TaskState.IN_PROGRESS},
    TaskState.IN_PROGRESS: {TaskState.ABORTING, TaskState.DEAD,
                            TaskState.COMPLETED},
    TaskState.ABORTING: {TaskState.ABORTED, TaskState.DEAD},
    TaskState.ABORTED: set(),
    TaskState.DEAD: set(),
    TaskState.COMPLETED: set(),
}

_task_ids = itertools.count()


@dataclasses.dataclass
class ExecutionTask:
    """One executable action derived from a proposal."""

    task_id: int
    proposal: ExecutionProposal
    task_type: TaskType
    state: TaskState = TaskState.PENDING
    start_time_ms: float = -1.0
    end_time_ms: float = -1.0
    #: how often the executor has observed no progress and re-submitted
    reexecution_count: int = 0
    #: process-independent identity for the durable journal: derived
    #: from the proposal CONTENT by the planner (type:topic:partition
    #: [:index]), so a restarted process decomposing the same journaled
    #: proposals lines its tasks up with the crashed process's records
    #: (task_id is a process-local counter and cannot)
    stable_key: str = ""

    @staticmethod
    def next_id() -> int:
        return next(_task_ids)

    # ---- state machine ----
    def _transition(self, to: TaskState, now_ms: float) -> None:
        if to not in _VALID[self.state]:
            raise ValueError(
                f"illegal task transition {self.state} -> {to} "
                f"(task {self.task_id})")
        self.state = to
        if to == TaskState.IN_PROGRESS:
            self.start_time_ms = now_ms
        if to in (TaskState.COMPLETED, TaskState.ABORTED, TaskState.DEAD):
            self.end_time_ms = now_ms

    def in_progress(self, now_ms: float) -> None:
        self._transition(TaskState.IN_PROGRESS, now_ms)

    def completed(self, now_ms: float) -> None:
        self._transition(TaskState.COMPLETED, now_ms)

    def aborting(self, now_ms: float) -> None:
        self._transition(TaskState.ABORTING, now_ms)

    def aborted(self, now_ms: float) -> None:
        self._transition(TaskState.ABORTED, now_ms)

    def kill(self, now_ms: float) -> None:
        self._transition(TaskState.DEAD, now_ms)

    # ---- queries ----
    def participants(self) -> set:
        """Brokers touched by this task (old + new replica sets) — the
        slot-accounting unit for inter-broker concurrency."""
        p = self.proposal
        return ({r.broker_id for r in p.old_replicas}
                | {r.broker_id for r in p.new_replicas})

    def intra_brokers(self) -> set:
        """Brokers where this task moves a replica between logdirs (the
        new∩old set) — the slot-accounting unit for intra-broker moves."""
        p = self.proposal
        return ({r.broker_id for r in p.new_replicas}
                & {r.broker_id for r in p.old_replicas})

    @property
    def done(self) -> bool:
        return self.state in (TaskState.COMPLETED, TaskState.ABORTED,
                              TaskState.DEAD)

    @property
    def active(self) -> bool:
        return self.state in (TaskState.IN_PROGRESS, TaskState.ABORTING)

    def to_json(self) -> dict:
        return {
            "executionId": self.task_id,
            "type": self.task_type.value,
            "state": self.state.value,
            "proposal": self.proposal.to_json(),
        }
