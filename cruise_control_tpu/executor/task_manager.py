"""Execution task manager.

Tracks in-flight tasks against per-broker concurrency caps and aggregates
progress counters — the reference's ExecutionTaskManager (reference
CC/executor/ExecutionTaskManager.java:1-469).  Single-writer: only the
executor's runnable mutates it; REST state reads take the lock.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Sequence

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor.planner import ExecutionTaskPlanner
from cruise_control_tpu.executor.strategy import ReplicaMovementStrategy
from cruise_control_tpu.executor.task import (ExecutionTask, TaskState,
                                              TaskType)


@dataclasses.dataclass
class ExecutionCounts:
    """Progress snapshot used by ExecutorState responses."""

    total: int = 0
    pending: int = 0
    in_progress: int = 0
    aborting: int = 0
    aborted: int = 0
    dead: int = 0
    completed: int = 0

    @property
    def finished(self) -> int:
        return self.aborted + self.dead + self.completed


class ExecutionTaskManager:
    """Owns the planner plus per-broker in-flight accounting."""

    def __init__(self,
                 concurrent_inter_broker_moves_per_broker: int = 5,
                 concurrent_intra_broker_moves_per_broker: int = 2,
                 concurrent_leader_movements: int = 1000,
                 strategy: Optional[ReplicaMovementStrategy] = None) -> None:
        self._lock = threading.RLock()
        self._planner = ExecutionTaskPlanner(strategy)
        self._inter_cap = concurrent_inter_broker_moves_per_broker
        self._intra_cap = concurrent_intra_broker_moves_per_broker
        self._leader_cap = concurrent_leader_movements
        self._in_flight_inter: Dict[int, int] = {}   # broker -> count
        self._in_flight_intra: Dict[int, int] = {}
        self._in_flight_leaders = 0
        self._inter_data_to_move = 0.0
        self._inter_data_moved = 0.0

    # ------------------------------------------------------------------
    def load_proposals(self, proposals: Sequence[ExecutionProposal],
                       brokers: Sequence[int]) -> None:
        with self._lock:
            self._planner.add_proposals(proposals)
            for b in brokers:
                self._in_flight_inter.setdefault(b, 0)
                self._in_flight_intra.setdefault(b, 0)
            self._inter_data_to_move = sum(
                t.proposal.inter_broker_data_to_move
                for t in self._planner.all_tasks()
                if t.task_type == TaskType.INTER_BROKER_REPLICA_ACTION)

    # ------------------------------------------------------------------
    # popping work (marks tasks IN_PROGRESS and reserves slots)
    # ------------------------------------------------------------------
    def next_inter_broker_tasks(self, now_ms: float) -> List[ExecutionTask]:
        with self._lock:
            slots = {b: self._inter_cap - used
                     for b, used in self._in_flight_inter.items()}
            tasks = self._planner.pop_inter_broker_tasks(slots)
            for t in tasks:
                t.in_progress(now_ms)
                for b in t.participants():
                    self._in_flight_inter[b] = (
                        self._in_flight_inter.get(b, 0) + 1)
            return tasks

    def next_intra_broker_tasks(self, now_ms: float) -> List[ExecutionTask]:
        with self._lock:
            slots = {b: self._intra_cap - used
                     for b, used in self._in_flight_intra.items()}
            tasks = self._planner.pop_intra_broker_tasks(slots)
            for t in tasks:
                t.in_progress(now_ms)
                for b in t.intra_brokers():
                    self._in_flight_intra[b] = (
                        self._in_flight_intra.get(b, 0) + 1)
            return tasks

    def next_leadership_tasks(self, now_ms: float) -> List[ExecutionTask]:
        with self._lock:
            free = self._leader_cap - self._in_flight_leaders
            tasks = self._planner.pop_leadership_tasks(max(0, free))
            for t in tasks:
                t.in_progress(now_ms)
            self._in_flight_leaders += len(tasks)
            return tasks

    # ------------------------------------------------------------------
    # finishing work (releases slots)
    # ------------------------------------------------------------------
    def finish_task(self, task: ExecutionTask, state: TaskState,
                    now_ms: float) -> None:
        with self._lock:
            if state == TaskState.COMPLETED:
                task.completed(now_ms)
            elif state == TaskState.ABORTED:
                task.aborted(now_ms)
            elif state == TaskState.DEAD:
                task.kill(now_ms)
            else:
                raise ValueError(f"not a terminal state: {state}")
            if task.task_type == TaskType.INTER_BROKER_REPLICA_ACTION:
                for b in task.participants():
                    self._in_flight_inter[b] = max(
                        0, self._in_flight_inter.get(b, 0) - 1)
                if state == TaskState.COMPLETED:
                    self._inter_data_moved += (
                        task.proposal.inter_broker_data_to_move)
            elif task.task_type == TaskType.INTRA_BROKER_REPLICA_ACTION:
                for b in task.intra_brokers():
                    self._in_flight_intra[b] = max(
                        0, self._in_flight_intra.get(b, 0) - 1)
            else:
                self._in_flight_leaders = max(0, self._in_flight_leaders - 1)

    def mark_aborting(self, task: ExecutionTask, now_ms: float) -> None:
        with self._lock:
            if task.state == TaskState.IN_PROGRESS:
                task.aborting(now_ms)

    # ------------------------------------------------------------------
    # crash recovery (executor/recovery.py reconcile plans)
    # ------------------------------------------------------------------
    def apply_recovery(self, resolutions, now_ms: float):
        """Seal reconciled task states into a freshly-loaded manager.

        Terminal resolutions walk the legal state machine (PENDING →
        IN_PROGRESS → terminal) WITHOUT touching in-flight slot
        accounting — those slots were never reserved in this process.
        Adopted resolutions mark the task IN_PROGRESS (original start
        time when the journal recorded one) AND reserve its slots, so
        the resumed phase loops respect the concurrency caps and the
        eventual `finish_task` decrement balances.  Returns the adopted
        tasks by type for the phase loops to poll."""
        # imported here, not at module top: recovery.py sits above this
        # module in the executor package's layering (it imports the
        # planner), and only this method needs its verdict constants
        from cruise_control_tpu.executor.recovery import ADOPT, TERMINAL
        adopted = {t: [] for t in TaskType}
        with self._lock:
            for task in self._planner.all_tasks():
                res = resolutions.get(task.stable_key)
                if res is None:
                    continue
                task.reexecution_count = res.reexecution_count
                if res.action == TERMINAL:
                    task.in_progress(now_ms)
                    state = TaskState(res.state)
                    if state is TaskState.COMPLETED:
                        task.completed(now_ms)
                        if task.task_type \
                                is TaskType.INTER_BROKER_REPLICA_ACTION:
                            self._inter_data_moved += (
                                task.proposal.inter_broker_data_to_move)
                    elif state is TaskState.ABORTED:
                        task.aborting(now_ms)
                        task.aborted(now_ms)
                    else:
                        task.kill(now_ms)
                elif res.action == ADOPT:
                    start = res.start_ms if res.start_ms > 0 else now_ms
                    task.in_progress(start)
                    if task.task_type \
                            is TaskType.INTER_BROKER_REPLICA_ACTION:
                        for b in task.participants():
                            self._in_flight_inter[b] = (
                                self._in_flight_inter.get(b, 0) + 1)
                    elif task.task_type \
                            is TaskType.INTRA_BROKER_REPLICA_ACTION:
                        for b in task.intra_brokers():
                            self._in_flight_intra[b] = (
                                self._in_flight_intra.get(b, 0) + 1)
                    else:
                        self._in_flight_leaders += 1
                    adopted[task.task_type].append(task)
                # "pending": leave the task PENDING for normal serving
        return adopted

    # ------------------------------------------------------------------
    # queries
    # ------------------------------------------------------------------
    def counts(self, task_type: Optional[TaskType] = None) -> ExecutionCounts:
        with self._lock:
            c = ExecutionCounts()
            for t in self._planner.all_tasks():
                if task_type is not None and t.task_type != task_type:
                    continue
                c.total += 1
                attr = t.state.value.lower()
                setattr(c, attr, getattr(c, attr) + 1)
            return c

    def tasks_in_state(self, state: TaskState,
                       task_type: Optional[TaskType] = None
                       ) -> List[ExecutionTask]:
        with self._lock:
            return [t for t in self._planner.all_tasks()
                    if t.state == state
                    and (task_type is None or t.task_type == task_type)]

    @property
    def inter_broker_data_to_move(self) -> float:
        with self._lock:
            return self._inter_data_to_move

    @property
    def inter_broker_data_moved(self) -> float:
        with self._lock:
            return self._inter_data_moved

    def clear(self) -> None:
        with self._lock:
            self._planner.clear()
            self._in_flight_inter.clear()
            self._in_flight_intra.clear()
            self._in_flight_leaders = 0
            self._inter_data_to_move = self._inter_data_moved = 0.0
