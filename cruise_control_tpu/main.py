"""Application entry point.

Reference CC/KafkaCruiseControlMain.java:23-53 + KafkaCruiseControlApp.java:
read a properties file, build the service stack from config, start the REST
server, block until interrupted.  Pluggable classes (sampler, sample store,
capacity resolver, notifiers, security provider) are instantiated from
config exactly like the reference's getConfiguredInstance wiring.
"""
from __future__ import annotations

import argparse
import logging
import logging.handlers
import os
import signal
import sys
import threading
from typing import Optional

from cruise_control_tpu.api.security import (BasicSecurityProvider,
                                             NoSecurityProvider)
from cruise_control_tpu.api.server import CruiseControlApp
from cruise_control_tpu.config.capacity import (
    BrokerCapacityConfigFileResolver, BrokerCapacityConfigResolver)
from cruise_control_tpu.config.main_config import CruiseControlConfig
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.monitor.sampling.sample_store import SampleStore
from cruise_control_tpu.monitor.sampling.sampler import MetricSampler

LOG = logging.getLogger(__name__)


#: Java-style `key=value` properties file with ${env:NAME} secret
#: resolution (reference readConfig + EnvConfigProvider)
from cruise_control_tpu.common.config import (  # noqa: E402
    ConfigException, load_properties as read_properties)


def build_constraint(config: CruiseControlConfig):
    """BalancingConstraint from the analyzer threshold keys (reference
    BalancingConstraint(KafkaCruiseControlConfig)).  Tuple order follows
    the Resource enum: CPU, NW_IN, NW_OUT, DISK."""
    from cruise_control_tpu.analyzer.context import BalancingConstraint

    def per_resource(fmt_cpu, fmt_nw_in, fmt_nw_out, fmt_disk):
        return (config.get_double(fmt_cpu), config.get_double(fmt_nw_in),
                config.get_double(fmt_nw_out), config.get_double(fmt_disk))

    return BalancingConstraint(
        resource_balance_percentage=per_resource(
            "cpu.balance.threshold", "network.inbound.balance.threshold",
            "network.outbound.balance.threshold", "disk.balance.threshold"),
        capacity_threshold=per_resource(
            "cpu.capacity.threshold", "network.inbound.capacity.threshold",
            "network.outbound.capacity.threshold",
            "disk.capacity.threshold"),
        low_utilization_threshold=per_resource(
            "cpu.low.utilization.threshold",
            "network.inbound.low.utilization.threshold",
            "network.outbound.low.utilization.threshold",
            "disk.low.utilization.threshold"),
        replica_balance_percentage=config.get_double(
            "replica.count.balance.threshold"),
        leader_replica_balance_percentage=config.get_double(
            "leader.replica.count.balance.threshold"),
        topic_replica_balance_percentage=config.get_double(
            "topic.replica.count.balance.threshold"),
        max_replicas_per_broker=int(
            config.get_long("max.replicas.per.broker")),
        goal_violation_distribution_threshold_multiplier=config.get_double(
            "goal.violation.distribution.threshold.multiplier"),
    )


def _goal_lists(config: CruiseControlConfig):
    """(goals, default, hard, detection, self-healing, intra-broker) from
    config with the reference's sanity rules: default.goals and hard.goals
    must be subsets of goals (KafkaCruiseControlConfig.sanityCheckGoalNames).
    """
    allowed = [g for g in config.get_list("goals") if g]
    default = [g for g in config.get_list("default.goals") if g] or allowed
    hard = [g for g in config.get_list("hard.goals") if g]
    for name, lst in (("default.goals", default), ("hard.goals", hard)):
        bad = [g for g in lst if allowed and g not in allowed]
        if bad:
            raise ValueError(f"{name} entries {bad} are not in `goals`")
    detection = ([g for g in config.get_list("anomaly.detection.goals")
                  if g] or None)
    self_healing = ([g for g in config.get_list("self.healing.goals")
                     if g] or None)
    intra = [g for g in config.get_list("intra.broker.goals") if g] or None
    return default, detection, self_healing, intra


def _detector_interval(config: CruiseControlConfig, key: str) -> float:
    """Per-type detector interval with the -1 → anomaly.detection.interval
    fallback (reference AnomalyDetectorConfig)."""
    v = config.get_long(key)
    if v < 0:
        v = config.get_long("anomaly.detection.interval.ms")
    return v / 1e3


def build_notifier(config: CruiseControlConfig):
    """AnomalyNotifier from config: the default SelfHealingNotifier gets
    the self.healing.* switches and broker-failure thresholds; any other
    class comes from the standard configured-instance hook."""
    from cruise_control_tpu.common.config import resolve_class
    from cruise_control_tpu.core.anomaly import AnomalyType
    from cruise_control_tpu.detector.notifier import SelfHealingNotifier
    cls = resolve_class(config.get("anomaly.notifier.class"))
    if not issubclass(cls, SelfHealingNotifier):
        return config.get_configured_instance("anomaly.notifier.class")
    master = config.get_boolean("self.healing.enabled")
    per_type = {
        AnomalyType.BROKER_FAILURE:
            config.get_boolean("self.healing.broker.failure.enabled"),
        AnomalyType.GOAL_VIOLATION:
            config.get_boolean("self.healing.goal.violation.enabled"),
        AnomalyType.DISK_FAILURE:
            config.get_boolean("self.healing.disk.failure.enabled"),
        AnomalyType.METRIC_ANOMALY:
            config.get_boolean("self.healing.metric.anomaly.enabled"),
        AnomalyType.TOPIC_ANOMALY:
            config.get_boolean("self.healing.topic.anomaly.enabled"),
    }
    enabled = {t: master and v for t, v in per_type.items()}
    return cls(
        self_healing_enabled=enabled,
        broker_failure_alert_threshold_ms=config.get_long(
            "broker.failure.alert.threshold.ms"),
        broker_failure_auto_fix_threshold_ms=config.get_long(
            "broker.failure.self.healing.threshold.ms"))


def _metric_anomaly_finders(config: CruiseControlConfig):
    """Metric-anomaly finder instances; the default percentile finder gets
    its two threshold keys (reference PercentileMetricAnomalyFinderConfig).
    """
    from cruise_control_tpu.common.config import resolve_class
    from cruise_control_tpu.core.anomaly import PercentileMetricAnomalyFinder
    finders = []
    for spec in config.get_list("metric.anomaly.finder.class"):
        if not spec:
            continue
        cls = resolve_class(spec)
        if issubclass(cls, PercentileMetricAnomalyFinder):
            finders.append(cls(
                upper_percentile=config.get_double(
                    "metric.anomaly.percentile.upper.threshold"),
                lower_percentile=config.get_double(
                    "metric.anomaly.percentile.lower.threshold")))
        else:
            finders.append(cls())
    return finders


def _slow_broker_config(config: CruiseControlConfig):
    from cruise_control_tpu.detector.slow_broker import SlowBrokerFinderConfig
    return SlowBrokerFinderConfig(
        min_bytes_in_rate=config.get_double(
            "slow.broker.bytes.rate.detection.threshold"),
        log_flush_time_threshold_ms=config.get_double(
            "slow.broker.log.flush.time.threshold.ms"),
        demotion_score=config.get_double("slow.broker.demotion.score"),
        removal_score=config.get_double("slow.broker.decommission.score"),
        allow_removal=config.get_boolean(
            "self.healing.slow.broker.removal.enabled"))


def _mesh_enabled_of(config) -> Optional[bool]:
    """mesh.enabled: 'auto' -> None (the facade enables the mesh only on
    non-CPU multi-device backends), 'true'/'false' -> forced."""
    raw = str(config.get("mesh.enabled") or "auto").strip().lower()
    if raw in ("auto", ""):
        return None
    if raw in ("true", "1", "yes", "on"):
        return True
    if raw in ("false", "0", "no", "off"):
        return False
    raise ValueError(f"mesh.enabled must be auto/true/false, got {raw!r}")


#: the documented base key; the free-form per-sensor keys are
#: `obs.metrics.buckets.<sensor-name-or-prefix>` = CSV of boundaries
#: in seconds
_BUCKETS_BASE = "obs.metrics.buckets"
_BUCKETS_PREFIX = _BUCKETS_BASE + "."


def _metrics_bucket_overrides(config) -> dict:
    """{sensor name/prefix: (bounds...)} from the suffixed
    obs.metrics.buckets.* keys in the raw properties (free-form keys:
    the sensor namespace is open-ended, so these are prefix-scanned
    from `originals` rather than individually defined)."""
    out = {}
    for key, raw in config.originals.items():
        if not key.startswith(_BUCKETS_PREFIX) or key == _BUCKETS_PREFIX:
            continue
        name = key[len(_BUCKETS_PREFIX):]
        try:
            bounds = tuple(sorted(float(x) for x
                                  in str(raw).split(",") if x.strip()))
        except ValueError:
            raise ValueError(
                f"{key} must be a CSV of bucket boundaries in seconds, "
                f"got {raw!r}")
        if not bounds or any(b <= 0 for b in bounds):
            raise ValueError(f"{key}: boundaries must be positive "
                             f"seconds, got {raw!r}")
        out[name] = bounds
    return out


def _slo_objectives(config) -> dict:
    """Per-class SLO objectives from the slo.<class>.* keys
    (obs/slo.ClassObjective per SchedulerClass)."""
    from cruise_control_tpu.obs.slo import (CLASS_SENSOR_SUFFIX,
                                            ClassObjective)
    return {
        klass: ClassObjective(
            latency_s=config.get_long(f"slo.{suffix}.latency.ms") / 1e3,
            queue_wait_s=config.get_long(
                f"slo.{suffix}.queue.wait.ms") / 1e3,
            error_budget=config.get_double(f"slo.{suffix}.error.budget"))
        for klass, suffix in CLASS_SENSOR_SUFFIX.items()}


def build_cruise_control(config: CruiseControlConfig, admin,
                         sampler: Optional[MetricSampler] = None,
                         solve_scheduler=None,
                         fleet_binding=None) -> CruiseControl:
    """Assemble the facade from config (reference KafkaCruiseControl
    constructor wiring :100-113).

    `solve_scheduler`/`fleet_binding` are the fleet-serving hooks
    (fleet/registry.py): a shared device-time scheduler and this
    tenant's binding (shape-bucket padding + cross-tenant fold).  Both
    default to None — the single-tenant path, byte-identical to
    pre-fleet behavior."""
    if sampler is None:
        sampler = config.get_configured_instance(
            "metric.sampler.class", MetricSampler)
    capacity_file = config.get("capacity.config.file")
    if capacity_file:
        resolver: BrokerCapacityConfigResolver = \
            BrokerCapacityConfigFileResolver(capacity_file)
    else:
        resolver = config.get_configured_instance(
            "broker.capacity.config.resolver.class",
            BrokerCapacityConfigResolver)
    sample_store = config.get_configured_instance(
        "sample.store.class", SampleStore)
    notifier = build_notifier(config)
    executor_notifier = None
    if config.get("executor.notifier.class"):
        executor_notifier = config.get_configured_instance(
            "executor.notifier.class")
    from cruise_control_tpu.common.config import resolve_class
    from cruise_control_tpu.executor.strategy import strategy_from_names
    strategy_names = [n for n in config.get_list(
        "default.replica.movement.strategies") if n]
    default_strategy = (strategy_from_names(strategy_names)
                        if strategy_names else None)
    default_goal_names, detection_goals, self_healing_goals, intra_goals = \
        _goal_lists(config)
    max_movements = config.get_long("max.num.cluster.movements")
    from cruise_control_tpu.analyzer.options_generator import (
        DefaultOptimizationOptionsGenerator, OptimizationOptionsGenerator)
    excluded_pattern = config.get(
        "topics.excluded.from.partition.movement") or ""
    gen_cls = resolve_class(config.get(
        "optimization.options.generator.class"))
    if gen_cls is DefaultOptimizationOptionsGenerator:
        options_generator = DefaultOptimizationOptionsGenerator(
            excluded_pattern)
    else:
        options_generator = config.get_configured_instance(
            "optimization.options.generator.class",
            OptimizationOptionsGenerator)
    anomaly_classes = {
        "goal.violations": resolve_class(config.get("goal.violations.class")),
        "broker.failures": resolve_class(config.get("broker.failures.class")),
        "disk.failures": resolve_class(config.get("disk.failures.class")),
        "metric.anomaly": resolve_class(config.get("metric.anomaly.class"))}
    from cruise_control_tpu.cluster.admin import (AdminTopicConfigProvider,
                                                  TopicConfigProvider)
    topic_config_provider = config.get_configured_instance(
        "topic.config.provider.class", TopicConfigProvider)
    if isinstance(topic_config_provider, AdminTopicConfigProvider):
        topic_config_provider.bind(admin)
    cpu_weights = (
        config.get_double("leader.network.inbound.weight.for.cpu.util"),
        config.get_double("leader.network.outbound.weight.for.cpu.util"),
        config.get_double("follower.network.inbound.weight.for.cpu.util"))
    return CruiseControl(
        admin, sampler,
        capacity_resolver=resolver,
        anomaly_notifier=notifier,
        executor_notifier=executor_notifier,
        constraint=build_constraint(config),
        goal_names=default_goal_names,
        detection_goal_names=detection_goals,
        self_healing_goals=self_healing_goals,
        intra_broker_goal_names=intra_goals,
        goal_violation_interval_s=_detector_interval(
            config, "goal.violation.detection.interval.ms"),
        disk_failure_interval_s=_detector_interval(
            config, "disk.failure.detection.interval.ms"),
        topic_anomaly_interval_s=_detector_interval(
            config, "topic.anomaly.detection.interval.ms"),
        metric_anomaly_interval_s=_detector_interval(
            config, "metric.anomaly.detection.interval.ms"),
        metric_anomaly_finders=_metric_anomaly_finders(config),
        slow_broker_config=_slow_broker_config(config),
        topic_min_isr_margin=config.get_int(
            "topic.replication.factor.margin"),
        topic_anomaly_finder_classes=[
            resolve_class(spec) for spec
            in config.get_list("topic.anomaly.finder.class") if spec],
        num_cached_recent_anomaly_states=config.get_int(
            "num.cached.recent.anomaly.states"),
        max_optimization_rounds=config.get_int("max.optimization.rounds"),
        balancedness_weights=(
            config.get_double("goal.balancedness.priority.weight"),
            config.get_double("goal.balancedness.strictness.weight")),
        allow_capacity_estimation=config.get_boolean(
            "allow.capacity.estimation.on.proposal"),
        allow_capacity_estimation_on_precompute=config.get_boolean(
            "allow.capacity.estimation.on.proposal.precompute"),
        options_generator=options_generator,
        exclude_recently_demoted_brokers=config.get_boolean(
            "self.healing.exclude.recently.demoted.brokers"),
        exclude_recently_removed_brokers=config.get_boolean(
            "self.healing.exclude.recently.removed.brokers"),
        detection_allow_capacity_estimation=config.get_boolean(
            "anomaly.detection.allow.capacity.estimation"),
        broker_failure_backoff_s=config.get_long(
            "broker.failure.detection.backoff.ms") / 1e3,
        broker_failure_fixable_max_count=config.get_int(
            "fixable.failed.broker.count.threshold"),
        broker_failure_fixable_max_ratio=config.get_double(
            "fixable.failed.broker.percentage.threshold"),
        failed_broker_store_path=(
            config.get("failed.brokers.zk.path") or None),
        anomaly_classes=anomaly_classes,
        topic_config_provider=topic_config_provider,
        proposal_expiration_s=config.get_long(
            "proposal.expiration.ms") / 1e3,
        proposal_precompute_interval_s=config.get_long(
            "proposal.precompute.interval.ms") / 1e3,
        warm_start_proposals=config.get_boolean(
            "proposal.warm.start.enabled"),
        solver_degradation_enabled=config.get_boolean(
            "solver.degradation.enabled"),
        solver_max_retries_per_rung=config.get_int(
            "solver.max.retries.per.rung"),
        solver_retry_backoff_base_s=config.get_long(
            "solver.retry.backoff.base.ms") / 1e3,
        solver_retry_backoff_max_s=config.get_long(
            "solver.retry.backoff.max.ms") / 1e3,
        solver_breaker_failure_threshold=config.get_int(
            "solver.circuit.breaker.failure.threshold"),
        solver_breaker_cooldown_s=config.get_long(
            "solver.circuit.breaker.cooldown.ms") / 1e3,
        solver_fusion_enabled=config.get_boolean("solver.fusion.enabled"),
        solver_host_skip_enabled=config.get_boolean(
            "solver.host.skip.enabled"),
        solver_precision=config.get("solver.precision"),
        solver_precision_balancedness_eps=config.get_double(
            "solver.precision.balancedness.eps"),
        solver_precision_min_move_overlap=config.get_double(
            "solver.precision.min.move.overlap"),
        precompute_solve_deadline_s=config.get_long(
            "proposal.precompute.solve.deadline.ms") / 1e3,
        scenario_engine_enabled=config.get_boolean(
            "scenario.engine.enabled"),
        scenario_max_batch_size=config.get_int("scenario.max.batch.size"),
        scenario_max_oom_halvings=config.get_int(
            "scenario.max.oom.halvings"),
        scenario_include_base=config.get_boolean(
            "scenario.include.base.solve"),
        portfolio_width=config.get_int("portfolio.width"),
        portfolio_seed=config.get_int("portfolio.seed"),
        portfolio_movement_cost_weight=config.get_double(
            "portfolio.movement.cost.weight"),
        portfolio_max_programs=config.get_int("portfolio.max.programs"),
        portfolio_max_eager_candidates=config.get_int(
            "portfolio.max.eager.candidates"),
        portfolio_background_enabled=config.get_boolean(
            "portfolio.background.enabled"),
        portfolio_background_interval_s=config.get_long(
            "portfolio.background.interval.ms") / 1e3,
        portfolio_background_width=config.get_int(
            "portfolio.background.width"),
        portfolio_background_generations=config.get_int(
            "portfolio.background.generations"),
        scheduler_enabled=config.get_boolean("scheduler.enabled"),
        scheduler_preemption_enabled=config.get_boolean(
            "scheduler.preemption.enabled"),
        scheduler_class_weights=[
            float(x) for x in config.get_list("scheduler.class.weights")
            if str(x).strip()],
        scheduler_class_queue_caps=[
            int(x) for x in config.get_list("scheduler.class.queue.caps")
            if str(x).strip()],
        scheduler_class_deadline_budgets_s=[
            float(x) / 1e3 for x in config.get_list(
                "scheduler.class.deadline.budget.ms") if str(x).strip()],
        mesh_enabled=_mesh_enabled_of(config),
        mesh_max_devices=(config.get_int("mesh.max.devices") or None),
        mesh_recovery_enabled=config.get_boolean("mesh.recovery.enabled"),
        mesh_watchdog_ms=float(config.get_long("mesh.watchdog.ms")),
        mesh_probe_interval_ms=float(
            config.get_long("mesh.probe.interval.ms")),
        mesh_min_devices=config.get_int("mesh.min.devices"),
        solve_scheduler=solve_scheduler,
        fleet_binding=fleet_binding,
        progcache_enabled=config.get_boolean("progcache.enabled"),
        progcache_dir=config.get("progcache.dir") or "",
        progcache_max_bytes=config.get_long("progcache.max.bytes"),
        progcache_fingerprint_override=config.get(
            "progcache.fingerprint.override") or "",
        incremental_enabled=config.get_boolean("incremental.enabled"),
        incremental_max_deltas=config.get_int("incremental.max.deltas"),
        incremental_max_dirty_ratio=config.get_double(
            "incremental.max.dirty.broker.ratio"),
        obs_tracing_enabled=config.get_boolean("obs.tracing.enabled"),
        obs_trace_log_enabled=config.get_boolean(
            "obs.trace.log.enabled"),
        obs_flight_recorder_capacity=config.get_int(
            "obs.flight.recorder.capacity"),
        obs_flight_recorder_max_pinned=config.get_int(
            "obs.flight.recorder.max.pinned"),
        obs_trace_sample_rate=config.get_double("obs.trace.sample.rate"),
        metrics_bucket_overrides=_metrics_bucket_overrides(config),
        slo_enabled=config.get_boolean("slo.enabled"),
        slo_objectives=_slo_objectives(config),
        slo_window_s=config.get_long("slo.window.ms") / 1e3,
        slo_alert_threshold=config.get_double("slo.burn.alert.threshold"),
        slo_evaluation_interval_s=config.get_long(
            "slo.evaluation.interval.ms") / 1e3,
        monitor_kwargs=dict(
            sample_store=sample_store,
            num_windows=config.get_int("num.partition.metrics.windows"),
            window_ms=config.get_long("partition.metrics.window.ms"),
            min_samples_per_window=config.get_int(
                "min.samples.per.partition.metrics.window"),
            broker_num_windows=config.get_int("num.broker.metrics.windows"),
            broker_window_ms=config.get_long("broker.metrics.window.ms"),
            broker_min_samples_per_window=config.get_int(
                "min.samples.per.broker.metrics.window"),
            sampling_interval_ms=config.get_long(
                "metric.sampling.interval.ms"),
            num_fetchers=config.get_int("num.metric.fetchers"),
            metadata_ttl_ms=config.get_long("metadata.ttl.ms"),
            max_allowed_extrapolations_per_partition=config.get_int(
                "max.allowed.extrapolations.per.partition"),
            max_allowed_extrapolations_per_broker=config.get_int(
                "max.allowed.extrapolations.per.broker"),
            allow_cpu_capacity_estimation=config.get_boolean(
                "sampling.allow.cpu.capacity.estimation"),
            state_update_interval_ms=config.get_long(
                "monitor.state.update.interval.ms"),
            completeness_cache_size=config.get_int(
                "partition.metric.sample.aggregator.completeness.cache.size"
            ),
            broker_completeness_cache_size=config.get_int(
                "broker.metric.sample.aggregator.completeness.cache.size"),
            min_valid_partition_ratio=config.get_double(
                "min.valid.partition.ratio"),
            partition_assignor=config.get_configured_instance(
                "metric.sampler.partition.assignor.class"),
            use_linear_regression_model=config.get_boolean(
                "use.linear.regression.model"),
            linear_regression_kwargs=dict(
                cpu_util_bucket_size_pct=config.get_int(
                    "linear.regression.model.cpu.util.bucket.size"),
                min_num_cpu_util_buckets=config.get_int(
                    "linear.regression.model.min.num.cpu.util.buckets"),
                required_samples_per_bucket=config.get_int(
                    "linear.regression.model.required.samples.per.bucket")),
            cpu_util_weights=cpu_weights),
        executor_journal_dir=(config.get("executor.journal.dir") or None),
        executor_recovery_mode=config.get("executor.recovery.mode"),
        executor_journal_segment_max_bytes=config.get_long(
            "executor.journal.segment.max.bytes"),
        executor_kwargs=dict(
            max_consecutive_poll_failures=config.get_int(
                "executor.max.consecutive.poll.failures"),
            concurrent_inter_broker_moves_per_broker=config.get_int(
                "num.concurrent.partition.movements.per.broker"),
            concurrent_intra_broker_moves_per_broker=config.get_int(
                "num.concurrent.intra.broker.partition.movements"),
            concurrent_leader_movements=config.get_int(
                "num.concurrent.leader.movements"),
            progress_check_interval_s=config.get_long(
                "execution.progress.check.interval.ms") / 1e3,
            max_task_lifetime_s=config.get_long(
                "max.execution.task.lifetime.ms") / 1e3,
            task_alerting_threshold_s=config.get_long(
                "task.execution.alerting.threshold.ms") / 1e3,
            leader_movement_timeout_s=config.get_long(
                "leader.movement.timeout.ms") / 1e3,
            inter_rate_alert_threshold_mb_s=config.get_double(
                "inter.broker.replica.movement.rate.alerting.threshold"),
            intra_rate_alert_threshold_mb_s=config.get_double(
                "intra.broker.replica.movement.rate.alerting.threshold"),
            logdir_response_timeout_s=config.get_long(
                "logdir.response.timeout.ms") / 1e3,
            removal_history_retention_s=config.get_long(
                "removal.history.retention.time.ms") / 1e3,
            demotion_history_retention_s=config.get_long(
                "demotion.history.retention.time.ms") / 1e3,
            max_cluster_movements=(max_movements
                                   if max_movements > 0 else None),
            default_strategy=default_strategy,
            replication_throttle_bytes_per_s=(
                config.get_long("default.replication.throttle")
                if config.get_long("default.replication.throttle") > 0
                else None)))


def _demo_admin(num_brokers: int = 6, num_partitions: int = 24):
    """(admin, sampler) for an in-process simulated cluster — the
    --demo-cluster path and the `"demo": true` fleet-config clusters."""
    import time as _t
    from cruise_control_tpu.cluster.simulated import SimulatedCluster
    from cruise_control_tpu.cluster.types import TopicPartition
    from cruise_control_tpu.monitor.sampling.sampler import (
        SimulatedClusterSampler)
    admin = SimulatedCluster(time_fn=_t.time)
    for b in range(num_brokers):
        admin.add_broker(b, rack=f"rack{b % 3}")
    # sizes well inside StaticCapacityResolver's default DISK capacity
    admin.create_topic(
        "demo", [[b % num_brokers, (b + 1) % num_brokers]
                 for b in range(num_partitions)],
        size_bytes=1e4)
    for p in range(num_partitions):
        admin.set_partition_load(TopicPartition("demo", p),
                                 leader_cpu=1.0, nw_in=50.0,
                                 nw_out=100.0)
    return admin, SimulatedClusterSampler(admin)


def build_fleet(config: CruiseControlConfig, fleet_config_path: str):
    """FleetRegistry from a --fleet-config JSON file: K tenants, each a
    full facade over its own admin client and config OVERLAY of the base
    properties, all sharing one device-time scheduler, one bucket index
    and one cross-tenant router (docs/FLEET.md).

    File format::

        {"clusters": [
            {"id": "alpha", "demo": true,
             "brokers": 6, "partitions": 24,
             "overrides": {"cpu.balance.threshold": "1.3"}},
            {"id": "beta",
             "overrides": {"cluster.admin.class": "my.mod.AdminImpl"}}
         ],
         "default": "alpha"}

    Non-demo clusters take their ClusterAdminClient from
    `cluster.admin.class` in the overlay (or the base properties).
    """
    import json as _json
    from cruise_control_tpu.common.config import resolve_class
    from cruise_control_tpu.fleet import FleetRegistry
    from cruise_control_tpu.sched.policy import SchedulerPolicy
    from cruise_control_tpu.sched.scheduler import DeviceTimeScheduler

    with open(fleet_config_path) as fh:
        spec = _json.load(fh)
    clusters = spec.get("clusters") or []
    if not clusters:
        raise ConfigException(
            f"{fleet_config_path}: fleet config needs a non-empty "
            f"'clusters' list")
    ids = [c.get("id") for c in clusters]
    if len(set(ids)) != len(ids) or not all(ids):
        raise ConfigException(
            f"{fleet_config_path}: cluster ids must be unique and "
            f"non-empty, got {ids}")

    # ONE scheduler for the whole fleet (the PR-4 gateway), policy from
    # the BASE config — per-tenant scheduler.* overrides are ignored by
    # design: admission/priority over the one device is fleet policy.
    # The shared scheduler also owns the ONE fleet-wide mesh token
    # (mesh.* from the base config): every tenant's solves run over the
    # same device mesh.
    from cruise_control_tpu.parallel.health import MeshSupervisor
    from cruise_control_tpu.parallel.mesh import runtime_mesh
    fleet_mesh_token = runtime_mesh(
        enabled=_mesh_enabled_of(config),
        max_devices=(config.get_int("mesh.max.devices") or None))
    # ONE mesh supervisor for the whole fleet, like the token it wraps:
    # a chip condemned under any tenant's solve shrinks the span every
    # tenant dispatches over (there is only one set of chips to lose)
    fleet_mesh_supervisor = (MeshSupervisor(
        fleet_mesh_token,
        enabled=config.get_boolean("mesh.recovery.enabled"),
        watchdog_ms=float(config.get_long("mesh.watchdog.ms")),
        probe_interval_ms=float(config.get_long("mesh.probe.interval.ms")),
        min_devices=config.get_int("mesh.min.devices"))
        if fleet_mesh_token.is_multichip else None)
    scheduler = DeviceTimeScheduler(
        SchedulerPolicy.from_lists(
            weights=[float(x) for x in config.get_list(
                "scheduler.class.weights") if str(x).strip()],
            queue_caps=[int(x) for x in config.get_list(
                "scheduler.class.queue.caps") if str(x).strip()],
            deadline_budgets_s=[float(x) / 1e3 for x in config.get_list(
                "scheduler.class.deadline.budget.ms") if str(x).strip()],
            preemption_enabled=config.get_boolean(
                "scheduler.preemption.enabled")),
        enabled=config.get_boolean("scheduler.enabled"),
        mesh_token=fleet_mesh_token,
        mesh_supervisor=fleet_mesh_supervisor)
    registry = FleetRegistry(
        scheduler,
        bucket_floor=config.get_int("fleet.bucket.floor"),
        bucket_max_tracked=config.get_int("fleet.bucket.max.tracked"),
        fold_enabled=config.get_boolean("fleet.fold.enabled"),
        max_tenants=config.get_int("fleet.max.tenants"))
    # the shared scheduler's sched-* sensors export through the fleet
    # registry (per-tenant registries must not fight over them)
    scheduler.attach_metrics(registry.metrics)

    default_id = (spec.get("default")
                  or config.get("fleet.default.cluster.id") or ids[0])
    if default_id not in ids:
        raise ConfigException(
            f"fleet default cluster {default_id!r} is not in {ids}")
    base_journal_dir = config.get("executor.journal.dir") or ""
    for entry in clusters:
        cid = entry["id"]
        merged = dict(config.originals)
        merged.update({k: str(v)
                       for k, v in (entry.get("overrides") or {}).items()})
        # per-tenant executor journal isolation: each cluster's WAL +
        # removal/demotion history lives in its own subdirectory of the
        # base executor.journal.dir (two tenants sharing one journal
        # would replay each other's executions); an explicit per-tenant
        # override wins
        if base_journal_dir and "executor.journal.dir" not in (
                entry.get("overrides") or {}):
            merged["executor.journal.dir"] = os.path.join(
                base_journal_dir, cid)
        tenant_config = CruiseControlConfig(merged)
        sampler = None
        if entry.get("demo"):
            admin, sampler = _demo_admin(
                num_brokers=int(entry.get("brokers", 6)),
                num_partitions=int(entry.get("partitions", 24)))
        else:
            admin_cls = (tenant_config.get("cluster.admin.class")
                         or tenant_config.get(
                             "network.client.provider.class"))
            if not admin_cls:
                raise ConfigException(
                    f"fleet cluster {cid!r}: set \"demo\": true or a "
                    f"cluster.admin.class override")
            admin = resolve_class(admin_cls)()
        cc = build_cruise_control(
            tenant_config, admin, sampler=sampler,
            solve_scheduler=scheduler,
            fleet_binding=registry.binding_for(cid))
        registry.register(cid, cc, default=(cid == default_id))
    return registry


def build_security(config: CruiseControlConfig):
    """SecurityProvider from config.

    `webserver.security.provider` names the provider class (the reference
    SPI); the two built-ins with constructor state get their wiring from
    their dedicated keys (Basic: credentials file; JWT: secret / public
    key / iss / aud).  Any other class is instantiated via the standard
    configured-instance hook (no-arg constructor + optional
    `configure(props)`)."""
    from cruise_control_tpu.api.security import (JwtSecurityProvider,
                                                 SecurityProvider,
                                                 TrustedProxySecurityProvider)
    from cruise_control_tpu.common.config import resolve_class

    if (config.get("spnego.keytab.file")
            or config.get("spnego.principal")
            or "spnego" in (config.get("webserver.security.provider")
                            or "").lower()):
        # SPNEGO/Kerberos termination is a documented non-goal: terminate
        # Kerberos at a fronting proxy and use the TrustedProxy provider
        # (docs/DECISIONS.md §SPNEGO)
        raise ConfigException(
            "SPNEGO is not terminated in-process: terminate Kerberos at a "
            "proxy and configure TrustedProxySecurityProvider with "
            "trusted.proxy.services / trusted.proxy.services.ip.regex "
            "(decision record: docs/DECISIONS.md)")
    if not config.get_boolean("webserver.security.enable"):
        return NoSecurityProvider()
    cls = resolve_class(config.get("webserver.security.provider"))
    if cls is TrustedProxySecurityProvider:
        creds = config.get("webserver.auth.credentials.file")
        inner = (BasicSecurityProvider.from_credentials_file(creds)
                 if creds else NoSecurityProvider())
        return TrustedProxySecurityProvider(
            inner,
            trusted_proxies=[s for s in config.get_list(
                "trusted.proxy.services") if s],
            ip_regex=config.get("trusted.proxy.services.ip.regex") or None)
    # convenience: JWT keys present with the provider key left at its
    # default select the JWT provider (an EXPLICIT provider choice wins)
    explicit = "webserver.security.provider" in config.originals
    jwt_configured = (
        getattr(config.get("webserver.security.jwt.secret"), "value",
                config.get("webserver.security.jwt.secret"))
        or config.get("webserver.security.jwt.public.key.location"))
    if not explicit and jwt_configured:
        cls = JwtSecurityProvider
    if cls is JwtSecurityProvider:
        jwt_secret = config.get("webserver.security.jwt.secret")
        jwt_secret = getattr(jwt_secret, "value", jwt_secret) or ""
        # jwt.auth.certificate.location is the reference-compat alias of
        # the public-key location
        jwt_pub = (config.get("webserver.security.jwt.public.key.location")
                   or config.get("jwt.auth.certificate.location"))
        pem = None
        if jwt_pub:
            with open(jwt_pub, "rb") as f:
                pem = f.read()
        return JwtSecurityProvider(
            hs256_secret=jwt_secret.encode() if jwt_secret else None,
            rs256_public_key_pem=pem,
            issuer=config.get("webserver.security.jwt.issuer") or None,
            audience=config.get("webserver.security.jwt.audience") or None,
            audiences=[a for a in config.get_list("jwt.expected.audiences")
                       if a],
            cookie_name=config.get("jwt.cookie.name") or None,
            login_url=config.get("jwt.authentication.provider.url") or None)
    if cls is BasicSecurityProvider:
        creds = config.get("webserver.auth.credentials.file")
        return (BasicSecurityProvider.from_credentials_file(creds)
                if creds else NoSecurityProvider())
    return config.get_configured_instance("webserver.security.provider",
                                          SecurityProvider)


def build_ssl_context(config: CruiseControlConfig):
    """ssl.SSLContext from the webserver.ssl.* keys, or None when TLS is
    disabled (reference KafkaCruiseControlApp.java:100-173)."""
    if not config.get_boolean("webserver.ssl.enable"):
        return None
    from cruise_control_tpu.api.server import make_server_ssl_context
    cert = config.get("webserver.ssl.keystore.location")
    if not cert:
        raise ValueError("webserver.ssl.enable requires "
                         "webserver.ssl.keystore.location")
    ks_type = (config.get("webserver.ssl.keystore.type") or "PEM").upper()
    if ks_type not in ("PEM", ""):
        raise ValueError(
            f"webserver.ssl.keystore.type={ks_type!r}: only PEM keystores "
            f"are supported (convert JKS/PKCS12 with `openssl pkcs12`)")
    password = config.get("webserver.ssl.key.password")
    password = getattr(password, "value", password) or None
    if not password:
        ks_password = config.get("webserver.ssl.keystore.password")
        password = getattr(ks_password, "value", ks_password) or None
    return make_server_ssl_context(
        cert, keyfile=config.get("webserver.ssl.keyfile.location") or None,
        key_password=password,
        protocol=config.get("webserver.ssl.protocol") or "TLS")


def build_app(config: CruiseControlConfig,
              cruise_control: CruiseControl,
              fleet=None) -> CruiseControlApp:
    from cruise_control_tpu.api.request_registry import (
        resolve_endpoint_classes)
    security = build_security(config)

    retention_keys = {
        "kafka.admin": "completed.kafka.admin.user.task.retention.time.ms",
        "kafka.monitor":
            "completed.kafka.monitor.user.task.retention.time.ms",
        "cruise.control.admin":
            "completed.cruise.control.admin.user.task.retention.time.ms",
        "cruise.control.monitor":
            "completed.cruise.control.monitor.user.task.retention.time.ms"}
    cached_keys = {
        "kafka.admin": "max.cached.completed.kafka.admin.user.tasks",
        "kafka.monitor": "max.cached.completed.kafka.monitor.user.tasks",
        "cruise.control.admin":
            "max.cached.completed.cruise.control.admin.user.tasks",
        "cruise.control.monitor":
            "max.cached.completed.cruise.control.monitor.user.tasks"}

    def _cat_map(keys: dict, getter, scale: float = 1.0) -> dict:
        out = {}
        for cat, key in keys.items():
            v = getter(key)
            if v is not None and v >= 0:
                out[cat] = v * scale
        return out

    return CruiseControlApp(
        cruise_control, security=security,
        two_step_verification=config.get_boolean(
            "two.step.verification.enabled"),
        async_response_timeout_s=config.get_long(
            "webserver.request.maxBlockTimeMs") / 1e3,
        access_log=config.get_boolean("webserver.accesslog.enabled"),
        purgatory_kwargs=dict(
            retention_s=config.get_long(
                "two.step.purgatory.retention.time.ms") / 1e3,
            max_requests=config.get_int("two.step.purgatory.max.requests")),
        user_task_kwargs=dict(
            max_active_tasks=config.get_int("max.active.user.tasks"),
            completed_retention_s=config.get_long(
                "completed.user.task.retention.time.ms") / 1e3,
            max_cached_completed_tasks=config.get_int(
                "max.cached.completed.user.tasks"),
            attach_max_age_s=config.get_long(
                "webserver.session.maxExpiryTimeMs") / 1e3,
            category_retention_s=_cat_map(retention_keys,
                                          config.get_long, 1e-3),
            category_max_cached=_cat_map(cached_keys, config.get_int)),
        cors_enabled=config.get_boolean("webserver.http.cors.enabled"),
        cors_origin=config.get("webserver.http.cors.origin") or "*",
        cors_allow_methods=config.get(
            "webserver.http.cors.allowmethods") or "OPTIONS, GET, POST",
        cors_expose_headers=config.get(
            "webserver.http.cors.exposeheaders") or "User-Task-ID",
        url_prefix=config.get("webserver.api.urlprefix") or None,
        endpoint_classes=resolve_endpoint_classes(config),
        request_reason_required=config.get_boolean(
            "request.reason.required"),
        session_path=config.get("webserver.session.path") or "/",
        ui_diskpath=config.get("webserver.ui.diskpath") or "",
        ui_urlprefix=config.get("webserver.ui.urlprefix") or "/ui",
        fleet=fleet,
        metrics_endpoint_enabled=config.get_boolean(
            "obs.metrics.endpoint.enabled"))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="cruise-control-tpu",
        description="TPU-native cluster-rebalancing service")
    parser.add_argument("config", help="properties file")
    parser.add_argument("port", nargs="?", type=int,
                        help="REST port override")
    parser.add_argument("host", nargs="?", help="REST host override")
    parser.add_argument("--demo-cluster", action="store_true",
                        help="run against an in-process simulated cluster "
                             "(no external infrastructure)")
    parser.add_argument("--fleet-config",
                        help="JSON file describing a multi-cluster fleet "
                             "(one tenant per cluster sharing this "
                             "process's device; see docs/FLEET.md)")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    config = CruiseControlConfig(read_properties(args.config))

    # route the NCSA access log to its own rotated file
    # (reference webserver.accesslog.{path,retention.days})
    accesslog_path = config.get("webserver.accesslog.path")
    if accesslog_path and config.get_boolean("webserver.accesslog.enabled"):
        handler = logging.handlers.TimedRotatingFileHandler(
            accesslog_path, when="D",
            backupCount=config.get_int("webserver.accesslog.retention.days"))
        handler.setFormatter(logging.Formatter("%(message)s"))
        access = logging.getLogger("accessLogger")
        access.addHandler(handler)
        access.propagate = False
    if config.get_boolean("zookeeper.security.enabled"):
        LOG.info("zookeeper.security.enabled is a reference-compat flag: "
                 "this framework has no ZooKeeper; cluster authentication "
                 "is the ClusterAdminClient implementation's "
                 "responsibility (docs/DECISIONS.md)")

    fleet = None
    if args.fleet_config:
        fleet = build_fleet(config, args.fleet_config)
        cc = fleet.facade_for()
        LOG.info("fleet: %d tenants (%s), default %r",
                 len(fleet.tenants()),
                 ", ".join(t.cluster_id for t in fleet.tenants()),
                 fleet.default_id)
    elif args.demo_cluster:
        admin, sampler = _demo_admin()
        cc = build_cruise_control(config, admin, sampler=sampler)
    else:
        # declared with default "" since ISSUE-15 (D301): a plain get
        # works whether or not the overlay names it
        admin_cls = config.get("cluster.admin.class") or None
        if not admin_cls:
            # reference-compat alias (network.client.provider.class)
            admin_cls = config.get("network.client.provider.class") or None
        if not admin_cls:
            print("error: provide --demo-cluster or set "
                  "cluster.admin.class (or its reference-compat alias "
                  "network.client.provider.class) to a ClusterAdminClient "
                  "implementation for your infrastructure",
                  file=sys.stderr)
            return 2
        from cruise_control_tpu.common.config import resolve_class
        admin = resolve_class(admin_cls)()
        cc = build_cruise_control(config, admin)

    if fleet is None:
        # warm from the persistent program cache BEFORE serving: a
        # process bounce re-enters FUSED/MESH with zero source-program
        # compiles when the cache holds this stack's programs (fleet
        # tenants warmed inside register()).  No-op when progcache.dir
        # is unset or the cache is empty.
        cc.warm_programs_from_cache()

    app = build_app(config, cc, fleet=fleet)
    startup_kwargs = dict(
        skip_loading_samples=config.get_boolean("skip.loading.samples"),
        start_proposal_precompute=config.get_int(
            "num.proposal.precompute.threads") > 0)
    if fleet is not None:
        for tenant in fleet.tenants():
            tenant.facade.start_up(**startup_kwargs)
    else:
        cc.start_up(**startup_kwargs)
    host = args.host or config.get("webserver.http.address")
    port = args.port if args.port is not None \
        else config.get_int("webserver.http.port")
    ssl_ctx = build_ssl_context(config)
    bound = app.start(host=host, port=port, ssl_context=ssl_ctx)
    LOG.info("REST API listening on %s://%s:%d%s",
             "https" if ssl_ctx else "http", host, bound, app.base_path)

    stop = threading.Event()

    def on_signal(signum, frame):  # noqa: ARG001
        stop.set()
    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    try:
        while not stop.wait(1.0):
            pass
    finally:
        # graceful drain (SIGTERM/SIGINT): stop admitting writes (503 +
        # Retry-After — clients back off like on a 429 and resubmit to
        # the replacement process), give the in-flight solve a bounded
        # window to finish, then settle the persistent program cache
        # and dump the flight recorder so the incident evidence and the
        # compiled programs survive the restart.  A wedged solve never
        # holds the process past the budget — the precompute-watchdog
        # rule applied to shutdown itself.
        drain_s = config.get_long("shutdown.drain.timeout.ms") / 1e3
        LOG.info("draining: writes now answer 503 + Retry-After "
                 "(budget %.0fs)", drain_s)
        app.drain(retry_after_s=drain_s)
        if not cc.solve_scheduler.quiesce(drain_s):
            LOG.warning("drain budget elapsed with a solve still in "
                        "flight; shutting down around it")
        from cruise_control_tpu.parallel import progcache as _progcache
        swept = _progcache.get_cache().flush()
        if swept:
            LOG.info("program cache: swept %d orphaned temp files",
                     swept)
        from cruise_control_tpu.obs import recorder as _recorder
        _recorder.get_recorder().dump(reason="shutdown drain")
        LOG.info("shutting down")
        app.stop()
        if fleet is not None:
            fleet.shutdown()
        else:
            cc.shutdown()
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
