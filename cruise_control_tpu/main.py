"""Application entry point.

Reference CC/KafkaCruiseControlMain.java:23-53 + KafkaCruiseControlApp.java:
read a properties file, build the service stack from config, start the REST
server, block until interrupted.  Pluggable classes (sampler, sample store,
capacity resolver, notifiers, security provider) are instantiated from
config exactly like the reference's getConfiguredInstance wiring.
"""
from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading
from typing import Mapping, Optional

from cruise_control_tpu.api.security import (BasicSecurityProvider,
                                             NoSecurityProvider)
from cruise_control_tpu.api.server import CruiseControlApp
from cruise_control_tpu.config.capacity import (
    BrokerCapacityConfigFileResolver, BrokerCapacityConfigResolver)
from cruise_control_tpu.config.main_config import CruiseControlConfig
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.monitor.sampling.sample_store import SampleStore
from cruise_control_tpu.monitor.sampling.sampler import MetricSampler

LOG = logging.getLogger(__name__)


#: Java-style `key=value` properties file with ${env:NAME} secret
#: resolution (reference readConfig + EnvConfigProvider)
from cruise_control_tpu.common.config import \
    load_properties as read_properties  # noqa: E402


def build_cruise_control(config: CruiseControlConfig, admin,
                         sampler: Optional[MetricSampler] = None
                         ) -> CruiseControl:
    """Assemble the facade from config (reference KafkaCruiseControl
    constructor wiring :100-113)."""
    if sampler is None:
        sampler = config.get_configured_instance(
            "metric.sampler.class", MetricSampler)
    capacity_file = config.get("capacity.config.file")
    if capacity_file:
        resolver: BrokerCapacityConfigResolver = \
            BrokerCapacityConfigFileResolver(capacity_file)
    else:
        resolver = config.get_configured_instance(
            "broker.capacity.config.resolver.class",
            BrokerCapacityConfigResolver)
    sample_store = config.get_configured_instance(
        "sample.store.class", SampleStore)
    notifier = config.get_configured_instance("anomaly.notifier.class")
    return CruiseControl(
        admin, sampler,
        capacity_resolver=resolver,
        anomaly_notifier=notifier,
        goal_names=[g for g in config.get_list("goals") if g],
        goal_violation_interval_s=config.get_long(
            "anomaly.detection.interval.ms") / 1e3,
        proposal_expiration_s=config.get_long(
            "proposal.expiration.ms") / 1e3,
        proposal_precompute_interval_s=config.get_long(
            "proposal.precompute.interval.ms") / 1e3,
        monitor_kwargs=dict(
            sample_store=sample_store,
            num_windows=config.get_int("num.partition.metrics.windows"),
            window_ms=config.get_long("partition.metrics.window.ms"),
            min_samples_per_window=config.get_int(
                "min.samples.per.partition.metrics.window"),
            broker_num_windows=config.get_int("num.broker.metrics.windows"),
            sampling_interval_ms=config.get_long(
                "metric.sampling.interval.ms"),
            num_fetchers=config.get_int("num.metric.fetchers"),
            metadata_ttl_ms=config.get_long("metadata.ttl.ms")),
        executor_kwargs=dict(
            concurrent_inter_broker_moves_per_broker=config.get_int(
                "num.concurrent.partition.movements.per.broker"),
            concurrent_intra_broker_moves_per_broker=config.get_int(
                "num.concurrent.intra.broker.partition.movements"),
            concurrent_leader_movements=config.get_int(
                "num.concurrent.leader.movements"),
            progress_check_interval_s=config.get_long(
                "execution.progress.check.interval.ms") / 1e3))


def build_app(config: CruiseControlConfig,
              cruise_control: CruiseControl) -> CruiseControlApp:
    if config.get_boolean("webserver.security.enable"):
        creds = config.get("webserver.auth.credentials.file")
        security = (BasicSecurityProvider.from_credentials_file(creds)
                    if creds else NoSecurityProvider())
    else:
        security = NoSecurityProvider()
    return CruiseControlApp(
        cruise_control, security=security,
        two_step_verification=config.get_boolean(
            "two.step.verification.enabled"),
        async_response_timeout_s=config.get_long(
            "webserver.request.maxBlockTimeMs") / 1e3,
        access_log=config.get_boolean("webserver.accesslog.enabled"))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="cruise-control-tpu",
        description="TPU-native cluster-rebalancing service")
    parser.add_argument("config", help="properties file")
    parser.add_argument("port", nargs="?", type=int,
                        help="REST port override")
    parser.add_argument("host", nargs="?", help="REST host override")
    parser.add_argument("--demo-cluster", action="store_true",
                        help="run against an in-process simulated cluster "
                             "(no external infrastructure)")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    config = CruiseControlConfig(read_properties(args.config))

    if args.demo_cluster:
        from cruise_control_tpu.cluster.simulated import SimulatedCluster
        from cruise_control_tpu.monitor.sampling.sampler import (
            SimulatedClusterSampler)
        import time as _t
        admin = SimulatedCluster(time_fn=_t.time)
        for b in range(6):
            admin.add_broker(b, rack=f"rack{b % 3}")
        from cruise_control_tpu.cluster.types import TopicPartition
        # sizes well inside StaticCapacityResolver's default DISK capacity
        admin.create_topic(
            "demo", [[b % 6, (b + 1) % 6] for b in range(24)],
            size_bytes=1e4)
        for p in range(24):
            admin.set_partition_load(TopicPartition("demo", p),
                                     leader_cpu=1.0, nw_in=50.0,
                                     nw_out=100.0)
        sampler = SimulatedClusterSampler(admin)
        cc = build_cruise_control(config, admin, sampler=sampler)
    else:
        admin_cls = config.get("cluster.admin.class") \
            if "cluster.admin.class" in config.originals else None
        if not admin_cls:
            print("error: provide --demo-cluster or set "
                  "cluster.admin.class to a ClusterAdminClient "
                  "implementation for your infrastructure",
                  file=sys.stderr)
            return 2
        from cruise_control_tpu.common.config import resolve_class
        admin = resolve_class(admin_cls)()
        cc = build_cruise_control(config, admin)

    app = build_app(config, cc)
    cc.start_up(start_proposal_precompute=config.get_int(
        "num.proposal.precompute.threads") > 0)
    host = args.host or config.get("webserver.http.address")
    port = args.port if args.port is not None \
        else config.get_int("webserver.http.port")
    bound = app.start(host=host, port=port)
    LOG.info("REST API listening on http://%s:%d%s", host, bound,
             "/kafkacruisecontrol")

    stop = threading.Event()

    def on_signal(signum, frame):  # noqa: ARG001
        stop.set()
    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    try:
        while not stop.wait(1.0):
            pass
    finally:
        LOG.info("shutting down")
        app.stop()
        cc.shutdown()
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
