"""The framework's main typed configuration.

Reference: CC/config/KafkaCruiseControlConfig.java:1-393 plus the eight
constant groups under CC/config/constants/ (MonitorConfig, AnalyzerConfig,
ExecutorConfig, AnomalyDetectorConfig, WebServerConfig,
CruiseControlRequestConfig, CruiseControlParametersConfig,
UserTaskManagerConfig) — ~200 typed keys with defaults, validators and
cross-field sanity checks.  The same grouping is kept here; endpoint→class
wiring (request/parameters groups) lives with the API layer and merges in
via `api.request_config_def()` when the webserver starts.
"""
from __future__ import annotations

from typing import Any, Mapping

from cruise_control_tpu.common.config import (AbstractConfig, ConfigDef,
                                              ConfigException, Importance,
                                              Type, in_range, in_values)

_H = Importance.HIGH
_M = Importance.MEDIUM
_L = Importance.LOW


def monitor_config_def(d: ConfigDef) -> ConfigDef:
    """reference config/constants/MonitorConfig.java (40 keys)"""
    d.define("partition.metrics.window.ms", Type.LONG, 3_600_000,
             in_range(min_value=1), _H,
             "Span of one partition-metric aggregation window.")
    d.define("num.partition.metrics.windows", Type.INT, 5,
             in_range(min_value=1), _H,
             "Number of stable partition windows kept.")
    d.define("min.samples.per.partition.metrics.window", Type.INT, 3,
             in_range(min_value=1), _M,
             "Samples required for a partition window to be valid.")
    d.define("broker.metrics.window.ms", Type.LONG, 3_600_000,
             in_range(min_value=1), _H,
             "Span of one broker-metric aggregation window.")
    d.define("num.broker.metrics.windows", Type.INT, 20,
             in_range(min_value=1), _H,
             "Number of stable broker windows kept.")
    d.define("min.samples.per.broker.metrics.window", Type.INT, 1,
             in_range(min_value=1), _M,
             "Samples required for a broker window to be valid.")
    d.define("metric.sampling.interval.ms", Type.LONG, 120_000,
             in_range(min_value=10), _H, "Interval between sampling runs.")
    d.define("num.metric.fetchers", Type.INT, 1, in_range(min_value=1), _M,
             "Parallel metric-fetcher workers.")
    d.define("metric.sampler.class", Type.CLASS,
             "cruise_control_tpu.monitor.sampling.sampler.NoopSampler",
             None, _H, "MetricSampler implementation.")
    d.define("sample.store.class", Type.CLASS,
             "cruise_control_tpu.monitor.sampling.sample_store.NoopSampleStore",
             None, _M, "SampleStore implementation for durable samples.")
    d.define("sample.store.directory", Type.STRING, "/tmp/cc-samples", None,
             _L, "Directory for the file sample store.")
    d.define("skip.loading.samples", Type.BOOLEAN, False, None, _L,
             "Skip reloading stored samples at startup.")
    d.define("broker.capacity.config.resolver.class", Type.CLASS,
             "cruise_control_tpu.config.capacity.StaticCapacityResolver",
             None, _H, "BrokerCapacityConfigResolver implementation.")
    d.define("capacity.config.file", Type.STRING, "", None, _M,
             "JSON capacity file for the file resolver.")
    d.define("metadata.ttl.ms", Type.LONG, 5_000, in_range(min_value=1), _L,
             "Cluster metadata cache TTL.")
    d.define("monitor.state.update.interval.ms", Type.LONG, 30_000,
             in_range(min_value=1), _L, "Sensor/state refresh interval.")
    d.define("broker.sample.retention.ms", Type.LONG, 86_400_000 * 7,
             in_range(min_value=1), _L, "Broker-sample retention for stores.")
    d.define("partition.sample.retention.ms", Type.LONG, 86_400_000 * 7,
             in_range(min_value=1), _L,
             "Partition-sample retention for stores.")
    d.define("sample.store.fsync", Type.BOOLEAN, False, None, _L,
             "fsync the sample-store files on every store call "
             "(journal-grade deployments): stored samples survive a "
             "host crash at the cost of one fsync per sampling "
             "interval.")
    d.define("sample.store.compaction.interval.ms", Type.LONG, -1, None,
             _L,
             "How often the file sample store applies retention ON "
             "DISK (rewrite-temp-then-rename compaction; without it "
             "the sample files grow unbounded).  -1 = a quarter of the "
             "shortest configured retention.")
    d.define("sampling.allow.cpu.capacity.estimation", Type.BOOLEAN, True,
             None, _L, "Allow estimated capacities during sampling.")
    d.define("max.allowed.extrapolations.per.partition", Type.INT, 5,
             in_range(min_value=0), _L,
             "Extrapolated windows tolerated per partition entity.")
    d.define("max.allowed.extrapolations.per.broker", Type.INT, 5,
             in_range(min_value=0), _L,
             "Extrapolated windows tolerated per broker entity.")
    d.define("partition.metric.sample.aggregator.completeness.cache.size",
             Type.INT, 5, in_range(min_value=0), _L,
             "Cached completeness evaluations (partition aggregator).")
    d.define("broker.metric.sample.aggregator.completeness.cache.size",
             Type.INT, 5, in_range(min_value=0), _L,
             "Cached completeness evaluations (broker aggregator).")
    d.define("min.valid.partition.ratio", Type.DOUBLE, 0.995,
             in_range(min_value=0.0, max_value=1.0), _M,
             "Default monitored-partition completeness required for model "
             "generation when a request names none.")
    d.define("metric.sampler.partition.assignor.class", Type.CLASS,
             "cruise_control_tpu.monitor.sampling.fetcher"
             ".DefaultPartitionAssignor",
             None, _L, "Partition-to-fetcher assignment strategy.")
    d.define("use.linear.regression.model", Type.BOOLEAN, False, None, _L,
             "Estimate CPU from the trained linear regression model "
             "instead of static coefficients.")
    d.define("linear.regression.model.cpu.util.bucket.size", Type.INT, 5,
             in_range(min_value=1, max_value=100), _L,
             "CPU-utilization bucket width (percent) for regression "
             "training.")
    d.define("linear.regression.model.min.num.cpu.util.buckets", Type.INT,
             5, in_range(min_value=1), _L,
             "Distinct CPU buckets required before the regression trains.")
    d.define("linear.regression.model.required.samples.per.bucket",
             Type.INT, 10, in_range(min_value=1), _L,
             "Samples required per CPU bucket before the regression "
             "trains.")
    d.define("leader.network.inbound.weight.for.cpu.util", Type.DOUBLE,
             0.6, in_range(min_value=0.0), _L,
             "Static CPU attribution weight of leader NW_IN.")
    d.define("leader.network.outbound.weight.for.cpu.util", Type.DOUBLE,
             0.1, in_range(min_value=0.0), _L,
             "Static CPU attribution weight of leader NW_OUT.")
    d.define("follower.network.inbound.weight.for.cpu.util", Type.DOUBLE,
             0.3, in_range(min_value=0.0), _L,
             "Static CPU attribution weight of follower NW_IN.")
    d.define("topic.config.provider.class", Type.CLASS,
             "cruise_control_tpu.cluster.admin.AdminTopicConfigProvider",
             None, _L, "TopicConfigProvider implementation.")
    d.define("num.cached.recent.anomaly.states", Type.INT, 10,
             in_range(min_value=1, max_value=100), _L,
             "Recent anomalies kept per type for the state endpoint.")
    return d


def analyzer_config_def(d: ConfigDef) -> ConfigDef:
    """reference config/constants/AnalyzerConfig.java (28 keys)"""
    d.define("cpu.balance.threshold", Type.DOUBLE, 1.1,
             in_range(min_value=1.0), _H,
             "Allowed CPU utilization ratio above/below cluster average.")
    d.define("network.inbound.balance.threshold", Type.DOUBLE, 1.1,
             in_range(min_value=1.0), _H, "NW_IN balance ratio.")
    d.define("network.outbound.balance.threshold", Type.DOUBLE, 1.1,
             in_range(min_value=1.0), _H, "NW_OUT balance ratio.")
    d.define("disk.balance.threshold", Type.DOUBLE, 1.1,
             in_range(min_value=1.0), _H, "DISK balance ratio.")
    d.define("cpu.capacity.threshold", Type.DOUBLE, 0.7,
             in_range(min_value=0.0, max_value=1.0), _H,
             "Usable fraction of CPU capacity.")
    d.define("network.inbound.capacity.threshold", Type.DOUBLE, 0.8,
             in_range(min_value=0.0, max_value=1.0), _H,
             "Usable fraction of NW_IN capacity.")
    d.define("network.outbound.capacity.threshold", Type.DOUBLE, 0.8,
             in_range(min_value=0.0, max_value=1.0), _H,
             "Usable fraction of NW_OUT capacity.")
    d.define("disk.capacity.threshold", Type.DOUBLE, 0.8,
             in_range(min_value=0.0, max_value=1.0), _H,
             "Usable fraction of DISK capacity.")
    d.define("cpu.low.utilization.threshold", Type.DOUBLE, 0.0,
             in_range(min_value=0.0, max_value=1.0), _L,
             "Below this CPU utilization, distribution goals stand down.")
    d.define("network.inbound.low.utilization.threshold", Type.DOUBLE, 0.0,
             in_range(min_value=0.0, max_value=1.0), _L, "NW_IN idle floor.")
    d.define("network.outbound.low.utilization.threshold", Type.DOUBLE, 0.0,
             in_range(min_value=0.0, max_value=1.0), _L, "NW_OUT idle floor.")
    d.define("disk.low.utilization.threshold", Type.DOUBLE, 0.0,
             in_range(min_value=0.0, max_value=1.0), _L, "DISK idle floor.")
    d.define("replica.count.balance.threshold", Type.DOUBLE, 1.1,
             in_range(min_value=1.0), _M,
             "Allowed replica-count ratio around the cluster average.")
    d.define("leader.replica.count.balance.threshold", Type.DOUBLE, 1.1,
             in_range(min_value=1.0), _M, "Leader-count balance ratio.")
    d.define("topic.replica.count.balance.threshold", Type.DOUBLE, 3.0,
             in_range(min_value=1.0), _M,
             "Per-topic replica-count balance ratio.")
    d.define("max.replicas.per.broker", Type.LONG, 10_000,
             in_range(min_value=1), _M, "Replica capacity per broker.")
    d.define("goals", Type.LIST,
             ("RackAwareGoal,ReplicaCapacityGoal,DiskCapacityGoal,"
              "NetworkInboundCapacityGoal,NetworkOutboundCapacityGoal,"
              "CpuCapacityGoal,ReplicaDistributionGoal,PotentialNwOutGoal,"
              "DiskUsageDistributionGoal,"
              "NetworkInboundUsageDistributionGoal,"
              "NetworkOutboundUsageDistributionGoal,"
              "CpuUsageDistributionGoal,TopicReplicaDistributionGoal,"
              "LeaderReplicaDistributionGoal,"
              "LeaderBytesInDistributionGoal"),
             None, _H, "Default goal list by descending priority.")
    d.define("hard.goals", Type.LIST,
             ("RackAwareGoal,ReplicaCapacityGoal,DiskCapacityGoal,"
              "NetworkInboundCapacityGoal,NetworkOutboundCapacityGoal,"
              "CpuCapacityGoal"),
             None, _H, "Goals that must always be satisfied.")
    d.define("default.goals", Type.LIST, "", None, _M,
             "Override of `goals` for proposal precomputation.")
    d.define("intra.broker.goals", Type.LIST,
             "IntraBrokerDiskCapacityGoal,IntraBrokerDiskUsageDistributionGoal",
             None, _M, "Goals for intra-broker (JBOD) rebalancing.")
    d.define("anomaly.detection.goals", Type.LIST,
             ("RackAwareGoal,ReplicaCapacityGoal,DiskCapacityGoal,"
              "NetworkInboundCapacityGoal,NetworkOutboundCapacityGoal,"
              "CpuCapacityGoal"),
             None, _M, "Goals the goal-violation detector checks.")
    d.define("self.healing.goals", Type.LIST, "", None, _L,
             "Goal override for self-healing (empty = default goals).")
    d.define("goal.balancedness.priority.weight", Type.DOUBLE, 1.1,
             in_range(min_value=1.0), _L,
             "Weight multiplier per goal-priority rank in balancedness.")
    d.define("goal.balancedness.strictness.weight", Type.DOUBLE, 1.5,
             in_range(min_value=1.0), _L,
             "Weight multiplier for hard goals in balancedness.")
    d.define("goal.violation.distribution.threshold.multiplier", Type.DOUBLE,
             1.0, in_range(min_value=1.0), _L,
             "Relaxation of distribution thresholds during violation fix.")
    d.define("num.proposal.precompute.threads", Type.INT, 1,
             in_range(min_value=0), _M,
             "Background proposal precompute loops; 0 disables the "
             "precompute (the device solver serializes on one chip, so "
             "values above 1 behave like 1).")
    d.define("proposal.expiration.ms", Type.LONG, 900_000,
             in_range(min_value=1), _M,
             "Cached proposals older than this are recomputed.")
    d.define("proposal.precompute.interval.ms", Type.LONG, 30_000,
             in_range(min_value=1), _L,
             "Pause between background proposal precompute passes "
             "(consecutive failures back off exponentially from this, "
             "capped at 32 intervals).")
    d.define("proposal.precompute.solve.deadline.ms", Type.LONG,
             1_800_000, in_range(min_value=1), _L,
             "Watchdog deadline for one precompute solve: a solve still "
             "running past this is considered wedged — shutdown stops "
             "waiting for it and the STATE endpoint flags it.")
    d.define("solver.degradation.enabled", Type.BOOLEAN, True, None, _M,
             "Run solves through the degradation ladder (classify "
             "failures, retry with backoff, fall back fused pipeline -> "
             "eager per-goal driver -> host/CPU self-healing-only "
             "solver, circuit breaker).  Disabled: every solve runs the "
             "fused pipeline once and failures propagate raw.")
    d.define("solver.max.retries.per.rung", Type.INT, 1,
             in_range(min_value=0), _L,
             "Same-rung retries (with backoff) before the ladder "
             "descends a rung.")
    d.define("solver.retry.backoff.base.ms", Type.LONG, 1_000,
             in_range(min_value=1), _L,
             "Base of the exponential retry backoff between solve "
             "attempts.")
    d.define("solver.retry.backoff.max.ms", Type.LONG, 60_000,
             in_range(min_value=1), _L,
             "Cap of the exponential retry backoff.")
    d.define("solver.circuit.breaker.failure.threshold", Type.INT, 3,
             in_range(min_value=1), _L,
             "Consecutive solve failures that trip the circuit breaker "
             "(pinning the degraded rung until the cooldown elapses).")
    d.define("solver.circuit.breaker.cooldown.ms", Type.LONG, 300_000,
             in_range(min_value=1), _L,
             "Cooldown after the breaker trips; once elapsed the next "
             "solve probes one rung up and success re-closes the "
             "breaker.")
    d.define("solver.fusion.enabled", Type.BOOLEAN, False, None, _M,
             "Fuse adjacent same-group goals into single compiled "
             "megaprograms (analyzer/fusion.py goal groups) instead of "
             "fixed-width pipeline segments: the default 15-goal stack "
             "drops from 4 to 3 goal programs per solve (the eager "
             "driver dispatches 30), cutting the serial dispatch tail "
             "the <5s headline needs.  Off keeps every historical "
             "program key and persistent-cache entry byte-stable.")
    d.define("solver.host.skip.enabled", Type.BOOLEAN, False, None, _L,
             "Skip a fused segment's device dispatch entirely when "
             "every goal in it reports no work (zero violated brokers "
             "on its no_work surface) on the segment's input state; "
             "skipped goals are metered as solver-goals-skipped.  "
             "Costs one scalar device sync per segment boundary, so it "
             "pays off only on transports where a dispatch is more "
             "expensive than a sync (remote TPU).  The zero-sync "
             "device-side early-exit inside the segment programs is "
             "always on and needs no flag.")
    d.define("solver.precision", Type.STRING, "float32",
             in_values("float32", "bfloat16"), _M,
             "Dtype of the solver's float load/capacity tables "
             "(replica loads, leadership bonuses, broker capacities); "
             "integer placement planes are always exact.  `bfloat16` "
             "halves table bandwidth per search round on TPU; results "
             "are accepted through the proposals-equivalence gate "
             "(analyzer/precision.py) instead of byte-identity — see "
             "solver.precision.balancedness.eps / "
             "solver.precision.min.move.overlap.")
    d.define("solver.precision.balancedness.eps", Type.DOUBLE, 0.5,
             in_range(min_value=0.0), _L,
             "Tolerance-gate term for reduced-precision solves: the "
             "bf16 result's balancedness score ([0,100]) must land "
             "within this many points of the f32 baseline when the "
             "gate is evaluated (bench / opt-in validation).")
    d.define("solver.precision.min.move.overlap", Type.DOUBLE, 0.90,
             in_range(min_value=0.0, max_value=1.0), _L,
             "Tolerance-gate term for reduced-precision solves: "
             "minimum Jaccard overlap between the bf16 and f32 "
             "placement-change sets.")
    d.define("scenario.engine.enabled", Type.BOOLEAN, True, None, _M,
             "Serve the SCENARIOS endpoint and multi-candidate broker "
             "operations through the batched what-if engine "
             "(scenario/engine.py).  Disabled: SCENARIOS requests fail "
             "and candidate-set requests are rejected.")
    d.define("scenario.max.batch.size", Type.INT, 32,
             in_range(min_value=1), _M,
             "Scenarios evaluated per batched device program; larger "
             "batches amortize one compile over more scenarios but cost "
             "K x the solve's HBM working set (see docs/SCENARIOS.md "
             "sizing guidance).")
    d.define("scenario.max.oom.halvings", Type.INT, 4,
             in_range(min_value=0), _L,
             "How many times a RESOURCE_EXHAUSTED scenario batch is "
             "halved and retried before the engine descends its "
             "degradation ladder (per-scenario eager loop, then host "
             "CPU fallback).")
    d.define("scenario.include.base.solve", Type.BOOLEAN, True, None, _L,
             "Prepend a no-op base scenario to every SCENARIOS batch so "
             "the report diffs each what-if against doing nothing.")
    d.define("portfolio.width", Type.INT, 1, in_range(min_value=1), _M,
             "Candidates per device-parallel portfolio search on the "
             "proposals/rebalance path (portfolio/): K perturbed solver "
             "candidates (goal-order shuffles, balance-threshold jitter, "
             "tie-break salts) ride one batched device solve and the "
             "best-by-fitness winner is served when STRICTLY better than "
             "greedy.  1 (default) disables the portfolio entirely — the "
             "greedy path stays byte-identical.")
    d.define("portfolio.seed", Type.INT, 0, None, _L,
             "Base seed for the candidate-perturbation streams; every "
             "candidate is a pure function of (base config, this seed, "
             "candidate index), so equal seeds replay bit-for-bit.")
    d.define("portfolio.movement.cost.weight", Type.DOUBLE, 4.0,
             in_range(min_value=0.0), _L,
             "Fitness = balancedness - weight x normalized movement "
             "(replica moves + 0.5 x leadership moves, per replica): how "
             "many balancedness points one cluster's-worth of movement "
             "costs a candidate.  0 ranks on balancedness alone.")
    d.define("portfolio.max.programs", Type.INT, 4,
             in_range(min_value=1), _L,
             "Distinct (goal order, fast-mode) trace programs a "
             "portfolio may compile; candidates beyond this share the "
             "pooled orders and differ only in batchable perturbations "
             "(thresholds, salts), keeping compile cost bounded while "
             "the width scales.")
    d.define("portfolio.max.eager.candidates", Type.INT, 4,
             in_range(min_value=1), _L,
             "Candidate budget at the portfolio's degraded EAGER rung "
             "(sequential per-candidate solves after a fused-batch "
             "failure); candidates beyond the budget are skipped.")
    d.define("portfolio.background.enabled", Type.BOOLEAN, False, None,
             _M,
             "Run the background refinement job: a SCENARIO_SWEEP-class "
             "loop that keeps searching for a better-than-cached "
             "proposal and installs winners through the compare-and-swap "
             "cache gate (stale generations dropped, never clobbering a "
             "fresher precompute).")
    d.define("portfolio.background.interval.ms", Type.LONG, 300000,
             in_range(min_value=1000), _L,
             "Delay between background refinement sweeps; failures back "
             "off exponentially (capped at 32 intervals) like the "
             "precompute loop.")
    d.define("portfolio.background.width", Type.INT, 8,
             in_range(min_value=2), _L,
             "Candidates per background refinement sweep (independent "
             "of the request-path portfolio.width).")
    d.define("portfolio.background.generations", Type.INT, 1,
             in_range(min_value=1), _L,
             "Evolutionary generations per background sweep: 1 is a "
             "one-shot search; >1 breeds each next population from the "
             "elite half (truncation selection + tier-respecting "
             "goal-order crossover + mutation).")
    d.define("scheduler.enabled", Type.BOOLEAN, True, None, _M,
             "Route every device solve (REST operations, proposal "
             "precompute, anomaly self-healing, scenario sweeps) through "
             "the device-time scheduler (cruise_control_tpu/sched/): "
             "priority admission, single-flight coalescing of identical "
             "requests, scenario folding, segment-boundary preemption "
             "and queue-cap backpressure (HTTP 429 + Retry-After).  "
             "Disabled: solves run inline on the calling thread (the "
             "pre-scheduler free-for-all).")
    d.define("scheduler.preemption.enabled", Type.BOOLEAN, True, None, _L,
             "Allow the dispatch loop to preempt preemptible classes "
             "(PRECOMPUTE, SCENARIO_SWEEP) at the next goal-segment "
             "boundary when a higher-priority solve queues up; the "
             "preempted job is re-queued with its aging credit intact.")
    d.define("scheduler.class.weights", Type.LIST, "8,4,2,1", None, _L,
             "Anti-starvation aging weight per scheduler class, in "
             "ANOMALY_HEAL,USER_INTERACTIVE,PRECOMPUTE,SCENARIO_SWEEP "
             "order: a class earns weight x (waited / deadline budget) "
             "priority classes of credit while queued, so background "
             "work can be delayed but never starved.")
    d.define("scheduler.class.queue.caps", Type.LIST, "8,6,2,8", None,
             _M,
             "Admission cap per scheduler class (same class order as "
             "scheduler.class.weights).  An offer beyond the cap is "
             "rejected with HTTP 429 and a Retry-After derived from the "
             "observed solve-latency EWMA x queue depth.  Keep the "
             "USER_INTERACTIVE cap below the USER_TASKS pool width "
             "(max_workers, 8): each pool worker holds at most one "
             "queued solve, so a larger cap can never fill from REST "
             "traffic and backpressure degrades to invisible pool "
             "queueing.")
    d.define("scheduler.class.deadline.budget.ms", Type.LIST,
             "5000,30000,120000,300000", None, _L,
             "Per-class deadline budget (same class order): the queue "
             "wait that earns one full priority class of aging credit "
             "(scaled by the class weight).")
    d.define("mesh.enabled", Type.STRING, "auto", None, _M,
             "Solve-mesh switch: 'auto' (default) runs the production "
             "solve over ALL visible accelerator devices on a 1-D "
             "('replica',) mesh when more than one non-CPU device is "
             "visible (v5e-8: broker tables and replica tensors shard, "
             "XLA inserts the ICI collectives); 'true' forces the mesh "
             "on whenever >1 device is visible (including the virtual "
             "multi-CPU test rig); 'false' pins single-chip solving.  "
             "With one device (or off) the solver runs the exact "
             "pre-mesh single-chip path — byte-identical, no padding, "
             "no resharding.  The scheduler's dispatch thread owns the "
             "mesh token; the degradation ladder gains a MESH rung "
             "above FUSED that descends to single-chip on "
             "collective/runtime failures (docs/MESH.md).")
    d.define("mesh.max.devices", Type.INT, 0, in_range(min_value=0), _L,
             "Clip the solve mesh to the first N visible devices "
             "(0 = use all).  Useful to reserve chips for other work or "
             "to A/B mesh scaling (BENCH_CONFIG=mesh automates the "
             "sweep).")
    d.define("mesh.recovery.enabled", Type.BOOLEAN, True, None, _M,
             "Elastic mesh recovery (parallel/health.py): on a wedged "
             "dispatch or collective failure the mesh supervisor "
             "condemns probed-dead chips, shrinks the span one rung "
             "down the MESH8-MESH4-MESH2-FUSED ladder, hydrates the "
             "survivor span's programs from the persistent program "
             "cache, and re-queues the in-flight solve — no process "
             "bounce.  Probe recovery climbs the span back one rung "
             "per probe cycle when the chips return.  false is the "
             "manual override (docs/OPERATIONS.md §5): failures fall "
             "through to the classic MESH->FUSED ladder descent and "
             "the watchdog is disarmed.")
    d.define("mesh.watchdog.ms", Type.LONG, 120_000,
             in_range(min_value=0), _M,
             "Watched-dispatch deadline: device execution runs on a "
             "watched worker thread, and a dispatch that has not "
             "answered within this many ms is declared WEDGED — the "
             "worker is abandoned (Python cannot abort an XLA "
             "dispatch), its executable quarantined, and the "
             "scheduler's dispatch thread released to shrink the span "
             "and re-queue the solve.  Must comfortably exceed the "
             "slowest legitimate solve SEGMENT on your hardware "
             "(compiles do not count — they run unwatched through the "
             "program-cache gateway).  0 disarms the watchdog.")
    d.define("mesh.probe.interval.ms", Type.LONG, 15_000,
             in_range(min_value=0), _L,
             "Minimum interval between per-chip health probes (the "
             "tiny known-answer program parallel/health.probe_devices "
             "runs per device).  While the span is shrunk or chips are "
             "condemned, each mesh solve older than this re-probes and "
             "climbs the span back ONE rung when the chips answer "
             "again — the same one-rung-per-probe discipline as the "
             "solver ladder.")
    d.define("mesh.min.devices", Type.INT, 1, in_range(min_value=1), _L,
             "Smallest mesh span worth its collectives: ladder rungs "
             "below this device count are skipped and the span ladder "
             "drops straight to the degenerate single-chip token "
             "(FUSED).  1 keeps every halving rung.")
    d.define("shutdown.drain.timeout.ms", Type.LONG, 30_000,
             in_range(min_value=0), _L,
             "Graceful-drain budget on SIGTERM/SIGINT: the REST layer "
             "answers writes 503 + Retry-After while the in-flight "
             "solve gets up to this many ms to finish; then pending "
             "program-cache temp files are swept, the flight recorder "
             "is dumped, and the process exits.  A wedged solve never "
             "holds shutdown past this budget (the precompute-watchdog "
             "rule applied to the whole process).")
    d.define("progcache.enabled", Type.BOOLEAN, True, None, _M,
             "Route every pipeline compile through the persistent "
             "compiled-program cache (parallel/progcache.py): warmup "
             "becomes a cache-first hydrate (serialized StableHLO via "
             "jax.export, the XLA persistent compilation cache as the "
             "lower tier), so a process bounce, tenant register() or "
             "ladder probe-recovery reaches FUSED/MESH in seconds "
             "instead of re-paying the ~300s AOT compile.  The cache is "
             "inert until progcache.dir names a directory; disabled, "
             "every compile path is byte-identical to the pre-cache "
             "behavior.")
    d.define("progcache.dir", Type.STRING, "", None, _M,
             "Directory of the persistent program cache (local disk or "
             "a shared blob mount — entries are atomic "
             "write-temp-then-rename, so concurrent writers are safe).  "
             "Empty (the default) disables persistence; '.progcache' is "
             "the conventional location (gitignored, `make warm-cache` "
             "pre-populates it).")
    d.define("progcache.max.bytes", Type.LONG, 2_147_483_648,
             in_range(min_value=1), _L,
             "Size cap of the program-cache directory; crossing it "
             "evicts oldest entries first (age by mtime, all "
             "fingerprint generations considered).")
    d.define("progcache.fingerprint.override", Type.STRING, "", None, _L,
             "Replaces the source-content term of the cache "
             "fingerprint (jax/jaxlib version, backend and device kind "
             "always apply).  Set a fixed label to share entries "
             "across builds you know are program-equivalent; bump it "
             "to force a cold generation.  A mismatched fingerprint is "
             "a miss, never a wrong answer.")
    d.define("incremental.enabled", Type.BOOLEAN, True, None, _M,
             "Device-resident incremental workload model "
             "(model/store.py + docs/INCREMENTAL.md): keep the current "
             "cluster model on device keyed by model generation, "
             "fast-forward it through structured monitor deltas "
             "(LoadMonitor.apply_model_delta) instead of rebuilding "
             "per solve, and let USER_INTERACTIVE default-stack solves "
             "warm-start with a dirty-region restriction (candidate "
             "sources/destinations limited to the delta's dirty "
             "brokers + their balance neighborhood).  Disabled, every "
             "solve re-materializes the full model and sweeps every "
             "broker — the pre-incremental behavior, byte-identical.")
    d.define("incremental.max.deltas", Type.INT, 64,
             in_range(min_value=0), _L,
             "Longest delta chain the store fast-forwards through "
             "before preferring a full rebuild (a delta storm is "
             "better served by one rebuild than by hundreds of "
             "scatter programs; fallback metered as "
             "incremental-store-fallbacks).")
    d.define("incremental.max.dirty.broker.ratio", Type.DOUBLE, 0.5,
             in_range(min_value=0.0, max_value=1.0), _L,
             "Dirty-region ceiling: when the deltas since the warm "
             "seed dirty more than this fraction of brokers, the "
             "restricted solve cannot beat a full sweep — the solve "
             "runs unrestricted (still store-served and warm-started; "
             "metered).")
    d.define("fleet.bucket.floor", Type.INT, 8, in_range(min_value=1), _M,
             "Smallest shape-bucket edge for fleet serving "
             "(fleet/buckets.py): every tenant's model pads each axis "
             "up to the next power of two, floored here, so tenants of "
             "similar size share ONE compiled program per (bucket, goal "
             "list).  Raise it when the fleet-bucket-compiles sensor "
             "shows tenant geometry fragmenting into too many buckets.")
    d.define("fleet.bucket.max.tracked", Type.INT, 64,
             in_range(min_value=1), _L,
             "LRU cap on tracked (bucket, goal-list) combos in the "
             "fleet bucket index; crossing it logs the bucket-explosion "
             "warning (the cap bounds tracking, not XLA executables).")
    d.define("fleet.fold.enabled", Type.BOOLEAN, True, None, _M,
             "Batch compatible queued solves from DIFFERENT tenants in "
             "the same shape bucket into one vmapped device dispatch "
             "(fleet/router.py; outcomes split back per tenant, "
             "fleet-folded-solves meter).  Disabled: tenants still "
             "share bucketed compiled programs but every solve "
             "dispatches alone.")
    d.define("fleet.max.tenants", Type.INT, 64, in_range(min_value=1),
             _M,
             "Registration cap for the fleet registry; registering "
             "beyond it fails (protects one device from unbounded "
             "tenant fan-in).")
    d.define("fleet.default.cluster.id", Type.STRING, "", None, _L,
             "Cluster id served when a request names no ?cluster= "
             "(must be one of the --fleet-config clusters; empty = the "
             "first configured cluster).")
    d.define("proposal.warm.start.enabled", Type.BOOLEAN, True, None, _L,
             "Seed default-stack solves from the previous solve's final "
             "placement when the model generation moved but the topology "
             "is unchanged (framework extension of the reference's "
             "generation-keyed proposal cache): converged goals then open "
             "at near-zero search rounds.  Results are identical in "
             "validity to a cold solve — only the search start changes.")
    d.define("max.optimization.rounds", Type.INT, 64,
             in_range(min_value=1), _L,
             "Per-goal cap on batched optimization rounds (TPU solver). "
             "Hard goals are floored at 1024 rounds regardless: an "
             "unconverged hard goal aborts the whole optimization, and "
             "rounds only run while progress is made, so the higher bound "
             "is free once converged.")
    d.define("allow.capacity.estimation.on.proposal", Type.BOOLEAN, True,
             None, _L, "Allow estimated capacities when computing proposals.")
    d.define("allow.capacity.estimation.on.proposal.precompute",
             Type.BOOLEAN, True, None, _L,
             "Allow estimated capacities in the background proposal "
             "precompute loop.")
    d.define("topics.excluded.from.partition.movement", Type.STRING, "",
             None, _M,
             "Regex of topics never moved by any optimization "
             "(merged into every request's excluded set).")
    d.define("optimization.options.generator.class", Type.CLASS,
             "cruise_control_tpu.analyzer.options_generator"
             ".DefaultOptimizationOptionsGenerator",
             None, _L,
             "OptimizationOptions generator applied to every request.")
    return d


def obs_config_def(d: ConfigDef) -> ConfigDef:
    """observability (framework extension, cruise_control_tpu/obs/ +
    docs/OBSERVABILITY.md): request-scoped tracing, the flight
    recorder, and the OpenMetrics exporter"""
    d.define("obs.tracing.enabled", Type.BOOLEAN, True, None, _M,
             "Request-scoped solve tracing (obs/trace.py): a "
             "TraceContext minted at the REST transport rides through "
             "the scheduler, the degradation ladder, model "
             "materialization and the device pipeline; every "
             "solve-bearing response carries a `traceId` resolvable "
             "via the TRACES endpoint.  Always-on by design (bounded "
             "overhead: host clock reads only, zero device work); "
             "disable only to rule tracing out during an incident.")
    d.define("obs.flight.recorder.capacity", Type.INT, 256,
             in_range(min_value=1), _L,
             "Completed solve traces retained in the flight-recorder "
             "ring (oldest evicted beyond it).")
    d.define("obs.flight.recorder.max.pinned", Type.INT, 256,
             in_range(min_value=0), _L,
             "Failed/degraded/preempted/fallback traces PINNED past "
             "ring eviction until a TRACES query exports them "
             "(incident evidence survives healthy traffic); 0 disables "
             "pinning.")
    d.define("obs.trace.log.enabled", Type.BOOLEAN, False, None, _L,
             "Emit one structured JSON log line per finished trace "
             "through the `traceLogger` logger (route it to its own "
             "file like the access log).")
    d.define("obs.trace.sample.rate", Type.DOUBLE, 1.0,
             in_range(min_value=0.0, max_value=1.0), _M,
             "Fraction of OK traces handed to the flight recorder "
             "(deterministic per trace id).  Non-ok traces "
             "(failed/degraded/fallback/preempted/rejected) are ALWAYS "
             "kept: at load-harness rates the ring churns in seconds, "
             "and sampling must thin the healthy wash, never the "
             "incident evidence.  Sampled-out traces are counted "
             "(recorder `sampledOut`), and the obs.trace.log.enabled "
             "stream is NOT sampled — the durable log still carries "
             "every finished trace.  1.0 = record everything (the "
             "pre-load-harness behavior).")
    d.define("obs.metrics.buckets", Type.STRING, "", None, _L,
             "Per-sensor histogram bucket boundaries: keys of the form "
             "`obs.metrics.buckets.<sensor-name-or-prefix>` (this bare "
             "key documents the family; set the SUFFIXED keys) map to "
             "a CSV of boundaries in SECONDS, e.g. "
             "`obs.metrics.buckets.sched-wait-hist=0.01,0.05,0.076,"
             "0.1,0.25,0.7,1.0` — a prefix covers every per-class "
             "histogram it prefixes.  Needed when the default "
             "boundaries cannot resolve two latency populations (76 ms "
             "incremental vs 700 ms full solves); align boundaries "
             "with `slo.<class>.*` thresholds to make burn rates "
             "exact.  Applied at histogram creation (startup) only.")
    d.define("obs.metrics.endpoint.enabled", Type.BOOLEAN, True, None,
             _M,
             "Serve the OpenMetrics scrape page at /metrics (outside "
             "the API prefix, behind the same authentication): every "
             "sensor registry, fleet tenants as cluster=\"<id>\" "
             "labeled series, histogram families for queue-wait and "
             "solve latency.")
    return d


def slo_config_def(d: ConfigDef) -> ConfigDef:
    """service-level objectives (framework extension, obs/slo.py +
    tools/slo_gate.py + docs/LOADGEN.md): per-scheduler-class latency
    thresholds and error budgets, burn rate computed live from the
    sched-* histograms, surfaced as STATE `sloStatus`, `/metrics`
    `cc_tpu_slo_*` series and the SLO_BURN anomaly"""
    d.define("slo.enabled", Type.BOOLEAN, True, None, _M,
             "Evaluate per-class SLO burn rates (obs/slo.py) and "
             "surface them in STATE `sloStatus`, the `slo-*` sensors "
             "and the SLO_BURN anomaly.  Disabled, the sloStatus block "
             "reports enabled=false and no SLO_BURN ever fires.")
    d.define("slo.window.ms", Type.LONG, 300_000, in_range(min_value=1000),
             _M,
             "Sliding window the burn rate is computed over: burn = "
             "(fraction of the window's observations over threshold) / "
             "error budget, so a breach ages out once the window rolls "
             "past it.")
    d.define("slo.evaluation.interval.ms", Type.LONG, 15_000,
             in_range(min_value=100), _L,
             "Interval of the scheduled SLO_BURN detector "
             "(detector/slo_burn.py); gauges and STATE refresh "
             "opportunistically on read regardless.")
    d.define("slo.burn.alert.threshold", Type.DOUBLE, 2.0,
             in_range(min_value=1.0), _M,
             "Burn rate at which a class enters `breach` status and "
             "the SLO_BURN anomaly fires (2.0 = consuming budget at "
             "twice the sustainable rate).  Between 1.0 and this the "
             "class reports `burning` without alerting.")
    for klass, latency_ms, wait_ms, budget in (
            ("anomaly-heal", 5_000, 1_000, 0.01),
            ("user-interactive", 2_000, 500, 0.02),
            ("precompute", 30_000, 10_000, 0.05),
            ("scenario-sweep", 60_000, 30_000, 0.05)):
        d.define(f"slo.{klass}.latency.ms", Type.LONG, latency_ms,
                 in_range(min_value=1), _M,
                 f"Device-time objective for {klass.upper().replace('-', '_')} "
                 f"solves: a dispatch slower than this consumes error "
                 f"budget (measured on sched-device-busy-hist-{klass}).")
        d.define(f"slo.{klass}.queue.wait.ms", Type.LONG, wait_ms,
                 in_range(min_value=1), _M,
                 f"Queue-wait objective for {klass.upper().replace('-', '_')}: "
                 f"waiting longer than this before dispatch consumes "
                 f"error budget (measured on sched-wait-hist-{klass}).")
        d.define(f"slo.{klass}.error.budget", Type.DOUBLE, budget,
                 in_range(min_value=1e-6, max_value=1.0), _M,
                 f"Fraction of {klass.upper().replace('-', '_')} "
                 f"observations allowed over threshold per window; "
                 f"burn = actual over-threshold fraction / this.")
    return d


def executor_config_def(d: ConfigDef) -> ConfigDef:
    """reference config/constants/ExecutorConfig.java (20 keys)"""
    d.define("num.concurrent.partition.movements.per.broker", Type.INT, 5,
             in_range(min_value=1), _H,
             "Cap of in-flight inter-broker moves per broker.")
    d.define("num.concurrent.intra.broker.partition.movements", Type.INT, 2,
             in_range(min_value=1), _M,
             "Cap of in-flight intra-broker (logdir) moves per broker.")
    d.define("num.concurrent.leader.movements", Type.INT, 1000,
             in_range(min_value=1), _M,
             "Cap of leadership changes per execution batch.")
    d.define("execution.progress.check.interval.ms", Type.LONG, 10_000,
             in_range(min_value=1), _H,
             "Interval between execution progress polls.")
    d.define("max.num.cluster.movements", Type.INT, 1250,
             in_range(min_value=1), _M,
             "Global cap of simultaneous movement tasks.")
    d.define("default.replication.throttle", Type.LONG, -1, None, _M,
             "Replication throttle in B/s applied during moves (-1 = none).")
    d.define("replica.movement.strategies", Type.LIST,
             "BaseReplicaMovementStrategy", None, _M,
             "Chain of task-ordering strategies.")
    d.define("default.replica.movement.strategies", Type.LIST,
             "BaseReplicaMovementStrategy", None, _L,
             "Default strategy chain when a request names none.")
    d.define("executor.notifier.class", Type.CLASS,
             "cruise_control_tpu.executor.executor.ExecutorNotifier",
             None, _L, "ExecutorNotifier implementation (the default logs "
             "execution completion).")
    d.define("max.execution.task.lifetime.ms", Type.LONG, 86_400_000,
             in_range(min_value=1), _L,
             "Tasks alive longer than this are marked dead.")
    d.define("task.execution.alerting.threshold.ms", Type.LONG, 90_000,
             in_range(min_value=1), _L,
             "Alert when a task takes longer than this.")
    d.define("leader.movement.timeout.ms", Type.LONG, 180_000,
             in_range(min_value=1), _L, "Timeout for a leadership movement.")
    d.define("demotion.history.retention.time.ms", Type.LONG, 1_209_600_000,
             in_range(min_value=1), _L, "Retention of demoted-broker records.")
    d.define("removal.history.retention.time.ms", Type.LONG, 1_209_600_000,
             in_range(min_value=1), _L, "Retention of removed-broker records.")
    d.define("inter.broker.replica.movement.rate.alerting.threshold",
             Type.DOUBLE, 0.1, in_range(min_value=0.0), _L,
             "Alert when inter-broker movement throughput (MB/s) drops "
             "below this while tasks are in flight.")
    d.define("intra.broker.replica.movement.rate.alerting.threshold",
             Type.DOUBLE, 0.2, in_range(min_value=0.0), _L,
             "Alert threshold for intra-broker (logdir) movement "
             "throughput in MB/s.")
    d.define("logdir.response.timeout.ms", Type.LONG, 10_000,
             in_range(min_value=1), _L,
             "Timeout for logdir describe/alter calls to the cluster.")
    d.define("executor.max.consecutive.poll.failures", Type.INT, 10,
             in_range(min_value=1), _M,
             "Consecutive execution-progress poll failures tolerated "
             "before the execution fails (transient admin blips are "
             "retried next interval; a permanently broken admin client "
             "must not wedge has_ongoing_execution forever).  1 = "
             "fail-fast: the second consecutive failure fails the run.")
    d.define("executor.journal.dir", Type.STRING, "", None, _M,
             "Directory of the durable executor journal (crash-safe "
             "execution, docs/EXECUTOR.md): an append-only CRC-framed "
             "WAL of execution state plus the removal/demotion history,"
             " replayed at startup to resume or abort an execution a "
             "process bounce interrupted.  Empty (the default) keeps "
             "the executor in-memory only.  Fleet deployments get one "
             "subdirectory per tenant.")
    d.define("executor.journal.segment.max.bytes", Type.LONG, 4_194_304,
             in_range(min_value=4096), _L,
             "Rotate the executor journal to a fresh segment beyond "
             "this size; settled segments are deleted when the next "
             "execution starts.")
    d.define("executor.recovery.mode", Type.STRING, "resume",
             in_values("resume", "abort"), _M,
             "What startup journal replay does with an execution the "
             "previous process left in flight: `resume` restarts it "
             "under the original uuid/caps/strategy (moves the cluster "
             "finished are sealed, moves still running are adopted and "
             "polled, never re-submitted); `abort` cancels the "
             "in-flight reassignments and settles the journal.  Both "
             "modes clear orphaned replication throttles first.")
    d.define("zookeeper.security.enabled", Type.BOOLEAN, False, None, _L,
             "Reference-compat flag: the reference secures its ZooKeeper "
             "sessions with this; this framework has no ZooKeeper — when "
             "set, startup logs that security is the cluster admin "
             "client's responsibility (see docs/DECISIONS.md).")
    d.define("cluster.admin.class", Type.CLASS, "", None, _H,
             "ClusterAdminClient implementation providing the cluster "
             "connection (metadata, topic configs, reassignment "
             "execution).  Unset: main falls back to the "
             "reference-compat alias `network.client.provider.class`, "
             "then to --demo-cluster.")
    d.define("network.client.provider.class", Type.CLASS, "", None, _L,
             "Reference-compat alias for the cluster client factory: "
             "when `cluster.admin.class` is unset, this class (a "
             "ClusterAdminClient) provides the cluster connection.")
    return d


def anomaly_detector_config_def(d: ConfigDef) -> ConfigDef:
    """reference config/constants/AnomalyDetectorConfig.java (24 keys)"""
    d.define("anomaly.detection.interval.ms", Type.LONG, 300_000,
             in_range(min_value=1), _H,
             "Base interval for scheduled anomaly detectors.")
    d.define("goal.violation.detection.interval.ms", Type.LONG, -1, None, _M,
             "Goal-violation detector interval (-1 = base interval).")
    d.define("metric.anomaly.detection.interval.ms", Type.LONG, -1, None, _M,
             "Metric-anomaly detector interval (-1 = base interval).")
    d.define("disk.failure.detection.interval.ms", Type.LONG, -1, None, _M,
             "Disk-failure detector interval (-1 = base interval).")
    d.define("topic.anomaly.detection.interval.ms", Type.LONG, -1, None, _M,
             "Topic-anomaly detector interval (-1 = base interval).")
    d.define("broker.failure.alert.threshold.ms", Type.LONG, 900_000,
             in_range(min_value=0), _M,
             "Grace before a broker failure is alerted.")
    d.define("broker.failure.self.healing.threshold.ms", Type.LONG,
             1_800_000, in_range(min_value=0), _M,
             "Grace before broker-failure self-healing starts.")
    d.define("anomaly.notifier.class", Type.CLASS,
             "cruise_control_tpu.detector.notifier.SelfHealingNotifier",
             None, _H, "AnomalyNotifier implementation.")
    d.define("self.healing.enabled", Type.BOOLEAN, False, None, _H,
             "Master switch for all self-healing.")
    d.define("self.healing.broker.failure.enabled", Type.BOOLEAN, True, None,
             _M, "Self-heal broker failures.")
    d.define("self.healing.goal.violation.enabled", Type.BOOLEAN, True, None,
             _M, "Self-heal goal violations.")
    d.define("self.healing.disk.failure.enabled", Type.BOOLEAN, True, None,
             _M, "Self-heal disk failures.")
    d.define("self.healing.metric.anomaly.enabled", Type.BOOLEAN, False,
             None, _M, "Self-heal metric anomalies.")
    d.define("self.healing.topic.anomaly.enabled", Type.BOOLEAN, False, None,
             _M, "Self-heal topic anomalies.")
    d.define("self.healing.slow.broker.removal.enabled", Type.BOOLEAN, False,
             None, _M, "Allow slow-broker escalation to removal.")
    d.define("metric.anomaly.finder.class", Type.LIST,
             "cruise_control_tpu.core.anomaly.PercentileMetricAnomalyFinder",
             None, _M, "MetricAnomalyFinder implementations.")
    d.define("metric.anomaly.percentile.upper.threshold", Type.DOUBLE, 95.0,
             in_range(min_value=0.0, max_value=100.0), _L,
             "Upper percentile for the percentile anomaly finder.")
    d.define("metric.anomaly.percentile.lower.threshold", Type.DOUBLE, 2.0,
             in_range(min_value=0.0, max_value=100.0), _L,
             "Lower percentile for the percentile anomaly finder.")
    d.define("slow.broker.bytes.rate.detection.threshold", Type.DOUBLE, 1024.0,
             in_range(min_value=0.0), _L,
             "Minimum byte rate before slow-broker scoring applies.")
    d.define("slow.broker.log.flush.time.threshold.ms", Type.DOUBLE, 1000.0,
             in_range(min_value=0.0), _L,
             "Log-flush-time floor for slow-broker detection.")
    d.define("slow.broker.demotion.score", Type.INT, 5,
             in_range(min_value=1), _L,
             "Slowness score at which a broker is demoted.")
    d.define("slow.broker.decommission.score", Type.INT, 50,
             in_range(min_value=1), _L,
             "Slowness score at which a broker is removed.")
    d.define("topic.anomaly.finder.class", Type.LIST, "", None, _L,
             "TopicAnomalyFinder implementations.")
    d.define("topic.replication.factor.margin", Type.INT, 1,
             in_range(min_value=0), _L,
             "Required RF margin over min.insync.replicas.")
    d.define("broker.failure.detection.backoff.ms", Type.LONG, 300_000,
             in_range(min_value=1), _L,
             "Backoff before re-checking liveness of a suspect broker.")
    d.define("fixable.failed.broker.count.threshold", Type.INT, 10,
             in_range(min_value=1), _L,
             "Self-healing declines broker failures above this count.")
    d.define("fixable.failed.broker.percentage.threshold", Type.DOUBLE,
             0.4, in_range(min_value=0.0, max_value=1.0), _L,
             "Self-healing declines failures above this broker fraction.")
    d.define("broker.failures.class", Type.CLASS,
             "cruise_control_tpu.detector.anomalies.BrokerFailures", None,
             _L, "Anomaly class instantiated for broker failures.")
    d.define("goal.violations.class", Type.CLASS,
             "cruise_control_tpu.detector.anomalies.GoalViolations", None,
             _L, "Anomaly class instantiated for goal violations.")
    d.define("disk.failures.class", Type.CLASS,
             "cruise_control_tpu.detector.anomalies.DiskFailures", None,
             _L, "Anomaly class instantiated for disk failures.")
    d.define("metric.anomaly.class", Type.CLASS,
             "cruise_control_tpu.core.anomaly.MetricAnomaly", None,
             _L, "Anomaly class instantiated for metric anomalies.")
    d.define("anomaly.detection.allow.capacity.estimation", Type.BOOLEAN,
             True, None, _L,
             "Allow estimated capacities in detector model builds.")
    d.define("self.healing.exclude.recently.demoted.brokers", Type.BOOLEAN,
             True, None, _L,
             "Exclude recently demoted brokers from self-healing "
             "leadership moves.")
    d.define("self.healing.exclude.recently.removed.brokers", Type.BOOLEAN,
             True, None, _L,
             "Exclude recently removed brokers from self-healing replica "
             "moves.")
    d.define("failed.brokers.zk.path", Type.STRING, "", None, _L,
             "Reference-compat name for the durable failed-broker store "
             "location (modernized: a filesystem path for the file store "
             "instead of a ZooKeeper znode path).")
    return d


def webserver_config_def(d: ConfigDef) -> ConfigDef:
    """reference config/constants/WebServerConfig.java (36 keys)"""
    d.define("webserver.http.port", Type.INT, 9090,
             in_range(min_value=0, max_value=65535), _H, "REST port.")
    d.define("webserver.http.address", Type.STRING, "127.0.0.1", None, _H,
             "REST bind address.")
    d.define("webserver.http.cors.enabled", Type.BOOLEAN, False, None, _L,
             "Enable CORS headers.")
    d.define("webserver.http.cors.origin", Type.STRING, "*", None, _L,
             "CORS allowed origin.")
    d.define("webserver.api.urlprefix", Type.STRING, "/kafkacruisecontrol",
             None, _M, "URL prefix for all endpoints.")
    d.define("webserver.session.maxExpiryTimeMs", Type.LONG, 60_000,
             in_range(min_value=1), _L, "Async session expiry.")
    d.define("webserver.session.path", Type.STRING, "/", None, _L,
             "Cookie path for the async-session cookie.")
    d.define("webserver.http.cors.allowmethods", Type.STRING,
             "OPTIONS, GET, POST", None, _L,
             "CORS Access-Control-Allow-Methods header value.")
    d.define("webserver.http.cors.exposeheaders", Type.STRING,
             "User-Task-ID", None, _L,
             "CORS Access-Control-Expose-Headers header value.")
    d.define("webserver.accesslog.path", Type.STRING, "", None, _L,
             "Access-log file path (empty = route the accessLogger "
             "logger yourself).")
    d.define("webserver.accesslog.retention.days", Type.INT, 14,
             in_range(min_value=1), _L,
             "Rotated access-log files kept (daily rotation).")
    d.define("webserver.ui.diskpath", Type.STRING, "", None, _L,
             "Directory of UI static files to serve (empty disables).")
    d.define("webserver.ui.urlprefix", Type.STRING, "/ui", None, _L,
             "URL prefix the UI is served under.")
    d.define("request.reason.required", Type.BOOLEAN, False, None, _L,
             "Reject POSTs without a reason parameter.")
    d.define("webserver.request.maxBlockTimeMs", Type.LONG, 10_000,
             in_range(min_value=0), _M,
             "How long a sync-looking request blocks before going async.")
    d.define("webserver.security.enable", Type.BOOLEAN, False, None, _M,
             "Enable authentication/authorization.")
    d.define("webserver.security.provider", Type.CLASS,
             "cruise_control_tpu.api.security.BasicSecurityProvider",
             None, _M, "SecurityProvider implementation.")
    d.define("webserver.auth.credentials.file", Type.STRING, "", None, _M,
             "Credentials file for basic auth (user: password,ROLE).")
    d.define("webserver.ssl.enable", Type.BOOLEAN, False, None, _M,
             "Serve HTTPS (requires keystore).")
    d.define("webserver.ssl.keystore.location", Type.STRING, "", None, _L,
             "PEM certificate (optionally with key) path for TLS.")
    d.define("webserver.ssl.keyfile.location", Type.STRING, "", None, _L,
             "PEM private-key path when separate from the certificate.")
    d.define("webserver.ssl.key.password", Type.PASSWORD, "", None, _L,
             "TLS key password.")
    d.define("webserver.ssl.keystore.password", Type.PASSWORD, "", None, _L,
             "Keystore password (used when webserver.ssl.key.password is "
             "unset).")
    d.define("webserver.ssl.keystore.type", Type.STRING, "PEM", None, _L,
             "Keystore format; this framework supports PEM (convert "
             "JKS/PKCS12 via openssl).")
    d.define("webserver.ssl.protocol", Type.STRING, "TLS", None, _L,
             "Minimum TLS version: TLS (library default), TLSv1.2 or "
             "TLSv1.3.")
    d.define("webserver.security.jwt.secret", Type.PASSWORD, "", None, _M,
             "HS256 shared secret for JwtSecurityProvider (use "
             "${env:NAME} indirection for the value).")
    d.define("webserver.security.jwt.public.key.location", Type.STRING, "",
             None, _M,
             "PEM RSA public key for RS256 JWT verification.")
    d.define("webserver.security.jwt.issuer", Type.STRING, "", None, _L,
             "Expected JWT iss claim (empty disables the check).")
    d.define("webserver.security.jwt.audience", Type.STRING, "", None, _L,
             "Expected JWT aud claim (empty disables the check).")
    d.define("jwt.auth.certificate.location", Type.STRING, "", None, _L,
             "Reference-compat alias of "
             "webserver.security.jwt.public.key.location (PEM "
             "certificate/public key for RS256 verification).")
    d.define("jwt.authentication.provider.url", Type.STRING, "", None, _L,
             "Login URL advertised in 401 challenges (browsers redirect "
             "here to obtain a token).")
    d.define("jwt.cookie.name", Type.STRING, "", None, _L,
             "Cookie name carrying the JWT (empty = Authorization header "
             "only).")
    d.define("jwt.expected.audiences", Type.LIST, "", None, _L,
             "Accepted JWT aud claims (superset form of "
             "webserver.security.jwt.audience).")
    d.define("spnego.keytab.file", Type.STRING, "", None, _L,
             "Reference-compat: SPNEGO keytab.  Kerberos termination is a "
             "documented non-goal (docs/DECISIONS.md) — setting this "
             "fails startup with the proxy-termination guidance.")
    d.define("spnego.principal", Type.STRING, "", None, _L,
             "Reference-compat: SPNEGO service principal (see "
             "spnego.keytab.file).")
    d.define("trusted.proxy.services", Type.LIST, "", None, _L,
             "Service principals accepted by the trusted-proxy provider.")
    d.define("trusted.proxy.services.ip.regex", Type.STRING, "", None, _L,
             "Regex of proxy source addresses allowed to assert "
             "doAs identities.")
    d.define("webserver.accesslog.enabled", Type.BOOLEAN, True, None, _L,
             "Write NCSA-style access log lines.")
    d.define("two.step.verification.enabled", Type.BOOLEAN, False, None, _M,
             "Park POST requests in the purgatory for review.")
    d.define("two.step.purgatory.retention.time.ms", Type.LONG,
             1_209_600_000, in_range(min_value=1), _L,
             "Purgatory retention for pending requests.")
    d.define("two.step.purgatory.max.requests", Type.INT, 25,
             in_range(min_value=1), _L, "Purgatory capacity.")
    return d


def user_task_manager_config_def(d: ConfigDef) -> ConfigDef:
    """reference config/constants/UserTaskManagerConfig.java (10 keys)"""
    d.define("max.active.user.tasks", Type.INT, 5, in_range(min_value=1), _M,
             "Maximum concurrently active async user tasks.")
    d.define("completed.user.task.retention.time.ms", Type.LONG, 86_400_000,
             in_range(min_value=1), _M,
             "Retention of completed user tasks.")
    d.define("max.cached.completed.user.tasks", Type.INT, 100,
             in_range(min_value=1), _L,
             "Maximum completed user tasks cached.")
    # per-category retention/caps (reference UserTaskManagerConfig splits
    # completed tasks into {kafka, cruise control} x {admin, monitor})
    d.define("completed.kafka.admin.user.task.retention.time.ms",
             Type.LONG, -1, None, _L,
             "Retention of completed Kafka-admin tasks (-1 = the general "
             "completed.user.task.retention.time.ms).")
    d.define("completed.kafka.monitor.user.task.retention.time.ms",
             Type.LONG, -1, None, _L,
             "Retention of completed Kafka-monitor tasks (-1 = general).")
    d.define("completed.cruise.control.admin.user.task.retention.time.ms",
             Type.LONG, -1, None, _L,
             "Retention of completed Cruise-Control-admin tasks "
             "(-1 = general).")
    d.define("completed.cruise.control.monitor.user.task.retention.time.ms",
             Type.LONG, -1, None, _L,
             "Retention of completed Cruise-Control-monitor tasks "
             "(-1 = general).")
    d.define("max.cached.completed.kafka.admin.user.tasks", Type.INT, -1,
             None, _L,
             "Cap of cached completed Kafka-admin tasks (-1 = the "
             "general max.cached.completed.user.tasks).")
    d.define("max.cached.completed.kafka.monitor.user.tasks", Type.INT, -1,
             None, _L,
             "Cap of cached completed Kafka-monitor tasks (-1 = general).")
    d.define("max.cached.completed.cruise.control.admin.user.tasks",
             Type.INT, -1, None, _L,
             "Cap of cached completed Cruise-Control-admin tasks "
             "(-1 = general).")
    d.define("max.cached.completed.cruise.control.monitor.user.tasks",
             Type.INT, -1, None, _L,
             "Cap of cached completed Cruise-Control-monitor tasks "
             "(-1 = general).")
    return d


def request_parameters_config_def(d: ConfigDef) -> ConfigDef:
    """reference config/constants/CruiseControlRequestConfig.java +
    CruiseControlParametersConfig.java (20 + 20 keys): per-endpoint
    request-handler and parameter-validation classes."""
    from cruise_control_tpu.api.request_registry import request_config_def
    request_config_def(d)
    return d


def config_def() -> ConfigDef:
    d = ConfigDef()
    monitor_config_def(d)
    analyzer_config_def(d)
    obs_config_def(d)
    slo_config_def(d)
    executor_config_def(d)
    anomaly_detector_config_def(d)
    webserver_config_def(d)
    user_task_manager_config_def(d)
    request_parameters_config_def(d)
    return d


class CruiseControlConfig(AbstractConfig):
    """reference CC/config/KafkaCruiseControlConfig.java — parsed config with
    cross-field sanity checks."""

    def __init__(self, props: Mapping[str, Any]):
        super().__init__(config_def(), props)
        self._sanity_check()

    def _sanity_check(self) -> None:
        """Cross-field checks (reference
        KafkaCruiseControlConfig.sanityCheck*)."""
        goals = [g for g in self.get_list("goals") if g]
        hard = [g for g in self.get_list("hard.goals") if g]
        missing = [g for g in hard if g not in goals]
        if missing:
            raise ConfigException(
                f"hard.goals {missing} are not in the goals list")
        detection = [g for g in self.get_list("anomaly.detection.goals")
                     if g]
        missing = [g for g in detection if g not in goals]
        if missing:
            raise ConfigException(
                f"anomaly.detection.goals {missing} are not in goals")
        if (self.get_long("broker.failure.self.healing.threshold.ms")
                < self.get_long("broker.failure.alert.threshold.ms")):
            raise ConfigException(
                "broker.failure.self.healing.threshold.ms must be >= "
                "broker.failure.alert.threshold.ms")
