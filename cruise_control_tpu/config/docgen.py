"""Configuration-reference generator.

The reference publishes a full key reference
(docs/wiki/User Guide/Configurations.md, ~293 lines); here the reference
document is GENERATED from the typed ConfigDef groups so it can never go
stale — `python -m cruise_control_tpu.config.docgen > docs/CONFIGURATION.md`
regenerates it, and a test asserts the committed file matches the live
definitions (the same defs-are-the-source-of-truth idea as the reference's
ResponseTest schema walk).
"""
from __future__ import annotations

from cruise_control_tpu.common.config import ConfigDef
from cruise_control_tpu.config import main_config as M

#: (section title, def-builder) in the reference's constant-group order
GROUPS = [
    ("Monitor", M.monitor_config_def),
    ("Analyzer", M.analyzer_config_def),
    ("Observability", M.obs_config_def),
    ("SLO", M.slo_config_def),
    ("Executor", M.executor_config_def),
    ("Anomaly detector", M.anomaly_detector_config_def),
    ("Webserver", M.webserver_config_def),
    ("User task manager", M.user_task_manager_config_def),
]


def render() -> str:
    out = [
        "# Configuration reference",
        "",
        "Generated from the typed config definitions "
        "(`cruise_control_tpu/config/main_config.py`) by "
        "`python -m cruise_control_tpu.config.docgen`; do not edit by "
        "hand.  Counterpart of the reference's "
        "docs/wiki/User Guide/Configurations.md, with the key groups of "
        "CC/config/constants/.",
        "",
        "Values in a `.properties` file may reference environment "
        "variables as `${env:NAME}` (secrets; reference "
        "EnvConfigProvider).",
    ]
    total = 0
    for title, builder in GROUPS:
        d = builder(ConfigDef())
        keys = d.keys()
        total += len(keys)
        out += ["", f"## {title} ({len(keys)} keys)", ""]
        out.append(d.document())
    out += ["", f"_{total} keys total._", ""]
    return "\n".join(out)


if __name__ == "__main__":
    print(render(), end="")
