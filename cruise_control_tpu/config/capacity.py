"""Broker capacity resolution.

Reference: CC/config/BrokerCapacityConfigResolver.java (SPI) and
BrokerCapacityConfigFileResolver.java:1-333 (default implementation reading
config/capacity.json with three flavors: flat capacities, JBOD per-logdir
DISK maps — config/capacityJBOD.json:1-30 — and per-broker core counts in
capacityCores.json).  Capacity units follow the reference: DISK in MiB,
NW_IN/NW_OUT in KiB/s, CPU in percent (cores × 100).
"""
from __future__ import annotations

import abc
import dataclasses
import json
from typing import Dict, Mapping, Optional, Tuple

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource

DEFAULT_CAPACITY_BROKER_ID = -1


@dataclasses.dataclass(frozen=True)
class BrokerCapacity:
    """Per-broker capacity info (reference BrokerCapacityInfo)."""

    capacity: Tuple[float, float, float, float]  # indexed by Resource
    disk_capacity_by_logdir: Optional[Mapping[str, float]] = None
    num_cpu_cores: float = 1.0
    is_estimated: bool = False
    estimation_info: str = ""

    def resource(self, r: Resource) -> float:
        return self.capacity[int(r)]


class BrokerCapacityConfigResolver(abc.ABC):
    """SPI: resolve broker capacities at model-build time
    (reference capacityForBroker(rack, host, id, timeout, allowEstimation))."""

    def configure(self, configs) -> None:  # pragma: no cover - plugin hook
        pass

    @abc.abstractmethod
    def capacity_for_broker(self, rack: Optional[str], host: str,
                            broker_id: int,
                            allow_estimation: bool = True) -> BrokerCapacity:
        ...

    def close(self) -> None:  # pragma: no cover
        pass


class StaticCapacityResolver(BrokerCapacityConfigResolver):
    """Uniform capacities for every broker (test/demo default)."""

    def __init__(self, cpu: float = 100.0, nw_in: float = 200_000.0,
                 nw_out: float = 200_000.0, disk: float = 1_000_000.0,
                 num_cpu_cores: float = 1.0):
        self._cap = BrokerCapacity((cpu, nw_in, nw_out, disk),
                                   num_cpu_cores=num_cpu_cores)

    def capacity_for_broker(self, rack, host, broker_id,
                            allow_estimation=True) -> BrokerCapacity:
        return self._cap


class BrokerCapacityConfigFileResolver(BrokerCapacityConfigResolver):
    """JSON capacity file resolver (reference
    BrokerCapacityConfigFileResolver.java:1-333).

    File format (same shape as the reference's config/capacity.json /
    capacityJBOD.json / capacityCores.json):

        {"brokerCapacities": [
           {"brokerId": "-1",
            "capacity": {"DISK": "1000000", "CPU": "100",
                         "NW_IN": "100000", "NW_OUT": "100000"}},
           {"brokerId": "0",
            "capacity": {"DISK": {"/data/d0": "500000",
                                  "/data/d1": "500000"},
                         "CPU": {"num.cores": "8"},
                         "NW_IN": "200000", "NW_OUT": "200000"}}]}

    brokerId -1 supplies the default for brokers not listed; using the
    default marks the capacity estimated.
    """

    def __init__(self, path: str):
        with open(path) as f:
            doc = json.load(f)
        self._by_id: Dict[int, BrokerCapacity] = {}
        for entry in doc.get("brokerCapacities", []):
            broker_id = int(entry["brokerId"])
            self._by_id[broker_id] = self._parse(entry, broker_id)
        if DEFAULT_CAPACITY_BROKER_ID not in self._by_id:
            raise ValueError(
                f"{path}: missing default capacity entry "
                f"(brokerId {DEFAULT_CAPACITY_BROKER_ID})")

    @staticmethod
    def _parse(entry: Mapping, broker_id: int) -> BrokerCapacity:
        cap_doc = entry["capacity"]
        # every resource must be present: a silent 0.0 capacity would make
        # capacity goals perpetually violated (the reference resolver
        # likewise rejects incomplete entries)
        missing = [k for k in ("DISK", "CPU", "NW_IN", "NW_OUT")
                   if k not in cap_doc]
        if missing:
            raise ValueError(
                f"capacity entry for broker {broker_id} is missing "
                f"resource(s) {missing}")
        caps = [0.0] * NUM_RESOURCES
        disk_by_logdir = None
        num_cores = 1.0

        disk = cap_doc["DISK"]
        if isinstance(disk, Mapping):  # JBOD per-logdir map
            disk_by_logdir = {str(k): float(v) for k, v in disk.items()}
            caps[Resource.DISK] = sum(disk_by_logdir.values())
        else:
            caps[Resource.DISK] = float(disk)

        cpu = cap_doc["CPU"]
        if isinstance(cpu, Mapping):  # capacityCores.json flavor
            num_cores = float(cpu.get("num.cores", 1))
            caps[Resource.CPU] = 100.0 * num_cores
        else:
            caps[Resource.CPU] = float(cpu)

        caps[Resource.NW_IN] = float(cap_doc["NW_IN"])
        caps[Resource.NW_OUT] = float(cap_doc["NW_OUT"])
        return BrokerCapacity(tuple(caps), disk_by_logdir, num_cores,
                              is_estimated=False)

    def capacity_for_broker(self, rack, host, broker_id,
                            allow_estimation=True) -> BrokerCapacity:
        cap = self._by_id.get(broker_id)
        if cap is not None:
            return cap
        if not allow_estimation:
            raise KeyError(
                f"no capacity configured for broker {broker_id} and "
                f"estimation not allowed")
        default = self._by_id[DEFAULT_CAPACITY_BROKER_ID]
        return dataclasses.replace(
            default, is_estimated=True,
            estimation_info=f"default capacity used for broker {broker_id}")
