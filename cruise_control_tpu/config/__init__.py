"""Configuration: typed config groups + pluggable resolvers.

Mirrors the reference's config package (CC/config/): the ~200-key
`KafkaCruiseControlConfig` equivalent lives in `main_config.py` built on the
core ConfigDef framework (cruise_control_tpu/common/config.py), capacity
resolution in `capacity.py`, topic-config provision in `topics.py`.
"""
from cruise_control_tpu.config.capacity import (BrokerCapacity,
                                                BrokerCapacityConfigResolver,
                                                BrokerCapacityConfigFileResolver,
                                                StaticCapacityResolver)
from cruise_control_tpu.config.main_config import CruiseControlConfig

__all__ = [
    "BrokerCapacity", "BrokerCapacityConfigResolver",
    "BrokerCapacityConfigFileResolver", "StaticCapacityResolver",
    "CruiseControlConfig",
]
