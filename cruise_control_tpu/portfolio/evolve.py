"""Optional G-generation refinement loop over the portfolio.

Truncation selection + tier-respecting goal-order crossover + mutation
(mutate.py) on top of the one-shot portfolio search: generation 0 is
exactly `make_portfolio(seed, width)`, every later generation keeps the
elite half and breeds the other half from parents chosen by fitness —
with the PER-GOAL entry/exit violated-broker counts (threaded through
ScenarioOutcome/OptimizerResult since PR 6) as the parent-selection
decomposition: among equal-fitness parents, the one whose own passes
REDUCED more per-goal violated-broker count ranks first, so crossover
prefers orders whose early goals actually retired violations rather
than orders that merely coasted to the same score.

Everything is a pure function of (base config, seed, width,
generations): candidate indices keep growing across generations
(generation g child j has index g*width + j), so `random.Random(
f"{seed}:{index}")` never reuses a stream and the whole evolution
replays bit-for-bit.
"""
from __future__ import annotations

import random
from typing import List, Optional, Sequence

from cruise_control_tpu.portfolio.engine import (CandidateOutcome,
                                                 PortfolioEngine,
                                                 PortfolioResult,
                                                 select_winner)
from cruise_control_tpu.portfolio.mutate import (SolverCandidate,
                                                 crossover_orders,
                                                 make_portfolio,
                                                 mutate_candidate)


def _violation_reduction(c: CandidateOutcome) -> int:
    """Sum over goals of (violated brokers at the goal's own entry −
    after its own pass): how much of the score each goal's own work
    earned.  0 when the serving rung carried no decomposition."""
    source = c.outcome if c.outcome is not None else c.result
    if source is None:
        return 0
    entry = getattr(source, "entry_broker_counts", None) or {}
    counts = getattr(source, "violated_broker_counts", None) or {}
    total = 0
    for goal, triple in counts.items():
        own = int(triple[1])
        total += max(0, int(entry.get(goal, triple[0])) - own)
    return total


def _parent_rank(c: CandidateOutcome):
    # fitness first; the per-goal violation-reduction decomposition
    # breaks fitness ties; candidate index last for determinism
    return (-c.fitness, -_violation_reduction(c), c.candidate.index)


def evolve(engine: PortfolioEngine, base_state, topology, base_order,
           seed: int, width: int, generations: int,
           max_programs: int = 4, options=None,
           include_proposals: bool = True,
           on_generation=None) -> PortfolioResult:
    """Run `generations` rounds of search-select-breed; returns the best
    PortfolioResult shape seen across ALL generations (winner = global
    best, candidates = final generation's scored population,
    generations = rounds actually completed).

    `on_generation(gen_index)` (when given) runs between generations —
    the background refinement job passes a staleness probe so a sweep
    whose model generation moved stops breeding dead candidates."""
    if generations < 1 or width < 1:
        return PortfolioResult(seed=seed, width=width, candidates=[])

    population: List[SolverCandidate] = make_portfolio(
        base_order, seed, width, max_programs=max_programs)
    best: Optional[CandidateOutcome] = None
    result: Optional[PortfolioResult] = None
    next_index = width
    duration = 0.0

    for gen in range(generations):
        result = engine.search(base_state, topology, population, seed,
                               options=options,
                               include_proposals=include_proposals)
        duration += result.duration_s
        result.generations = gen + 1
        gen_best = select_winner(result.candidates)
        if gen_best is not None and (best is None
                                     or gen_best.fitness > best.fitness):
            best = gen_best
        if gen + 1 >= generations:
            break
        if on_generation is not None and not on_generation(gen):
            break
        population, next_index = _breed(result.candidates, base_order,
                                        seed, width, next_index)

    assert result is not None
    result.winner = best
    result.duration_s = duration
    return result


def _breed(scored: Sequence[CandidateOutcome], base_order, seed: int,
           width: int, next_index: int):
    """Next generation: elite half survives unchanged, the rest are
    crossover+mutation children of rank-adjacent parents.  Indices keep
    growing so RNG streams never repeat."""
    ranked = sorted(scored, key=_parent_rank)
    feasible = [c for c in ranked if c.feasible] or list(ranked)
    elite_n = max(1, width // 2)
    elite = [c.candidate for c in feasible[:elite_n]]
    children: List[SolverCandidate] = []
    parent_i = 0
    while len(elite) + len(children) < width:
        a = elite[parent_i % len(elite)]
        b = elite[(parent_i + 1) % len(elite)]
        parent_i += 1
        rng = random.Random(f"{seed}:x:{next_index}")
        child_order = crossover_orders(a.goal_order, b.goal_order, rng)
        template = SolverCandidate(
            index=a.index, goal_order=child_order,
            fast_mode=a.fast_mode if rng.random() < 0.5 else b.fast_mode,
            threshold_scale=(a.threshold_scale + b.threshold_scale) / 2.0,
            move_seed=a.move_seed,
            description=f"x({a.index},{b.index})")
        children.append(mutate_candidate(template, seed, next_index,
                                         base_order=base_order))
        next_index += 1
    return elite + children, next_index
