"""Portfolio engine: K perturbed solver configs as ONE batched solve.

Candidates group by their TRACE key (goal order, fast_mode) — the only
knobs that change the compiled program — and each group rides the
scenario engine's caller-assembled batch path
(`ScenarioEngine.solve_compiled`): one vmapped dispatch per group,
lane-sharded across the mesh when the dispatch thread holds a
multi-chip token, OOM-halving and broker-table re-widening inherited
from the scenario engine, preemption checkpoints at every segment
boundary.  Lane-level perturbations (balance-threshold jitter via a
per-candidate jittered BalancingConstraint, move-seed load noise)
stack along the batch axis like any other scenario variant.

Fitness needs NO extra host round-trips: its inputs — the per-goal
violated masks behind the balancedness score and the movement counters
from the on-device `__moves__` epilogue — already ride the scenario
engine's single end-of-batch instrument fetch; combining them into one
scalar is host arithmetic on already-fetched values.

    fitness = balancedness
              − movement_cost_weight · (replica_moves + ½·leader_moves)
                                       / num_replicas
    fitness = −inf when any hard goal is still violated (the hard-goal
              feasibility mask: infeasible lanes can never win)

Failure policy: the portfolio owns its OWN degradation ladder,
separate from both the facade request ladder and the scenario engine's
(a failing portfolio sweep must not pin either).  FUSED = the batched
group solves; EAGER = a bounded per-candidate loop through
`GoalOptimizer.optimizations(eager_driver=True)`; below EAGER the
search returns no winner and the greedy result serves the request —
portfolio search degrades to "no improvement", never to an error.
Fault site: ``portfolio.search`` (armed before the first group
dispatch).
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time as _time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                 OptimizationOptions,
                                                 make_context,
                                                 partition_replica_index)
from cruise_control_tpu.analyzer.degradation import (CircuitBreaker,
                                                     DegradationLadder,
                                                     SolverRung,
                                                     classify_failure)
from cruise_control_tpu.model.state import ClusterState
from cruise_control_tpu.portfolio.mutate import MOVE_SEED_EPS, SolverCandidate
from cruise_control_tpu.scenario.compiler import CompiledBatch, materialize
from cruise_control_tpu.scenario.engine import ScenarioOutcome
from cruise_control_tpu.scenario.spec import ScenarioSpec
from cruise_control_tpu.sched.runtime import SolvePreempted
from cruise_control_tpu.utils import faults

LOG = logging.getLogger(__name__)


def portfolio_fitness(balancedness: float, replica_moves: int,
                      leader_moves: int, num_replicas: int,
                      movement_cost_weight: float) -> float:
    """The shared fitness formula — used for candidates AND for scoring
    the greedy baseline, so the strictly-better comparison is apples to
    apples."""
    cost = (replica_moves + 0.5 * leader_moves) / max(1, num_replicas)
    return balancedness - movement_cost_weight * cost


@dataclasses.dataclass
class CandidateOutcome:
    """One candidate's verdict: the declarative perturbation, its
    fitness, and whichever result form the serving rung produced
    (`outcome` from the fused batch, `result` from the eager loop)."""

    candidate: SolverCandidate
    fitness: float
    rung: str = "FUSED"
    outcome: Optional[ScenarioOutcome] = None
    result: Optional[object] = None          #: eager-rung OptimizerResult

    @property
    def feasible(self) -> bool:
        return self.fitness != float("-inf")

    def to_json(self) -> dict:
        return {
            "candidate": self.candidate.to_json(),
            "fitness": (round(self.fitness, 4) if self.feasible
                        else None),
            "feasible": self.feasible,
            "rung": self.rung,
        }


@dataclasses.dataclass
class PortfolioResult:
    """One portfolio search: every candidate scored, best first."""

    seed: int
    width: int
    candidates: List[CandidateOutcome]
    winner: Optional[CandidateOutcome] = None
    duration_s: float = 0.0
    rung: str = "FUSED"
    generations: int = 0

    def to_json(self) -> dict:
        return {
            "seed": self.seed,
            "width": self.width,
            "rung": self.rung,
            "generations": self.generations,
            "durationS": round(self.duration_s, 3),
            "winner": (self.winner.to_json() if self.winner is not None
                       else None),
            "candidates": [c.to_json() for c in self.candidates],
        }


def select_winner(candidates: Sequence[CandidateOutcome]
                  ) -> Optional[CandidateOutcome]:
    """Best fitness wins; ties break toward the LOWEST candidate index
    (closest to the identity), so same-fitness runs are deterministic
    and biased toward the least-perturbed config."""
    best: Optional[CandidateOutcome] = None
    for c in candidates:
        if not c.feasible:
            continue
        if (best is None or c.fitness > best.fitness
                or (c.fitness == best.fitness
                    and c.candidate.index < best.candidate.index)):
            best = c
    return best


class PortfolioEngine:
    """Population-of-solvers search over one base model.

    `scenario_engine` supplies the batched execution substrate
    (solve_compiled); `optimizer_factory(goal_names_or_None)` builds the
    goal stack for a candidate's order — the facade passes its own
    factory so portfolio programs share the process-wide trace cache
    with request solves."""

    def __init__(self, scenario_engine, optimizer_factory: Callable,
                 constraint: Optional[BalancingConstraint] = None,
                 movement_cost_weight: float = 4.0,
                 max_eager_candidates: int = 4,
                 breaker_failure_threshold: int = 3,
                 breaker_cooldown_s: float = 300.0,
                 metrics=None,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self._scenario_engine = scenario_engine
        self._optimizer_factory = optimizer_factory
        self._constraint = constraint or BalancingConstraint()
        self.movement_cost_weight = movement_cost_weight
        self.max_eager_candidates = max(1, max_eager_candidates)
        self._metrics = metrics
        self._time = time_fn or _time.time
        self.breaker = CircuitBreaker(
            failure_threshold=breaker_failure_threshold,
            cooldown_s=breaker_cooldown_s, time_fn=self._time)
        self.ladder = DegradationLadder(self.breaker)
        self._lock = threading.Lock()
        #: per-goal-order optimizer cache: repeated searches over the
        #: same pool reuse goal stacks (and through them the process-
        #: wide program caches) instead of re-instantiating per sweep
        self._optimizers: "OrderedDict[tuple, object]" = OrderedDict()
        self._max_optimizers = 8
        # telemetry (STATE PortfolioState + portfolio-* sensors)
        self.total_searches = 0
        self.total_candidates = 0
        self.total_descents = 0
        self.last_width = 0
        self.last_duration_s = 0.0
        self.last_best_fitness: Optional[float] = None

    # ------------------------------------------------------------------
    def attach_metrics(self, registry) -> None:
        self._metrics = registry

    def to_json(self) -> dict:
        with self._lock:
            return {
                "rung": self.ladder.rung.name,
                "breaker": self.breaker.to_json(),
                "totalSearches": self.total_searches,
                "totalCandidates": self.total_candidates,
                "totalDescents": self.total_descents,
                "lastWidth": self.last_width,
                "lastDurationS": round(self.last_duration_s, 3),
                "lastBestFitness": (
                    None if self.last_best_fitness is None
                    else round(self.last_best_fitness, 4)),
            }

    # ------------------------------------------------------------------
    def greedy_fitness(self, result, num_replicas: int) -> float:
        """Score an inline greedy OptimizerResult with the candidate
        formula (the strictly-better bar a winner must clear)."""
        return portfolio_fitness(
            result.balancedness_score(), result.num_replica_movements,
            result.num_leadership_movements, num_replicas,
            self.movement_cost_weight)

    # ------------------------------------------------------------------
    def search(self, base_state: ClusterState, topology,
               candidates: Sequence[SolverCandidate], seed: int,
               options: Optional[OptimizationOptions] = None,
               include_proposals: bool = True) -> PortfolioResult:
        """Solve every candidate, score, select.  Never raises for
        solver-side failures (the portfolio degrades to winner=None);
        SolvePreempted always propagates — the scheduler re-queues the
        sweep."""
        t0 = self._time()
        candidates = list(candidates)
        options = options or OptimizationOptions()
        result = PortfolioResult(seed=seed, width=len(candidates),
                                 candidates=[])
        if not candidates:
            return result

        rung = self.ladder.entry_rung()
        if rung <= SolverRung.FUSED:
            try:
                faults.inject("portfolio.search")
                result.candidates = self._search_fused(
                    base_state, topology, candidates, options,
                    include_proposals)
                self.ladder.on_success(SolverRung.FUSED)
                result.rung = "FUSED"
            except SolvePreempted:
                raise
            except Exception as exc:  # noqa: BLE001 - ladder classifies
                kind = classify_failure(exc)
                self.ladder.on_failure(SolverRung.FUSED)
                self._descend_metered()
                LOG.warning(
                    "batched portfolio search of %d candidates failed "
                    "(%s): %s; descending to bounded EAGER loop",
                    len(candidates), kind.value, exc)
                rung = SolverRung.EAGER
        if rung >= SolverRung.EAGER and not result.candidates:
            result.rung = rung.name
            result.candidates = self._search_eager(
                base_state, topology, candidates, options, rung)

        result.winner = select_winner(result.candidates)
        result.duration_s = self._time() - t0
        with self._lock:
            self.total_searches += 1
            self.total_candidates += len(candidates)
            self.last_width = len(candidates)
            self.last_duration_s = result.duration_s
            if result.winner is not None:
                self.last_best_fitness = result.winner.fitness
        if self._metrics is not None:
            self._metrics.update_timer("portfolio-search-timer",
                                       result.duration_s)
        return result

    # ------------------------------------------------------------------
    def optimizer_for(self, order):
        """The (LRU-cached) optimizer for a candidate goal order —
        public so winner-result conversion reuses the exact optimizer
        (and its hard-goal set) that solved the candidate."""
        return self._optimizer_for(tuple(order))

    def _optimizer_for(self, order: Tuple[str, ...]):
        with self._lock:
            opt = self._optimizers.get(order)
            if opt is not None:
                self._optimizers.move_to_end(order)
                return opt
        opt = self._optimizer_factory(list(order))
        with self._lock:
            self._optimizers[order] = opt
            while len(self._optimizers) > self._max_optimizers:
                self._optimizers.popitem(last=False)
        return opt

    def _search_fused(self, base_state, topology, candidates, options,
                      include_proposals) -> List[CandidateOutcome]:
        import jax

        groups: "OrderedDict[tuple, List[SolverCandidate]]" = OrderedDict()
        for cand in candidates:
            groups.setdefault(cand.trace_key(), []).append(cand)

        # each trace group compiles one program per goal segment (plus
        # prologue/epilogue); reserve room for the whole sweep so
        # repeated searches don't thrash the scenario engine's LRU
        self._scenario_engine.reserve_program_capacity(len(groups) * 16)

        # one no-op materialization serves every lane: portfolio
        # candidates never touch the cluster, only the solver config
        noop = ScenarioSpec(name="__portfolio_base__")
        rack_index = {r: i for i, r in enumerate(topology.rack_ids)}
        with jax.transfer_guard_device_to_host("allow"):
            # sanctioned pre-dispatch host region (variant assembly
            # reads the base model's device arrays)
            mat_state, mat_topo, _opts = materialize(
                base_state, topology, noop, base_state.num_brokers,
                rack_index, base_state.num_racks, base_state.num_hosts)

            out: Dict[int, CandidateOutcome] = {}
            num_replicas = int(np.asarray(mat_state.replica_valid).sum())
            for (order, fast), group in groups.items():
                optimizer = self._optimizer_for(order)
                batch = self._build_batch(mat_state, mat_topo, group,
                                          options, fast)
                telemetry = self._scenario_engine.solve_compiled(
                    optimizer, batch,
                    include_proposals=include_proposals)
                for cand, outcome in zip(group, telemetry.outcomes):
                    out[cand.index] = self._score(cand, outcome,
                                                  num_replicas)
        return [out[c.index] for c in candidates]

    def _build_batch(self, mat_state: ClusterState, mat_topo,
                     group: Sequence[SolverCandidate],
                     options: OptimizationOptions,
                     fast: bool) -> CompiledBatch:
        import jax.numpy as jnp

        lane_options = (options if options.fast_mode == fast
                        else dataclasses.replace(options, fast_mode=fast))
        specs, states, contexts, topologies = [], [], [], []
        for cand in group:
            state = mat_state
            if cand.move_seed:
                # ppm-scale load noise re-rolls every load-derived
                # tie-break salt (kernels.rotation_salt and the pairwise
                # jitters hash load columns) — the move-seed mutation
                noise = 1.0 + MOVE_SEED_EPS * np.random.RandomState(
                    cand.move_seed).uniform(
                        -1.0, 1.0,
                        size=np.asarray(mat_state.replica_base_load).shape)
                state = dataclasses.replace(
                    mat_state,
                    replica_base_load=jnp.asarray(
                        np.asarray(mat_state.replica_base_load)
                        * noise, dtype=jnp.float32))
            specs.append(ScenarioSpec(name=f"portfolio:{cand.index}",
                                      goals=cand.goal_order))
            states.append(state)
            contexts.append(make_context(
                state, cand.jittered_constraint(self._constraint),
                lane_options, mat_topo))
            topologies.append(mat_topo)
        slots = max(c.table_slots for c in contexts)
        contexts = [c if c.table_slots == slots
                    else dataclasses.replace(c, table_slots=slots)
                    for c in contexts]
        rows = partition_replica_index(states[0],
                                       rf_max=contexts[0].rf_max)
        # per-lane membership (fleet-fold mode) even though membership is
        # shared: it makes the engine retain each feasible lane's FINAL
        # placement, which the facade needs to rebuild the winner's
        # final state (warm-seed parity with inline solves)
        return CompiledBatch(
            specs=specs, states=states, contexts=contexts,
            topologies=topologies, num_brokers=mat_state.num_brokers,
            partition_rows=rows, shared_membership=False,
            partition_rows_per=[rows] * len(group))

    def _score(self, cand: SolverCandidate, outcome: ScenarioOutcome,
               num_replicas: int) -> CandidateOutcome:
        if not outcome.feasible:
            return CandidateOutcome(candidate=cand,
                                    fitness=float("-inf"),
                                    outcome=outcome)
        # count moves by the PROPOSAL definitions (replicas added;
        # leadership = leader-only proposals) whenever the lane carried
        # proposals — the device `__moves__` epilogue counts every
        # leader flip, including ones induced by replica relocation, so
        # scoring candidates by epilogue counts while greedy_fitness
        # scores the baseline by proposal counts would bias the
        # strictly-better bar against candidates.  Proposals are host
        # arithmetic on the already-fetched placement planes: no extra
        # device round-trip.
        if outcome.proposals:
            replica_moves = sum(len(p.replicas_to_add)
                                for p in outcome.proposals)
            leader_moves = sum(1 for p in outcome.proposals
                               if p.has_leader_action
                               and not p.has_replica_action)
        else:
            replica_moves = outcome.num_replica_moves
            leader_moves = outcome.num_leadership_moves
        fitness = portfolio_fitness(
            outcome.balancedness, replica_moves, leader_moves,
            num_replicas, self.movement_cost_weight)
        return CandidateOutcome(candidate=cand, fitness=fitness,
                                outcome=outcome)

    # ------------------------------------------------------------------
    def _search_eager(self, base_state, topology, candidates, options,
                      rung: SolverRung) -> List[CandidateOutcome]:
        """Bounded per-candidate fallback: the first
        `max_eager_candidates` candidates run through the eager driver;
        the rest are reported infeasible (never solved).  The EAGER rung
        realizes goal-order / fast-mode / move-seed perturbations only —
        the balance-threshold jitter lives in the batched context build
        and is dropped here (a degraded rung searches a narrower
        portfolio, it does not fail).  A total EAGER wash returns an
        empty feasible set — the greedy result serves."""
        import jax
        import jax.numpy as jnp

        out: List[CandidateOutcome] = []
        with jax.transfer_guard_device_to_host("allow"):
            num_replicas = int(np.asarray(base_state.replica_valid).sum())
            base_load = np.asarray(base_state.replica_base_load)
        budget = self.max_eager_candidates
        for cand in candidates:
            if budget <= 0:
                out.append(CandidateOutcome(candidate=cand,
                                            fitness=float("-inf"),
                                            rung=rung.name))
                continue
            budget -= 1
            try:
                optimizer = self._optimizer_for(cand.goal_order)
                lane_options = dataclasses.replace(
                    options, fast_mode=cand.fast_mode)
                lane_state = base_state
                if cand.move_seed:
                    noise = 1.0 + MOVE_SEED_EPS * np.random.RandomState(
                        cand.move_seed).uniform(-1.0, 1.0,
                                                size=base_load.shape)
                    lane_state = dataclasses.replace(
                        base_state, replica_base_load=jnp.asarray(
                            base_load * noise, dtype=jnp.float32))
                result = optimizer.optimizations(
                    lane_state, topology, lane_options,
                    check_sanity=False, eager_driver=True)
                fitness = portfolio_fitness(
                    result.balancedness_score(),
                    result.num_replica_movements,
                    result.num_leadership_movements, num_replicas,
                    self.movement_cost_weight)
                out.append(CandidateOutcome(
                    candidate=cand, fitness=fitness, rung=rung.name,
                    result=result))
                self.ladder.on_success(SolverRung.EAGER)
            except SolvePreempted:
                raise
            except Exception as exc:  # noqa: BLE001 - one lane fails
                LOG.warning("eager portfolio candidate %d failed: %s",
                            cand.index, exc)
                self.ladder.on_failure(SolverRung.EAGER)
                out.append(CandidateOutcome(candidate=cand,
                                            fitness=float("-inf"),
                                            rung=rung.name))
        return out

    def _descend_metered(self) -> None:
        with self._lock:
            self.total_descents += 1
        if self._metrics is not None:
            self._metrics.meter("portfolio-descents").mark()
