"""Seeded, declarative perturbation vocabulary over solver configs.

Every candidate is a PURE function of `(base config, portfolio seed,
candidate index)`: the RNG for candidate i is `random.Random(f"{seed}:{i}")`
(string seeding hashes through SHA-512, stable across platforms and
Python versions), so the same (seed, index) always yields the same
perturbation and a portfolio run is reproducible end to end.

The vocabulary (ISSUE 19):

* **goal-order shuffle** — hard goals shuffle among themselves, soft
  goals among themselves, and the hard tier always precedes the soft
  tier, so hard-goal precedence is preserved (the same constraint
  tests/test_random_goal_order.py pins for arbitrary orders);
* **balance-threshold jitter** — one scalar scale on every balance
  margin above 1.0 (resource / replica / leader / topic percentages),
  realized as a jittered BalancingConstraint so it lands in the
  context's batchable `balance_upper_pct`/`balance_lower_pct` array
  planes: candidates with different thresholds still share one program.
  The scale only ever TIGHTENS (`THRESHOLD_SCALE_RANGE` tops out at
  1.0) because each lane is scored against its own constraint — a
  tightened winner provably also satisfies the base margins, whereas a
  loosened one could beat greedy merely by grading itself on a curve;
* **rotation-salt / move-seed mutation** — the solver's tie-break salts
  are derived from load columns (kernels.rotation_salt is a state
  hash), so a ppm-scale multiplicative noise on `replica_base_load`
  re-rolls every rotation salt and pairwise jitter without materially
  changing the optimization problem.  `move_seed=0` applies no noise;
* **round-budget reallocation** — `fast_mode=True` quarters every soft
  goal's round budget (hard goals are unaffected), trading soft-goal
  polish on early goals for the chance that a different order converges
  better overall.

Goal order and fast_mode are TRACE-TIME properties (each distinct pair
compiles its own program), so `make_portfolio` draws them from a pool
capped at `max_programs` distinct (order, fast_mode) keys; width beyond
the pool varies only the lane-batchable knobs (threshold jitter, move
seed).  Candidate 0 is always the identity.
"""
from __future__ import annotations

import dataclasses
import random
from typing import List, Optional, Sequence, Tuple

from cruise_control_tpu.analyzer.context import BalancingConstraint
from cruise_control_tpu.analyzer.goals.registry import GOAL_CLASSES

#: bounds of the balance-threshold jitter: the margin above 1.0 scales
#: by a factor drawn from this range (identity = 1.0).  Tighten-only
#: (<= 1.0) on purpose: a candidate's verdicts and balancedness are
#: evaluated under its OWN jittered constraint, so a placement that
#: satisfies tighter margins also satisfies the operator's base margins
#: and its reported balancedness lower-bounds the base-margin value —
#: "winner never worse" stays sound.  A loosening scale (> 1.0) would
#: let a candidate "win" by relaxing the very thresholds it is scored
#: against.
THRESHOLD_SCALE_RANGE = (0.7, 1.0)

#: multiplicative amplitude of the move-seed load noise — ppm scale, far
#: below any capacity/balance decision threshold but enough to re-roll
#: every load-derived tie-break salt
MOVE_SEED_EPS = 1e-5


def _is_hard(name: str) -> bool:
    cls = GOAL_CLASSES.get(name)
    return bool(cls is not None and cls.is_hard)


def split_tiers(order: Sequence[str]) -> Tuple[List[str], List[str]]:
    """(hard tier, soft tier) of `order`, each in its original order."""
    hard = [g for g in order if _is_hard(g)]
    soft = [g for g in order if not _is_hard(g)]
    return hard, soft


@dataclasses.dataclass(frozen=True)
class SolverCandidate:
    """One perturbed solver configuration, fully declarative.

    `index` is the candidate's position in its portfolio; together with
    the portfolio seed it reproduces the perturbation exactly.
    `description` is the human-readable provenance string surfaced in
    responses (`solverProvenance.perturbation`)."""

    index: int
    goal_order: Tuple[str, ...]
    fast_mode: bool = False
    threshold_scale: float = 1.0
    move_seed: int = 0
    description: str = "identity"

    @property
    def is_identity(self) -> bool:
        return (self.fast_mode is False and self.threshold_scale == 1.0
                and self.move_seed == 0 and self.description == "identity")

    def trace_key(self) -> Tuple[Tuple[str, ...], bool]:
        """Candidates sharing a trace key share one compiled program:
        goal order and fast_mode are the only trace-time knobs."""
        return (self.goal_order, self.fast_mode)

    def jittered_constraint(self,
                            base: BalancingConstraint
                            ) -> BalancingConstraint:
        """`base` with every balance margin above 1.0 scaled by
        `threshold_scale` (identity returns `base` unchanged, so the
        K=1 path reuses the exact same constraint object)."""
        s = self.threshold_scale
        if s == 1.0:
            return base

        def _scale(pct: float) -> float:
            return 1.0 + (pct - 1.0) * s

        return dataclasses.replace(
            base,
            resource_balance_percentage=tuple(
                _scale(p) for p in base.resource_balance_percentage),
            replica_balance_percentage=_scale(
                base.replica_balance_percentage),
            leader_replica_balance_percentage=_scale(
                base.leader_replica_balance_percentage),
            topic_replica_balance_percentage=_scale(
                base.topic_replica_balance_percentage))

    def to_json(self) -> dict:
        return {
            "index": self.index,
            "goalOrder": list(self.goal_order),
            "fastMode": self.fast_mode,
            "thresholdScale": round(self.threshold_scale, 4),
            "moveSeed": self.move_seed,
            "description": self.description,
        }


def shuffled_order(order: Sequence[str], rng: random.Random
                   ) -> Tuple[str, ...]:
    """Shuffle hard and soft tiers independently; hard tier first.

    Hard-goal precedence is structural: a hard goal can never end up
    after a soft goal, whatever the draw."""
    hard, soft = split_tiers(order)
    rng.shuffle(hard)
    rng.shuffle(soft)
    return tuple(hard + soft)


def _candidate_rng(seed: int, index: int) -> random.Random:
    return random.Random(f"{seed}:{index}")


def _trace_pool(base_order: Sequence[str], seed: int, width: int,
                max_programs: int) -> List[Tuple[Tuple[str, ...], bool]]:
    """The capped pool of distinct (goal order, fast_mode) trace keys.

    Key 0 is always the base order without fast mode.  Additional keys
    alternate shuffled orders and fast-mode variants; the pool never
    exceeds `max_programs` so a K=32 portfolio does not compile 32
    programs — candidates past the pool recycle keys and differ only in
    lane-batchable knobs."""
    pool: List[Tuple[Tuple[str, ...], bool]] = [(tuple(base_order), False)]
    j = 1
    while len(pool) < min(width, max(1, max_programs)):
        rng = _candidate_rng(seed, -j)  # order pool draws: negative
        # indices so candidate RNG streams never collide with pool draws
        order = shuffled_order(base_order, rng)
        fast = bool(j % 3 == 0)  # every third pool entry reallocates
        # round budget (fast_mode) on top of its shuffle
        key = (order, fast)
        if key not in pool:
            pool.append(key)
        else:
            pool.append((shuffled_order(base_order, rng), fast))
        j += 1
    return pool


def make_portfolio(base_order: Sequence[str], seed: int, width: int,
                   max_programs: int = 4,
                   include_identity: bool = True) -> List[SolverCandidate]:
    """The width-K portfolio for (base config, seed): candidate 0 is the
    identity, candidates 1..K-1 are seeded perturbations.

    `include_identity=False` drops candidate 0 (the facade's sync path
    already holds the greedy result — re-solving the identity lane
    would waste a lane) while keeping indices 1..K-1 IDENTICAL to the
    included-identity portfolio, so provenance indices mean the same
    thing either way."""
    base_order = tuple(base_order)
    candidates: List[SolverCandidate] = []
    if include_identity:
        candidates.append(SolverCandidate(index=0, goal_order=base_order))
    pool = _trace_pool(base_order, seed, width, max_programs)
    for i in range(1, width):
        rng = _candidate_rng(seed, i)
        order, fast = pool[i % len(pool)]
        scale = round(rng.uniform(*THRESHOLD_SCALE_RANGE), 4)
        move_seed = rng.randrange(1, 2**31 - 1)
        parts = []
        if order != base_order:
            hard, _ = split_tiers(base_order)
            soft_part = [g for g in order if not _is_hard(g)]
            parts.append("order=" + ",".join(
                g.replace("Goal", "") for g in
                (list(order[:len(hard)]) + soft_part)[:3]) + "…")
        if fast:
            parts.append("fast-rounds")
        parts.append(f"thresh×{scale}")
        parts.append(f"salt:{move_seed % 10_000}")
        candidates.append(SolverCandidate(
            index=i, goal_order=order, fast_mode=fast,
            threshold_scale=scale, move_seed=move_seed,
            description=" ".join(parts)))
    return candidates


def mutate_candidate(parent: SolverCandidate, seed: int, index: int,
                     base_order: Optional[Sequence[str]] = None
                     ) -> SolverCandidate:
    """One mutation step for the evolve loop: re-jitter the threshold,
    re-roll the move seed, and with probability 1/3 swap two goals
    within one tier of the parent's order.  Pure in (parent, seed,
    index)."""
    rng = _candidate_rng(seed, index)
    order = list(parent.goal_order)
    mutated_order = False
    if rng.random() < (1.0 / 3.0):
        hard, soft = split_tiers(order)
        tier = soft if (len(soft) >= 2 and
                        (len(hard) < 2 or rng.random() < 0.7)) else hard
        if len(tier) >= 2:
            a, b = rng.sample(range(len(tier)), 2)
            tier[a], tier[b] = tier[b], tier[a]
            order = hard + soft
            mutated_order = True
    drift = rng.uniform(0.85, 1.15)
    lo, hi = THRESHOLD_SCALE_RANGE
    scale = round(min(hi, max(lo, parent.threshold_scale * drift)), 4)
    move_seed = rng.randrange(1, 2**31 - 1)
    desc = (f"mut({parent.index})"
            + (" swap" if mutated_order else "")
            + f" thresh×{scale} salt:{move_seed % 10_000}")
    return SolverCandidate(
        index=index, goal_order=tuple(order), fast_mode=parent.fast_mode,
        threshold_scale=scale, move_seed=move_seed, description=desc)


def crossover_orders(a: Sequence[str], b: Sequence[str],
                     rng: random.Random) -> Tuple[str, ...]:
    """Tier-respecting order crossover: per tier, keep a random prefix
    of parent A's tier order and fill the remainder in parent B's
    relative order (classic OX restricted within each tier, so the
    child still satisfies hard-goal precedence)."""
    def _cross(ta: List[str], tb: List[str]) -> List[str]:
        if len(ta) < 2:
            return list(ta)
        cut = rng.randrange(1, len(ta))
        head = ta[:cut]
        return head + [g for g in tb if g not in head]

    hard_a, soft_a = split_tiers(a)
    hard_b, soft_b = split_tiers(b)
    return tuple(_cross(hard_a, hard_b) + _cross(soft_a, soft_b))
