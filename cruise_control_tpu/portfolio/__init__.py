"""Device-parallel portfolio search: a population of perturbed solver
configurations solved as ONE batched program (ISSUE 19).

The greedy goal ladder — not raw speed — pinned balancedness at 85.1
for three bench rounds (BENCH_r03–r05).  This package points the
scenario engine's vmapped batch axis (PR 3) and the mesh lane-sharding
(PR 6) at hypothetical *solver configs* instead of hypothetical
*clusters*: K seeded perturbations of the solver configuration
(`mutate.py`) solve side by side in one dispatch (`engine.py`), an
on-device fitness epilogue scores them, and the best strictly-better
candidate replaces the greedy answer — optionally refined over G
generations (`evolve.py`).

Determinism contract: every candidate is a pure function of
`(base config, portfolio seed, candidate index)`; candidate 0 is the
identity perturbation, and a width-1 portfolio never runs at all, so
K=1 is byte-identical to today's greedy solve.
"""
from cruise_control_tpu.portfolio.engine import (CandidateOutcome,
                                                 PortfolioEngine,
                                                 PortfolioResult)
from cruise_control_tpu.portfolio.evolve import evolve
from cruise_control_tpu.portfolio.mutate import (SolverCandidate,
                                                 make_portfolio,
                                                 mutate_candidate)

__all__ = [
    "CandidateOutcome",
    "PortfolioEngine",
    "PortfolioResult",
    "SolverCandidate",
    "evolve",
    "make_portfolio",
    "mutate_candidate",
]
