"""Anomaly SPI and the percentile-based metric-anomaly finder.

Re-design of the reference's core anomaly layer (reference:
cruise-control-core/src/main/java/com/linkedin/cruisecontrol/detector/ —
Anomaly.java, AnomalyType.java, metricanomaly/MetricAnomaly.java,
metricanomaly/MetricAnomalyFinder.java, and
metricanomaly/PercentileMetricAnomalyFinder.java:1-191).
"""
from __future__ import annotations

import abc
import dataclasses
import enum
from typing import Hashable, List, Mapping, Optional, Sequence

import numpy as np

from cruise_control_tpu.core.aggregator import ValuesAndExtrapolations


class AnomalyType(enum.Enum):
    """Anomaly categories with self-healing priority: lower value = handled
    first (reference CC detector/AnomalyType ordering)."""

    BROKER_FAILURE = 0
    DISK_FAILURE = 1
    METRIC_ANOMALY = 2
    GOAL_VIOLATION = 3
    TOPIC_ANOMALY = 4
    #: the SOLVER degraded (rung descent / circuit-breaker trip in the
    #: degradation ladder, analyzer/degradation.py) — informational: the
    #: ladder already IS the fix, so notification-only, lowest priority
    SOLVER_DEGRADATION = 5
    #: the solve MESH degraded (watchdog fire / chip condemnation /
    #: span shrink in the mesh supervisor, parallel/health.py) —
    #: notification-only like SOLVER_DEGRADATION: the span ladder is
    #: the remediation, the anomaly routes the evidence (condemned
    #: chips, span, flight-recorder dump) through the notifier plane
    MESH_DEGRADATION = 6
    #: an interrupted execution was recovered at startup (crash
    #: reconcile-and-resume, executor/recovery.py) or the executor
    #: journal degraded to journal-less operation — notification-only:
    #: recovery already ran; the anomaly routes the evidence (resumed
    #: uuid, adopted/sealed task counts, cleared throttles,
    #: flight-recorder dump) through the notifier plane
    EXECUTION_RECOVERY = 7
    #: a per-class SLO error budget is burning faster than the alert
    #: threshold (obs/slo.py burn rate over the sched-* histograms) —
    #: notification-only: the remediation is operational (shed
    #: SCENARIO_SWEEP load, raise capacity, investigate the slow
    #: dimension), the anomaly routes the evidence (class, queue-wait
    #: vs device-time burn, objective) through the notifier plane
    SLO_BURN = 8


class Anomaly(abc.ABC):
    """reference CORE/detector/Anomaly.java — something that can be fixed."""

    @property
    @abc.abstractmethod
    def anomaly_type(self) -> AnomalyType: ...

    @property
    @abc.abstractmethod
    def anomaly_id(self) -> str: ...

    @abc.abstractmethod
    def fix(self) -> bool:
        """Attempt the fix; True if a fix was started."""

    def reason_supported(self) -> bool:
        return False


@dataclasses.dataclass
class MetricAnomaly(Anomaly):
    """A metric out of its historical normal range
    (reference CORE/detector/metricanomaly/MetricAnomaly.java)."""

    entity: Hashable
    metric_id: int
    windows: List[int]
    description: str
    _id: str = dataclasses.field(default="")

    def __post_init__(self):
        if not self._id:
            self._id = f"metric-anomaly-{self.entity}-{self.metric_id}"

    @property
    def anomaly_type(self) -> AnomalyType:
        return AnomalyType.METRIC_ANOMALY

    @property
    def anomaly_id(self) -> str:
        return self._id

    def fix(self) -> bool:
        return False  # metric anomalies have no direct fix in the reference


class MetricAnomalyFinder(abc.ABC):
    """Plugin interface (reference
    CORE/detector/metricanomaly/MetricAnomalyFinder.java)."""

    @abc.abstractmethod
    def metric_anomalies(
            self,
            metrics_history_by_entity: Mapping[Hashable, ValuesAndExtrapolations],
            current_metrics_by_entity: Mapping[Hashable, ValuesAndExtrapolations],
    ) -> List[MetricAnomaly]: ...


#: Values whose upper percentile is below this are noise, never anomalous
#: (reference PercentileMetricAnomalyFinder.SIGNIFICANT_METRIC_VALUE_THRESHOLD)
SIGNIFICANT_METRIC_VALUE_THRESHOLD = 1.0


class PercentileMetricAnomalyFinder(MetricAnomalyFinder):
    """Current value vs historical percentile band
    (reference CORE/detector/metricanomaly/PercentileMetricAnomalyFinder.java:
    40-140): anomalous when current > P_hi * (1 + upper_margin) or
    current < P_lo * lower_margin, with an insignificance floor on P_hi.
    """

    def __init__(self, upper_percentile: float = 95.0,
                 lower_percentile: float = 2.0,
                 upper_margin: float = 0.5,
                 lower_margin: float = 0.2,
                 interested_metrics: Optional[Sequence[int]] = None,
                 metric_name_fn=None) -> None:
        self.upper_percentile = upper_percentile
        self.lower_percentile = lower_percentile
        self.upper_margin = upper_margin
        self.lower_margin = lower_margin
        self.interested_metrics = (None if interested_metrics is None
                                   else set(interested_metrics))
        self._metric_name_fn = metric_name_fn or str

    def _anomaly_for_metric(self, entity, metric_id: int,
                            history: ValuesAndExtrapolations,
                            current: ValuesAndExtrapolations
                            ) -> Optional[MetricAnomaly]:
        hist = np.asarray(history.metric_values(metric_id), dtype=np.float64)
        if hist.size == 0:
            return None
        upper_pct = float(np.percentile(hist, self.upper_percentile))
        if upper_pct <= SIGNIFICANT_METRIC_VALUE_THRESHOLD:
            return None
        upper = upper_pct * (1.0 + self.upper_margin)
        lower = float(np.percentile(hist, self.lower_percentile)) \
            * self.lower_margin
        cur = float(current.metric_values(metric_id)[-1])
        if cur > upper or cur < lower:
            name = self._metric_name_fn(metric_id)
            description = (
                f"Metric value {cur:.3f} of {name} for {entity} in window "
                f"{current.window_times_ms[0] if current.window_times_ms else '?'}"
                f" is out of [{lower:.3f}, {upper:.3f}] over "
                f"{hist.size} history windows.")
            return MetricAnomaly(entity=entity, metric_id=metric_id,
                                 windows=list(current.window_times_ms),
                                 description=description)
        return None

    def metric_anomalies(self, metrics_history_by_entity,
                         current_metrics_by_entity) -> List[MetricAnomaly]:
        if metrics_history_by_entity is None or current_metrics_by_entity is None:
            raise ValueError("metrics history/current cannot be None")
        anomalies: List[MetricAnomaly] = []
        for entity, current in current_metrics_by_entity.items():
            history = metrics_history_by_entity.get(entity)
            if history is None:
                continue
            num_metrics = current.values.shape[1]
            metric_ids = (range(num_metrics) if self.interested_metrics is None
                          else [m for m in self.interested_metrics
                                if m < num_metrics])
            for metric_id in metric_ids:
                anomaly = self._anomaly_for_metric(entity, metric_id,
                                                   history, current)
                if anomaly is not None:
                    anomalies.append(anomaly)
        return anomalies
